"""Deterministic schedule exploration of the orchestrator (RACE dynamic tier).

The static race lint (:mod:`.race_lint`) sees torn windows; this module
*drives* them: every scenario below builds a real orchestration inside
the controlled loop of :mod:`blance_tpu.testing.sched` and is run under
many interleavings — bounded-exhaustive enumeration for the small
scenarios, pinned-seed random walks for the chaos ones — while checking
the control plane's declared dynamic invariants:

- progress counters are monotonic, pause/resume stay balanced, and the
  stream closes exactly once;
- ``progress.errors`` is append-only (every earlier snapshot a prefix of
  every later one) and, under fault-tolerant options, holds only
  structured ``MoveFailure``s;
- per-partition move cursors never reverse, and ``failed_at`` is
  write-once;
- ``achieved_map()`` equals ``beg_map`` with exactly the successfully
  executed callback batches applied (recomputed independently from the
  assign log);
- no schedule deadlocks, and a completed run reaches ``end_map``.

A violating schedule is emitted as a JSON trace (``testing.sched.Trace``)
that replays the exact interleaving — the race becomes a deterministic
regression test (see ``tests/test_race_regressions.py`` for the
committed pause-guard trace that fails on the pre-fix supplier).

CLI (the CI ``race-smoke`` step)::

    python -m blance_tpu.analysis.schedule --ci [--trace-dir DIR]
    python -m blance_tpu.analysis.schedule --scenario NAME --budget 2
    python -m blance_tpu.analysis.schedule --scenario NAME --seeds 1,2,3

``--ci`` runs the bounded-exhaustive pass over the small scenarios plus
the pinned-seed walk batch over the chaos scenarios, writes any
violating schedule into ``--trace-dir``, and exits nonzero.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, Coroutine, Optional

from ..core.types import Partition, PartitionMap, PartitionModelState
from ..orchestrate.faults import FaultPlan, NodeFaults
from ..orchestrate.health import HALF_OPEN, HealthTracker
from ..orchestrate.orchestrator import (
    MoveFailure,
    Orchestrator,
    OrchestratorOptions,
    OrchestratorProgress,
    orchestrate_moves,
)
from ..testing.sched import (
    ExploreReport,
    InvariantViolation,
    RandomWalkPolicy,
    ScheduleOutcome,
    Trace,
    explore,
    run_controlled,
    save_trace,
)

__all__ = [
    "SCENARIOS",
    "Scenario",
    "ProgressInvariants",
    "run_scenario_walks",
    "run_scenario_exhaustive",
    "main",
]

# Pinned walk seeds for the CI chaos batch: three fixed, documented
# seeds — reproducible forever, diverse enough to hit distinct
# interleaving families (each seed drives a full random walk).
CI_WALK_SEEDS = (11, 23, 37)

_MODEL = {"primary": PartitionModelState(priority=0, constraints=0)}


def _pm(d: dict[str, dict[str, list[str]]]) -> PartitionMap:
    return {name: Partition(name, {s: list(ns) for s, ns in nbs.items()})
            for name, nbs in d.items()}


# -- invariants --------------------------------------------------------------


class ProgressInvariants:
    """Fold progress snapshots, raising InvariantViolation on any break.

    Checks the invariants that must hold under EVERY schedule: counter
    monotonicity, append-only errors, cursor monotonicity (sampled per
    snapshot via ``visit_next_moves``), failed_at write-once, and — at
    ``finish()`` — close-once plus achieved-map consistency against the
    independently recorded assign log.
    """

    def __init__(self, o: Orchestrator,
                 ft_errors_structured: bool = False) -> None:
        self._o = o
        self._ft = ft_errors_structured
        self._last: Optional[OrchestratorProgress] = None
        self._monotone = [
            name for name in OrchestratorProgress().__dict__
            if name != "errors"]
        self._cursors: dict[str, int] = {}
        self._failed_at: dict[str, Optional[int]] = {}
        self.snapshots = 0

    def observe(self, progress: OrchestratorProgress) -> None:
        self.snapshots += 1
        last = self._last
        if last is not None:
            for name in self._monotone:
                cur, prev = getattr(progress, name), getattr(last, name)
                if cur < prev:
                    raise InvariantViolation(
                        f"counter {name} regressed: {prev} -> {cur}")
            if progress.errors[:len(last.errors)] != last.errors:
                raise InvariantViolation(
                    "progress.errors is not append-only: "
                    f"{last.errors!r} is not a prefix of "
                    f"{progress.errors!r}")
        if progress.tot_pause_new_assignments < \
                progress.tot_resume_new_assignments:
            raise InvariantViolation(
                f"resume counter overtook pause: "
                f"{progress.tot_pause_new_assignments} < "
                f"{progress.tot_resume_new_assignments}")
        if self._ft:
            for e in progress.errors:
                if not isinstance(e, MoveFailure):
                    raise InvariantViolation(
                        f"unstructured error under fault-tolerant "
                        f"options: {type(e).__name__}: {e}")
        self._last = progress
        self._check_cursors()

    def _check_cursors(self) -> None:
        def check(m: dict[str, Any]) -> None:
            for name, nm in m.items():
                prev = self._cursors.get(name, 0)
                if nm.next < prev:
                    raise InvariantViolation(
                        f"cursor reversed for partition {name}: "
                        f"{prev} -> {nm.next}")
                self._cursors[name] = nm.next
                prev_failed = self._failed_at.get(name)
                if prev_failed is not None and \
                        nm.failed_at != prev_failed:
                    raise InvariantViolation(
                        f"failed_at rewritten for partition {name}: "
                        f"{prev_failed} -> {nm.failed_at}")
                self._failed_at[name] = nm.failed_at

        self._o.visit_next_moves(check)

    def finish(
        self,
        executed: Optional[list[tuple[str, tuple[str, ...],
                                      tuple[str, ...],
                                      tuple[str, ...]]]] = None,
        expect_complete: bool = False,
    ) -> None:
        last = self._last
        if last is None:
            raise InvariantViolation("progress stream closed with no "
                                     "snapshots")
        if last.tot_progress_close != 1:
            raise InvariantViolation(
                f"tot_progress_close == {last.tot_progress_close} "
                f"after stream close (must be exactly 1)")
        if executed is not None:
            self._check_achieved(executed)
        if expect_complete:
            achieved = self._o.achieved_map()
            if achieved != self._o.end_map:
                raise InvariantViolation(
                    "clean run did not reach end_map: "
                    f"achieved={achieved!r}")
            if last.errors:
                raise InvariantViolation(
                    f"clean run recorded errors: {last.errors!r}")

    def _check_achieved(
        self,
        executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                             tuple[str, ...]]],
    ) -> None:
        """achieved_map() must equal beg_map + successfully executed
        moves, recomputed here from the assign log alone."""
        expect: dict[str, dict[str, list[str]]] = {
            name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
            for name, p in self._o.beg_map.items()}
        for node, partitions, states, ops in executed:
            for pname, state in zip(partitions, states):
                nbs = expect[pname]
                for ns in nbs.values():
                    if node in ns:
                        ns.remove(node)
                if state:
                    nbs.setdefault(state, []).append(node)
        achieved = self._o.achieved_map()
        got = {name: {s: list(ns) for s, ns in p.nodes_by_state.items()}
               for name, p in achieved.items()}
        # Normalize empty state lists both ways (a state emptied by a
        # removal vs never present).
        def norm(m: dict[str, dict[str, list[str]]]) \
                -> dict[str, dict[str, list[str]]]:
            return {name: {s: sorted(ns) for s, ns in nbs.items() if ns}
                    for name, nbs in m.items()}
        if norm(got) != norm(expect):
            raise InvariantViolation(
                f"achieved_map inconsistent with executed moves:\n"
                f"  achieved: {norm(got)!r}\n"
                f"  from log: {norm(expect)!r}")


def _logging_assign(
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]],
) -> Callable[..., Coroutine[Any, Any, None]]:
    """An async assign callback that records each SUCCESSFUL batch
    (append happens after the yield, so a cancelled/timed-out callback
    never logs — matching the orchestrator's not-applied assumption)."""

    async def assign(stop_ch: Any, node: str, partitions: list[str],
                     states: list[str], ops: list[str]) -> None:
        await asyncio.sleep(0)
        executed.append((node, tuple(partitions), tuple(states),
                         tuple(ops)))

    return assign


# -- scenarios ---------------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """One explorable orchestration scenario.

    ``factory()`` returns a FRESH coroutine: the whole orchestration is
    built inside it, and it raises InvariantViolation (or deadlocks)
    when a schedule breaks an invariant.  ``exhaustive`` scenarios are
    small enough for the bounded-exhaustive CI pass with the given
    ``branch_budget``; every scenario also supports seeded walks.
    """

    name: str
    doc: str
    factory: Callable[[], Coroutine[Any, Any, Any]]
    exhaustive: bool = False
    branch_budget: Optional[int] = 2
    max_schedules: int = 4000


async def _two_movers_three_partitions() -> dict[str, int]:
    """The acceptance scenario: 2 movers, 3 partitions, 6 moves, legacy
    options — every interleaving must preserve every invariant and end
    at end_map with the exact per-partition op sequences."""
    beg = _pm({"p0": {"primary": ["n1"]},
               "p1": {"primary": ["n2"]},
               "p2": {"primary": ["n1"]}})
    end = _pm({"p0": {"primary": ["n2"]},
               "p1": {"primary": ["n1"]},
               "p2": {"primary": ["n2"]}})
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]] = []
    o = orchestrate_moves(_MODEL, OrchestratorOptions(), ["n1", "n2"],
                          beg, end, _logging_assign(executed))
    inv = ProgressInvariants(o)
    plans: dict[str, list[tuple[str, str, str]]] = {}
    o.visit_next_moves(lambda m: plans.update(
        {k: [(mv.node, mv.state, mv.op) for mv in v.moves]
         for k, v in m.items()}))
    async for progress in o.progress_ch():
        inv.observe(progress)
    o.stop()
    inv.finish(executed=executed, expect_complete=True)
    # Exact per-partition execution order == the up-front move plans.
    seen: dict[str, list[tuple[str, str, str]]] = {}
    for node, partitions, states, ops in executed:
        for p, s, op in zip(partitions, states, ops):
            seen.setdefault(p, []).append((node, s, op))
    if seen != plans:
        raise InvariantViolation(
            f"executed ops diverge from move plans:\n  plans: "
            f"{plans!r}\n  seen: {seen!r}")
    return {"snapshots": inv.snapshots, "batches": len(executed)}


async def _pause_cycle_guard() -> dict[str, int]:
    """The pause-guard regression: a pause→resume→pause cycle landing
    inside the supplier's pause-counter put must NOT let a new round
    feed while paused.  The assign callback asserts the invariant
    directly; the scenario scripts the racy cycle and then resumes via
    an out-of-band timer so the fixed supplier (which correctly honors
    the second pause) completes."""
    beg = _pm({"p0": {"primary": []}, "p1": {"primary": []}})
    end = _pm({"p0": {"primary": ["n1"]}, "p1": {"primary": ["n1"]}})

    o: Optional[Orchestrator] = None

    async def assign(stop_ch: Any, node: str, partitions: list[str],
                     states: list[str], ops: list[str]) -> None:
        assert o is not None
        if o._pause_ch is not None:
            raise InvariantViolation(
                f"assign started for {partitions!r} on {node!r} while "
                f"new assignments are paused (torn pause guard)")
        await asyncio.sleep(0)

    o = orchestrate_moves(_MODEL, OrchestratorOptions(), ["n1"],
                          beg, end, assign)
    inv = ProgressInvariants(o)
    # Pause before the supplier's first round can feed anything.
    o.pause_new_assignments()
    cycled = False

    async def resume_later() -> None:
        await asyncio.sleep(0.001)  # virtual time: fires when loop idles
        o.resume_new_assignments()

    resumer: Optional[asyncio.Task[None]] = None
    async for progress in o.progress_ch():
        inv.observe(progress)
        for e in progress.errors:
            # The torn-guard assign assertion is caught by the
            # orchestrator as an app error; surface it as the scenario
            # failure it is.
            if isinstance(e, InvariantViolation):
                raise e
        if not cycled and progress.tot_run_supply_moves_pause >= 1:
            # The supplier is inside its pause window (the bump put just
            # rendezvoused with us): cycle resume->pause to strand it on
            # a stale channel if the guard is torn.
            cycled = True
            o.resume_new_assignments()
            o.pause_new_assignments()
            resumer = asyncio.ensure_future(resume_later())
    o.stop()
    if resumer is not None:
        await resumer
    if not cycled:
        raise InvariantViolation("scenario never cycled pause/resume — "
                                 "driver drifted from the code under test")
    inv.finish(expect_complete=True)
    return {"snapshots": inv.snapshots}


async def _pause_resume_during_retry_backoff() -> dict[str, int]:
    """Pause/resume while a mover sits in a retry backoff: the backoff
    finishes, the retried move lands after the heal, and every
    counter/error invariant holds along the way."""
    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(3)})
    end = _pm({f"p{i}": {"primary": ["b"]} for i in range(3)})
    plan = FaultPlan(seed=1, nodes={"b": NodeFaults(dead=True,
                                                    heal_after=2)})
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]] = []
    o = orchestrate_moves(
        _MODEL,
        OrchestratorOptions(move_timeout_s=0.25, max_retries=4,
                            backoff_base_s=0.002, backoff_jitter=0.25),
        ["a", "b"], beg, end, plan.wrap(_logging_assign(executed)))
    inv = ProgressInvariants(o, ft_errors_structured=True)
    paused = False

    async def resume_later() -> None:
        await asyncio.sleep(0.001)
        o.resume_new_assignments()

    resumer: Optional[asyncio.Task[None]] = None
    async for progress in o.progress_ch():
        inv.observe(progress)
        if not paused and progress.tot_mover_assign_partition_retry >= 1:
            paused = True
            o.pause_new_assignments()
            resumer = asyncio.ensure_future(resume_later())
    o.stop()
    if resumer is not None:
        await resumer
    if not paused:
        raise InvariantViolation("no retry observed — the fault plan "
                                 "no longer forces retries")
    inv.finish(executed=executed, expect_complete=True)
    return {"snapshots": inv.snapshots,
            "retries": o._progress.tot_mover_assign_partition_retry}


async def _stop_during_quarantine_probe() -> dict[str, int]:
    """stop() landing in the breaker's half-open probe window: the
    wind-down must complete under every interleaving, with counters and
    the error stream intact.

    Probe admission is structural, not lucky: partition ``p0`` trips
    ``dead``'s breaker at virtual time 0 (every schedule must drain the
    runnable frontier before the loop can idle, so the trip always
    precedes the first timer).  Partitions ``q*`` sequence a ``slow``
    primary move BEFORE their dead-targeted replica move; ``slow``'s
    0.005 s of virtual work advances the clock past the 0.001 s probe
    dwell, so when the replica move reaches the dead mover the breaker
    is ripe for a half-open probe — which ``heal_after=2`` lets
    succeed.  The consumer stops the instant it observes the half-open
    state, so the wind-down races the in-flight probe."""
    loop = asyncio.get_running_loop()
    model = {"primary": PartitionModelState(priority=0, constraints=0),
             "replica": PartitionModelState(priority=1, constraints=1)}
    beg = _pm({"p0": {"primary": ["dead"], "replica": []},
               "q0": {"primary": ["a"], "replica": []},
               "q1": {"primary": ["a"], "replica": []}})
    end = _pm({"p0": {"primary": ["a"], "replica": []},
               "q0": {"primary": ["slow"], "replica": ["dead"]},
               "q1": {"primary": ["slow"], "replica": ["dead"]}})
    plan = FaultPlan(seed=4, nodes={"dead": NodeFaults(dead=True,
                                                       heal_after=2)})
    health = HealthTracker(threshold=1, probe_after_s=0.001,
                           clock=loop.time)

    async def assign(stop_ch: Any, node: str, partitions: list[str],
                     states: list[str], ops: list[str]) -> None:
        # Virtual-time work on the slow node idles the loop, advancing
        # the clock past the breaker's probe dwell.
        await asyncio.sleep(0.005 if node == "slow" else 0.0)

    o = orchestrate_moves(
        model,
        OrchestratorOptions(move_timeout_s=0.25, max_retries=0,
                            health=health),
        ["a", "dead", "slow"], beg, end, plan.wrap(assign))
    inv = ProgressInvariants(o, ft_errors_structured=True)
    stopped = False
    async for progress in o.progress_ch():
        inv.observe(progress)
        if not stopped and health.state("dead") == HALF_OPEN:
            stopped = True
            o.stop()
    if not stopped:
        o.stop()
    inv.finish()
    if o._progress.tot_quarantine_trips < 1:
        raise InvariantViolation("breaker never tripped — scenario "
                                 "drifted from the code under test")
    return {"snapshots": inv.snapshots,
            "stopped_during_probe": int(stopped),
            "trips": o._progress.tot_quarantine_trips}


async def _movers_race_breaker_trip() -> dict[str, int]:
    """Two movers pounding two failing nodes race their breaker trips
    and quarantine releases against the supplier's rounds; the failure
    bookkeeping must stay exact under every interleaving."""
    beg = _pm({f"p{i}": {"primary": ["ok"]} for i in range(4)})
    end = _pm({f"p{i}": {"primary": ["bad1" if i % 2 else "bad2"]}
               for i in range(4)})
    plan = FaultPlan(seed=9, nodes={"bad1": NodeFaults(dead=True),
                                    "bad2": NodeFaults(dead=True)})
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]] = []
    o = orchestrate_moves(
        _MODEL,
        OrchestratorOptions(move_timeout_s=0.25, max_retries=1,
                            backoff_base_s=0.002, quarantine_after=1,
                            probe_after_s=60.0),
        ["ok", "bad1", "bad2"], beg, end,
        plan.wrap(_logging_assign(executed)))
    inv = ProgressInvariants(o, ft_errors_structured=True)
    async for progress in o.progress_ch():
        inv.observe(progress)
    o.stop()
    inv.finish(executed=executed)
    last = o._progress
    if last.tot_move_failures != len(o.move_failures()):
        raise InvariantViolation(
            f"failure counter ({last.tot_move_failures}) diverges from "
            f"move_failures() ({len(o.move_failures())})")
    if len(last.errors) != last.tot_move_failures:
        raise InvariantViolation(
            f"errors stream ({len(last.errors)}) diverges from the "
            f"failure counter ({last.tot_move_failures})")
    if last.tot_quarantine_trips < 2:
        raise InvariantViolation(
            f"expected both breakers to trip, got "
            f"{last.tot_quarantine_trips} trips")
    return {"snapshots": inv.snapshots,
            "failures": last.tot_move_failures,
            "trips": last.tot_quarantine_trips}


async def _slo_gauges_under_chaos() -> dict[str, int]:
    """The live-telemetry plane under chaos: every interleaving must
    keep the SLO gauges well-formed — availability within [0, 1] at
    every progress snapshot, the executed-move count monotone,
    convergence lag non-negative — and at the end the tracker's
    incrementally maintained view must agree EXACTLY with both the
    independently logged assign batches and a from-scratch availability
    recompute off ``achieved_map()``.  The whole run (orchestrator
    clocks included) rides a virtual-time Recorder, so gauge values are
    pure functions of the schedule."""
    from ..obs import Recorder, use_recorder
    from ..obs.slo import SloTracker

    loop = asyncio.get_running_loop()
    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    end = _pm({"p0": {"primary": ["b"]}, "p1": {"primary": ["b"]},
               "p2": {"primary": ["bad"]}, "p3": {"primary": ["flaky"]}})
    plan = FaultPlan(seed=13, nodes={
        "bad": NodeFaults(dead=True),
        "flaky": NodeFaults(fail_rate=0.5),
    })
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]] = []
    with use_recorder(Recorder(clock=loop.time)) as rec:
        slo = SloTracker(beg, primary_states=("primary",),
                         clock=loop.time, recorder=rec)
        o = orchestrate_moves(
            _MODEL,
            OrchestratorOptions(move_timeout_s=0.25, max_retries=2,
                                backoff_base_s=0.002, quarantine_after=2,
                                probe_after_s=60.0),
            ["a", "b", "bad", "flaky"], beg, end,
            plan.wrap(_logging_assign(executed)), move_observers=(slo,))
        o.visit_next_moves(lambda m: slo.set_min_moves(
            sum(len(nm.moves) for nm in m.values())))
        slo.attach_health(o.health)
        inv = ProgressInvariants(o, ft_errors_structured=True)
        prev_executed = 0
        async for progress in o.progress_ch():
            inv.observe(progress)
            a = slo.availability()
            if not 0.0 <= a <= 1.0:
                raise InvariantViolation(f"availability out of [0,1]: {a}")
            if slo.moves_executed < prev_executed:
                raise InvariantViolation(
                    f"executed-move count regressed: {prev_executed} -> "
                    f"{slo.moves_executed}")
            prev_executed = slo.moves_executed
            if slo.convergence_lag_s() < 0.0:
                raise InvariantViolation("negative convergence lag")
            if slo.churn_ratio() < 0.0:
                raise InvariantViolation("negative churn")
        o.stop()
        inv.finish(executed=executed)
        logged = sum(len(parts) for _node, parts, _s, _o in executed)
        if slo.moves_executed != logged:
            raise InvariantViolation(
                f"tracker executed {slo.moves_executed} != {logged} "
                f"batches logged by the assign callback")
        achieved = o.achieved_map()
        recomputed = sum(
            1 for p in achieved.values()
            if p.nodes_by_state.get("primary")) / len(achieved)
        if abs(recomputed - slo.availability()) > 1e-12:
            raise InvariantViolation(
                f"incremental availability {slo.availability()} diverges "
                f"from achieved-map recompute {recomputed}")
    return {"snapshots": inv.snapshots, "executed": logged,
            "failed": slo.moves_failed}


async def _reschedule_on_quarantine() -> dict[str, int]:
    """The critical-path scheduler's online-reschedule path (ISSUE 12):
    a breaker trip mid-schedule must rebuild the plan in one atomic
    window — under EVERY interleaving the rebuilt (plan, remaining)
    snapshot pair stays consistent:

    - every unfinished move reappears in the rebuilt schedule exactly
      once (scheduled on a lane XOR stalled, never both, none lost);
    - no orphan lanes: nothing is scheduled onto a quarantined node,
      and every lane index is within the machine's capacity;
    - cursors never reverse and failed_at is write-once (the standard
      ProgressInvariants), with achieved_map consistent against the
      independently logged assign batches."""
    from ..obs import Recorder, use_recorder
    from ..orchestrate.sched import CriticalPathScheduler
    from ..orchestrate.sched.policy import _CriticalPathBound

    loop = asyncio.get_running_loop()
    beg = _pm({f"p{i}": {"primary": ["a"]} for i in range(4)})
    end = _pm({"p0": {"primary": ["dead"]}, "p1": {"primary": ["dead"]},
               "p2": {"primary": ["b"]}, "p3": {"primary": ["b"]}})
    plan = FaultPlan(seed=17, nodes={"dead": NodeFaults(dead=True)})
    executed: list[tuple[str, tuple[str, ...], tuple[str, ...],
                         tuple[str, ...]]] = []
    max_lanes = 2
    with use_recorder(Recorder(clock=loop.time)):
        o = orchestrate_moves(
            _MODEL,
            OrchestratorOptions(
                move_timeout_s=0.25, max_retries=0, quarantine_after=1,
                probe_after_s=60.0,
                max_concurrent_partition_moves_per_node=max_lanes,
                scheduler=CriticalPathScheduler()),
            ["a", "b", "dead"], beg, end,
            plan.wrap(_logging_assign(executed)))
        bound = o.sched
        assert isinstance(bound, _CriticalPathBound)
        inv = ProgressInvariants(o, ft_errors_structured=True)
        async for progress in o.progress_ch():
            inv.observe(progress)
            # The (plan, last_remaining) pair must be consistent at
            # EVERY observation point, not just at the end — _build
            # writes both in one no-await window.
            keys = [(m.partition, m.index) for m in bound.plan.moves]
            if len(set(keys)) != len(keys):
                raise InvariantViolation(
                    f"duplicate moves in the schedule: {keys!r}")
            all_keys = set(keys) | set(bound.plan.stalled)
            if len(keys) + len(bound.plan.stalled) != len(all_keys):
                raise InvariantViolation(
                    "a move is both scheduled and stalled: "
                    f"{keys!r} / {bound.plan.stalled!r}")
            if all_keys != set(bound.last_remaining):
                raise InvariantViolation(
                    "rebuilt schedule diverges from the remaining set: "
                    f"plan+stalled={sorted(all_keys)!r} vs "
                    f"remaining={sorted(bound.last_remaining)!r}")
            for mv in bound.plan.moves:
                if mv.node in bound.quarantined():
                    raise InvariantViolation(
                        f"orphan lane: {mv!r} scheduled onto "
                        f"quarantined node {mv.node!r}")
                if not 0 <= mv.lane < max_lanes:
                    raise InvariantViolation(
                        f"lane {mv.lane} outside machine capacity "
                        f"{max_lanes} for {mv!r}")
        o.stop()
        inv.finish(executed=executed)
        if o._progress.tot_quarantine_trips < 1:
            raise InvariantViolation("breaker never tripped — scenario "
                                     "drifted from the code under test")
        if bound.reschedules < 1:
            raise InvariantViolation(
                "quarantine trip did not trigger a reschedule")
    return {"snapshots": inv.snapshots, "reschedules": bound.reschedules,
            "trips": o._progress.tot_quarantine_trips}


async def _supersede_mid_rebalance() -> dict[str, int]:
    """The continuous-rebalance controller's supersede path: a second
    cluster delta fired from INSIDE the first transition's assign
    callback (structurally mid-flight) must cancel cleanly — no orphan
    tasks after wind-down, no spurious failures — and the loop must
    land on the SAME final map as a quiesced sequential run.  The
    survivors here reduce to one node, so the sequential reference is
    unique regardless of which prefix of the first transition executed
    before the cancel.  Under most schedules the delta supersedes the
    in-flight pass (``superseded == 1``); a schedule that lets the pass
    finish first handles it as a second cycle — both must converge
    identically."""
    from ..obs import Recorder, use_recorder
    from ..plan.api import plan_next_map
    from ..rebalance import ClusterDelta, RebalanceController, count_moves

    loop = asyncio.get_running_loop()
    nodes = ["a", "b", "c"]
    # Unlike the scripted-move scenarios above, this one PLANS — the
    # model needs a real constraint (1 primary per partition), not the
    # constraints=0 placeholder of _MODEL.
    plan_model = {"primary": PartitionModelState(priority=0,
                                                 constraints=1)}
    beg = _pm({f"p{i}": {"primary": [nodes[i % 3]]} for i in range(4)})
    with use_recorder(Recorder(clock=loop.time)):
        fired = False
        ctl: Optional[RebalanceController] = None

        async def assign(stop_ch: Any, node: str, partitions: list[str],
                         states: list[str], ops: list[str]) -> None:
            nonlocal fired
            assert ctl is not None
            if not fired:
                fired = True
                ctl.submit(ClusterDelta(fail=("b",)))
            await asyncio.sleep(0.01)

        ctl = RebalanceController(plan_model, nodes, beg, assign,
                                  debounce_s=0.001)
        ctl.start()
        ctl.submit(ClusterDelta(remove=("a",)))
        final = await ctl.quiesce()
        await ctl.stop()
        for _ in range(3):  # let just-resolved movers finalize
            await asyncio.sleep(0)
        if ctl.pending_tasks():
            raise InvariantViolation(
                f"orphan tasks after cancel + wind-down: "
                f"{[t.get_name() for t in ctl.pending_tasks()]}")
        if ctl.failures:
            raise InvariantViolation(
                f"spurious failures on a fault-free supersede: "
                f"{ctl.failures!r}")
        if not fired:
            raise InvariantViolation(
                "the mid-flight delta never fired — scenario drifted "
                "from the code under test")
        # Sequential reference: quiesce delta 1 fully, then delta 2 —
        # pure planning, schedule-independent (c is the only survivor,
        # so the final map is unique).
        m1, _w1 = plan_next_map(beg, beg, nodes, ["a"], [], plan_model,
                                backend="greedy")
        m2, _w2 = plan_next_map(m1, m1, nodes, ["a", "b"], [], plan_model,
                                backend="greedy")
        if count_moves(plan_model, m2, final) != 0:
            raise InvariantViolation(
                f"superseded run diverged from the quiesced sequential "
                f"reference:\n  sequential: "
                f"{ {k: v.nodes_by_state for k, v in m2.items()} !r}\n"
                f"  superseded: "
                f"{ {k: v.nodes_by_state for k, v in final.items()} !r}")
        if any(p.nodes_by_state.get("primary") != ["c"]
               for p in final.values()):
            raise InvariantViolation(
                f"final map incomplete on the sole survivor: "
                f"{ {k: v.nodes_by_state for k, v in final.items()} !r}")
    return {"superseded": ctl.superseded, "cycles": ctl.cycles,
            "cancels": ctl.superseded}


async def _fleet_coalesce_window() -> dict[str, int]:
    """The plan service's coalescing window under admission fairness
    (ISSUE 13): a chatty tenant fires three CONCURRENT requests against
    ``fair_share=1`` while two calm neighbors submit one each, under
    arbitrary interleavings of the submitters, the dispatcher and the
    window timer.  Invariants: every future resolves exactly once with
    its own tenant's bit-exact single-problem solve (cross-wiring would
    surface as a foreign assign), no batch ever holds more than
    fair_share requests of one key, the starved counter equals the
    observed deferral events, and stop() strands nothing."""
    import numpy as np

    from ..obs import Recorder, use_recorder
    from ..plan.fleet import TenantProblem, solve_fleet
    from ..plan.service import PlanService

    loop = asyncio.get_running_loop()

    def tenant(key: str, seed: int) -> Any:
        P, N, S, R = 2, 3, 1, 1
        prev = np.full((P, S, R), -1, np.int32)
        prev[0, 0, 0] = seed % N
        prev[1, 0, 0] = (seed + 1) % N
        return TenantProblem(
            key=key, prev=prev,
            partition_weights=np.ones(P, np.float32),
            node_weights=np.ones(N, np.float32),
            valid_node=np.ones(N, bool),
            stickiness=np.full((P, S), 1.5, np.float32),
            gids=np.arange(N, dtype=np.int32).reshape(1, N),
            gid_valid=np.ones((1, N), bool),
            constraints=(1,), rules=((),))

    seeds = {"chatty": 0, "calm-b": 1, "calm-c": 2}
    # The oracle: each tenant's single-problem fleet solve (the service
    # result must be bit-identical to it, whatever the batching).
    expected = {key: solve_fleet([tenant(key, s)], record=False)[0].assign
                for key, s in seeds.items()}

    batches: list[list[str]] = []
    deferrals = 0

    class _Capturing(PlanService):
        def _solve_batch(self, problems: list[Any],
                         trace_ids: dict[str, Any]) -> Any:
            batches.append([t.key for t in problems])
            return super()._solve_batch(problems, trace_ids)

        def _defer(self, req: Any) -> None:
            nonlocal deferrals
            deferrals += 1
            super()._defer(req)

    rec = Recorder(clock=loop.time)
    with use_recorder(rec):
        svc = _Capturing(admission_window_s=0.01, fair_share=1,
                         inline_solve=True, max_pending=8, recorder=rec)
        await svc.start()
        results: dict[str, Any] = {}

        async def one(key: str, tag: str) -> None:
            results[tag] = await svc.submit(tenant(key, seeds[key]))

        tasks = [asyncio.ensure_future(one("chatty", f"chatty{i}"))
                 for i in range(3)]
        tasks += [asyncio.ensure_future(one("calm-b", "b")),
                  asyncio.ensure_future(one("calm-c", "c"))]
        await asyncio.gather(*tasks)
        await svc.stop()

    if len(results) != 5:
        raise InvariantViolation(
            f"{5 - len(results)} submit futures never resolved")
    for tag, res in results.items():
        key = "chatty" if tag.startswith("chatty") else \
            ("calm-b" if tag == "b" else "calm-c")
        if res.key != key:
            raise InvariantViolation(
                f"request {tag} resolved with tenant {res.key!r}: "
                f"cross-wired batch")
        if not np.array_equal(res.assign, expected[key]):
            raise InvariantViolation(
                f"request {tag} diverged from the single-problem "
                f"oracle: batching must be bit-neutral")
    for keys in batches:
        counts: dict[str, int] = {}
        for k in keys:
            counts[k] = counts.get(k, 0) + 1
        if any(c > 1 for c in counts.values()):
            raise InvariantViolation(
                f"a batch exceeded fair_share=1 for one tenant: {keys}")
    starved = int(rec.counters.get("fleet.starved_admissions", 0))
    if starved != deferrals:
        raise InvariantViolation(
            f"starved counter {starved} != observed deferral events "
            f"{deferrals}")
    return {"batches": len(batches), "starved": starved,
            "resolved": len(results)}


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        Scenario(
            name="two_movers_three_partitions",
            doc="2 movers / 3 partitions / 6 moves, legacy options: "
                "full invariant suite + exact op sequences",
            factory=_two_movers_three_partitions,
            exhaustive=True, branch_budget=2, max_schedules=12000),
        Scenario(
            name="pause_cycle_guard",
            doc="pause->resume->pause cycle inside the supplier's "
                "pause window must never feed while paused",
            factory=_pause_cycle_guard,
            exhaustive=True, branch_budget=2, max_schedules=4000),
        Scenario(
            name="pause_resume_during_retry_backoff",
            doc="pause/resume while a mover is in retry backoff "
                "(seeded chaos walks)",
            factory=_pause_resume_during_retry_backoff),
        Scenario(
            name="stop_during_quarantine_probe",
            doc="stop() inside the breaker's half-open probe window "
                "(seeded chaos walks)",
            factory=_stop_during_quarantine_probe),
        Scenario(
            name="movers_race_breaker_trip",
            doc="two movers race breaker trips on two dead nodes "
                "(seeded chaos walks)",
            factory=_movers_race_breaker_trip),
        Scenario(
            name="slo_gauges_under_chaos",
            doc="SLO gauges stay well-formed and agree with the "
                "achieved map under chaos (seeded chaos walks)",
            factory=_slo_gauges_under_chaos),
        Scenario(
            name="reschedule_on_quarantine",
            doc="a breaker trip mid-schedule rebuilds the critical-"
                "path plan: every unfinished move exactly once, no "
                "orphan lanes, cursors never reverse (seeded chaos "
                "walks)",
            factory=_reschedule_on_quarantine),
        Scenario(
            name="supersede_mid_rebalance",
            doc="a delta mid-rebalance cancels cleanly (no orphan "
                "tasks) and lands on the sequential run's final map "
                "(seeded chaos walks)",
            factory=_supersede_mid_rebalance),
        Scenario(
            name="fleet_coalesce_window",
            doc="plan-service coalescing window under admission "
                "fairness: a chatty tenant vs fair_share=1 — every "
                "request resolves bit-exactly, no batch over quota, "
                "starved counter consistent (seeded chaos walks)",
            factory=_fleet_coalesce_window),
    )
}


# -- runners -----------------------------------------------------------------


# "use the scenario's own budget" sentinel for run_scenario_exhaustive —
# distinct from None, which (as in explore()) means a true unbounded
# exhaustive enumeration.
_SCENARIO_DEFAULT = object()


def run_scenario_exhaustive(
    scenario: Scenario,
    branch_budget: object = _SCENARIO_DEFAULT,
    max_schedules: Optional[int] = None,
) -> ExploreReport:
    budget: Optional[int]
    if branch_budget is _SCENARIO_DEFAULT:
        budget = scenario.branch_budget
    else:
        assert branch_budget is None or isinstance(branch_budget, int)
        budget = branch_budget
    return explore(
        scenario.factory,
        branch_budget=budget,
        max_schedules=(scenario.max_schedules if max_schedules is None
                       else max_schedules))


def run_scenario_walks(
    scenario: Scenario, seeds: tuple[int, ...] = CI_WALK_SEEDS,
) -> list[tuple[int, ScheduleOutcome]]:
    return [(seed,
             run_controlled(scenario.factory, RandomWalkPolicy(seed)))
            for seed in seeds]


def _emit_traces(scenario: str, violations: list[Any],
                 trace_dir: str, limit: int = 5) -> list[str]:
    os.makedirs(trace_dir, exist_ok=True)
    paths = []
    for i, v in enumerate(violations[:limit]):
        path = os.path.join(trace_dir, f"{scenario}-{i}.json")
        save_trace(v.to_trace(scenario), path)
        paths.append(path)
    return paths


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m blance_tpu.analysis.schedule",
        description="deterministic schedule exploration of the "
                    "orchestrator (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--ci", action="store_true",
                    help="the race-smoke gate: bounded-exhaustive pass "
                         "over the small scenarios + pinned-seed walks "
                         "over the chaos scenarios")
    ap.add_argument("--scenario", default=None,
                    help="run one scenario by name")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    ap.add_argument("--budget", type=int, default=None,
                    help="branch budget for exhaustive mode (-1 = "
                         "unbounded)")
    ap.add_argument("--max-schedules", type=int, default=None,
                    help="schedule cap for exhaustive mode")
    ap.add_argument("--seeds", default=None,
                    help="comma-separated walk seeds (walk mode)")
    ap.add_argument("--trace-dir", default="sched-traces",
                    help="where violating schedules are written as "
                         "replayable JSON traces")
    args = ap.parse_args(argv)

    if args.list:
        for s in SCENARIOS.values():
            kind = ("exhaustive" if s.exhaustive else "walk")
            print(f"{s.name:40s} [{kind}] {s.doc}")
        return 0

    budget: object = _SCENARIO_DEFAULT
    if args.budget is not None:
        # Negative = explicit None = truly unbounded enumeration; any
        # other value overrides the scenario's own bounded budget.
        budget = None if args.budget < 0 else args.budget

    failed = False

    def run_one(s: Scenario, exhaustive: bool,
                seeds: tuple[int, ...]) -> None:
        nonlocal failed
        if exhaustive:
            rep = run_scenario_exhaustive(
                s, branch_budget=budget, max_schedules=args.max_schedules)
            status = rep.summary()
            if rep.violations:
                failed = True
                paths = _emit_traces(s.name, rep.violations,
                                     args.trace_dir)
                status += " -> " + ", ".join(paths)
            if not rep.complete:
                # A capped enumeration silently stops checking the
                # coverage the gate promises — fail loudly so the
                # budget gets raised (or the scenario shrunk) instead.
                failed = True
                status += " — INCOMPLETE (raise --max-schedules or " \
                          "shrink the scenario)"
            print(f"explore {s.name}: {status}")
        else:
            for seed, out in run_scenario_walks(s, seeds):
                line = f"walk {s.name} seed={seed}: {out.describe()}"
                if not out.ok:
                    failed = True
                    os.makedirs(args.trace_dir, exist_ok=True)
                    path = os.path.join(args.trace_dir,
                                        f"{s.name}-seed{seed}.json")
                    save_trace(
                        Trace(scenario=s.name, choices=out.choices,
                              candidate_counts=out.candidate_counts,
                              seed=seed,
                              note=f"{type(out.error).__name__}: "
                                   f"{out.error}"),
                        path)
                    line += f" -> {path}"
                print(line)

    seeds = CI_WALK_SEEDS
    if args.seeds:
        seeds = tuple(int(x) for x in args.seeds.split(","))

    if args.scenario:
        s = SCENARIOS.get(args.scenario)
        if s is None:
            print(f"unknown scenario {args.scenario!r}; --list shows "
                  f"the registry", file=sys.stderr)
            return 2
        run_one(s, exhaustive=(s.exhaustive and args.seeds is None),
                seeds=seeds)
    elif args.ci:
        for s in SCENARIOS.values():
            if s.exhaustive:
                run_one(s, exhaustive=True, seeds=seeds)
        for s in SCENARIOS.values():
            # The exhaustive scenarios' walk interleavings are a strict
            # subset of the enumeration that just ran — chaos walks only.
            if not s.exhaustive:
                run_one(s, exhaustive=False, seeds=seeds)
    else:
        ap.print_help()
        return 2

    print("blance_tpu.analysis.schedule: " +
          ("FAIL (traces in %s)" % args.trace_dir if failed else "OK"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
