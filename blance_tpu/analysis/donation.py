"""Donation lint: static use-after-donation detection (DON00x).

The fused pipeline's perf story rests on buffer donation (plan/tensor.py:
``prev`` and the consumed carry table are XLA-aliased into the outputs),
and donation has a property no test tier catches: on CPU it is a warning
and a silent copy, on device backends it invalidates the operand buffer.
PR 11's post-review found two live use-after-donation reads that every
CPU run sailed through.  This pass is the static gate: the donation
contract becomes build-failing findings the moment they are written
(TOAST's thesis, arXiv:2508.15010 — partitioning-system invariants
belong to principled static analysis, not review memory).

The pass builds the shared :class:`._astutil.ModuleIndex`, then

1. resolves every donating callable: module-level
   ``f = jax.jit(impl, donate_argnames=...)`` bindings (and their plain
   aliases), ``partial(jax.jit, ...)`` application, and
   ``@jax.jit(...)`` / ``@partial(jax.jit, ...)`` decorators, with
   ``donate_argnums`` mapped to parameter names through the wrapped
   function's positional signature;
2. runs a linear execution-order liveness walk over every function
   (nested defs are fresh scopes), tracking value identity through
   rebinds (generation counters), zero-copy device aliases
   (``jnp.asarray`` / ``jax.device_put``), tuple packing for ``*args``
   splats, and attribute roots (``self.current``, ``carry.used``).

Rules:

- **DON001** read of a donated operand after its donating dispatch —
  including reads through aliases, attribute roots, packed argument
  tuples, and values returned so callers can re-read them (the exact
  PR-11 bug shape).  On a device backend that buffer is gone.
- **DON002** a donated operand escapes before the dispatch — stored to
  ``self.*``/an outer container or handed to a ``self.*`` store method
  (the CarryCache/EncodeCache risk surface): another window now holds a
  reference the dispatch invalidates.
- **DON003** the same value dispatched through a donating callable
  twice without rebinding — the second dispatch donates an
  already-invalidated buffer.
- **DON004** host snapshot (``np.asarray`` / ``.copy()``) of a donated
  operand AFTER its dispatch: the snapshot reads invalidated memory.
  The same snapshot BEFORE the dispatch is the sanctioned fix recipe
  (``prev_fb = np.asarray(prev) if donate else prev``) and is
  recognized as producing a fresh value, exempt from every rule.
- **DON000** file does not parse (the shared parse-error funnel).

Conservative exemptions keep the signal clean: ``.shape``/``.dtype``
metadata reads survive donation (the aval outlives the buffer) and a
conditional snapshot arm (the ``if donate else`` idiom) makes the bound
name a fresh value.  Findings fold through ``analysis/baseline.toml``
exactly like JIT/ASY/RACE/DET rules; the package itself carries zero.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional, Sequence

from . import Finding
from ._astutil import FuncInfo, ModuleIndex, ModuleInfo
from ._astutil import dotted as _dotted
from .jit_purity import _literal_ints, _literal_strings

__all__ = ["DonationPass", "DonatingCallable"]

# A value identity: a root key (("name", "prev") / ("attr", "self.current"))
# plus a rebind generation — rebinding bumps the generation, so a donated
# vid never matches the freshly bound value under the same name.
_Key = tuple[str, str]
_Vid = tuple[_Key, int]

#: Zero-copy device aliases: the result shares the operand's buffer when
#: it is already on device, so donating the result donates the operand.
_ALIAS_FQS = frozenset({
    "jax.numpy.asarray",
    "jax.numpy.ascontiguousarray",
    "jax.device_put",
})

#: Host snapshots: the result is a fresh host copy, never aliased —
#: donating after one is safe, snapshotting a donated value is not.
_SNAPSHOT_FQS = frozenset({
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
    "numpy.copy",
    "jax.device_get",
})

#: Attribute reads that survive donation: jax keeps the aval (shape,
#: dtype, sharding metadata) alive after the buffer is invalidated.
_METADATA_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "device", "aval", "weak_type", "is_deleted",
})

#: Store methods on ``self.*`` receivers that publish a reference to a
#: longer-lived container (the CarryCache/EncodeCache surface).
_ESCAPE_METHODS = frozenset({
    "store", "store_pending", "promote", "append", "add", "put",
    "update", "setdefault", "push", "cache",
})


@dataclass(frozen=True)
class DonatingCallable:
    """One jit-wrapped callable with donated parameters resolved."""

    fq: str  # fully-qualified name the dispatch sites call
    line: int
    params: tuple[str, ...]  # wrapped function's full parameter order
    donated: tuple[str, ...]  # donated parameter names


def _is_jit_ref(index: ModuleIndex, mi: ModuleInfo, node: ast.AST) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    fq = index.resolve(mi, d)
    return fq in ("jax.jit", "jax.pjit", "jax.jit.jit") or \
        (fq.endswith(".jit") and fq.startswith("jax"))


class DonationPass:
    """Whole-program pass: index, resolve donating callables, run the
    liveness walk over every function body."""

    def __init__(self, files: list[str], repo_root: str) -> None:
        self.index = ModuleIndex(files, repo_root)
        self.findings: list[Finding] = []
        self.registry: dict[str, DonatingCallable] = {}
        for rel, line, msg in self.index.parse_errors:
            self.findings.append(Finding(
                rule="DON000", path=rel, line=line, symbol="",
                message=f"file does not parse: {msg}"))

    # -- donating-callable discovery ----------------------------------------

    def _wrapped_info(self, mi: ModuleInfo,
                      node: ast.AST) -> Optional[FuncInfo]:
        """The function a jit wraps: a dotted reference or a one-level
        ``partial(f, ...)``."""
        if isinstance(node, ast.Call):
            return self.index.partial_target(mi, node)
        d = _dotted(node)
        if d is None:
            return None
        return self.index.lookup_function(mi, d)

    def _donated_params(self, mi: ModuleInfo, keywords: list[ast.keyword],
                        wrapped: Optional[FuncInfo]) -> list[str]:
        out: list[str] = []
        for kw in keywords:
            if kw.arg == "donate_argnames":
                names = _literal_strings(kw.value, mi.constants)
                if names:
                    out.extend(n for n in names if n not in out)
            elif kw.arg == "donate_argnums" and wrapped is not None:
                nums = _literal_ints(kw.value, mi.constants)
                fnode = wrapped.node
                if nums is None or not isinstance(
                        fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                args = fnode.args
                pos = [a.arg for a in args.posonlyargs] + \
                    [a.arg for a in args.args]
                for i in nums:
                    if 0 <= i < len(pos) and pos[i] not in out:
                        out.append(pos[i])
        return out

    def _donating_from_value(self, mi: ModuleInfo,
                             value: ast.expr) -> Optional[DonatingCallable]:
        """``jax.jit(f, donate_*=...)`` or
        ``partial(jax.jit, donate_*=...)(f)`` as an assigned value."""
        if not isinstance(value, ast.Call):
            return None
        if _is_jit_ref(self.index, mi, value.func) and value.args:
            wrapped = self._wrapped_info(mi, value.args[0])
            donated = self._donated_params(mi, value.keywords, wrapped)
            if donated and wrapped is not None:
                return DonatingCallable(
                    fq=wrapped.fq, line=value.lineno,
                    params=tuple(wrapped.params), donated=tuple(donated))
            return None
        inner = value.func
        if isinstance(inner, ast.Call) and inner.args and \
                _is_jit_ref(self.index, mi, inner.args[0]) and \
                self.index.resolve(mi, _dotted(inner.func) or "") == \
                "functools.partial" and value.args:
            wrapped = self._wrapped_info(mi, value.args[0])
            donated = self._donated_params(mi, inner.keywords, wrapped)
            if donated and wrapped is not None:
                return DonatingCallable(
                    fq=wrapped.fq, line=value.lineno,
                    params=tuple(wrapped.params), donated=tuple(donated))
        return None

    def _donating_from_decorator(
            self, mi: ModuleInfo, fn: FuncInfo,
            dec: ast.AST) -> Optional[DonatingCallable]:
        if not isinstance(dec, ast.Call):
            return None
        keywords: Optional[list[ast.keyword]] = None
        if _is_jit_ref(self.index, mi, dec.func):  # @jax.jit(...)
            keywords = dec.keywords
        elif dec.args and _is_jit_ref(self.index, mi, dec.args[0]) and \
                self.index.resolve(mi, _dotted(dec.func) or "") == \
                "functools.partial":  # @partial(jax.jit, ...)
            keywords = dec.keywords
        if keywords is None:
            return None
        donated = self._donated_params(mi, keywords, fn)
        fnode = fn.node
        if not donated or not isinstance(
                fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        return DonatingCallable(
            fq=fn.fq, line=fnode.lineno, params=tuple(fn.params),
            donated=tuple(donated))

    def _build_registry(self) -> None:
        for mi in self.index.modules.values():
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    dc = self._donating_from_value(mi, node.value)
                    if dc is not None:
                        self.registry[
                            f"{mi.name}.{node.targets[0].id}"] = dc
            for fn in mi.functions.values():
                if not isinstance(
                        fn.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for dec in fn.node.decorator_list:
                    dc = self._donating_from_decorator(mi, fn, dec)
                    if dc is not None:
                        self.registry[fn.fq] = dc
        # Plain aliases of donating bindings (one propagation round:
        # ``impl = _warm_repair_donating`` at module level).
        for mi in self.index.modules.values():
            for node in ast.walk(mi.tree):
                if not (isinstance(node, ast.Assign) and
                        len(node.targets) == 1 and
                        isinstance(node.targets[0], ast.Name)):
                    continue
                d = _dotted(node.value)
                if d is None:
                    continue
                dc = self._registry_lookup(mi, d)
                if dc is not None:
                    self.registry.setdefault(
                        f"{mi.name}.{node.targets[0].id}", dc)

    def _registry_lookup(self, mi: ModuleInfo,
                         dotted_ref: str) -> Optional[DonatingCallable]:
        local = f"{mi.name}.{dotted_ref}"
        if local in self.registry:
            return self.registry[local]
        return self.registry.get(self.index.resolve(mi, dotted_ref))

    # -- driver -------------------------------------------------------------

    def run(self) -> list[Finding]:
        self._build_registry()
        for mi in self.index.modules.values():
            for fn in mi.functions.values():
                node = fn.node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._lint_body(mi, fn.path, fn.qualname, node.body)
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return self.findings

    def _lint_body(self, mi: ModuleInfo, path: str, symbol: str,
                   body: list[ast.stmt]) -> None:
        _ScopeLinter(self, mi, path, symbol).run(body)


def _walk_no_nested(nodes: Sequence[ast.AST]) -> list[ast.AST]:
    """All nodes under ``nodes`` except nested function/class bodies
    (those are linted as their own scopes)."""
    out: list[ast.AST] = []
    stack: list[ast.AST] = list(nodes)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class _ScopeLinter:
    """Linear execution-order liveness walk over one function body.

    Path-insensitive: branches are walked in source order and their
    effects accumulate — sound for the dispatch helpers this pass
    guards, whose donating call happens exactly once per scope, and
    conservative everywhere else (a read in EITHER branch after a
    dispatch in EITHER branch is flagged)."""

    def __init__(self, owner: DonationPass, mi: ModuleInfo, path: str,
                 symbol: str) -> None:
        self.owner = owner
        self.mi = mi
        self.path = path
        self.symbol = symbol
        self.gen: dict[_Key, int] = {}
        # name -> vid of the value it aliases (x = jnp.asarray(prev))
        self.alias_of: dict[str, _Vid] = {}
        # name -> element exprs of a tuple literal (for *args splats)
        self.tuple_bind: dict[str, list[ast.expr]] = {}
        # vid -> (time, line, callee fq, donated param name)
        self.donated: dict[_Vid, tuple[int, int, str, str]] = {}
        # (vid, time, line, where) — judged against dispatch times at end
        self.escapes: list[tuple[_Vid, int, int, str]] = []
        self.time = 0
        self.callable_aliases: dict[str, DonatingCallable] = {}

    # -- plumbing -----------------------------------------------------------

    def _emit(self, rule: str, line: int, message: str) -> None:
        self.owner.findings.append(Finding(
            rule=rule, path=self.path, line=line, symbol=self.symbol,
            message=message))

    def _vid(self, key: _Key) -> _Vid:
        return (key, self.gen.get(key, 0))

    def _describe(self, vid: _Vid) -> str:
        return vid[0][1]

    # -- value identity -----------------------------------------------------

    def _unwrap_alias(self, expr: ast.expr) -> ast.expr:
        while isinstance(expr, ast.Call) and len(expr.args) == 1 and \
                not expr.keywords:
            d = _dotted(expr.func)
            if d is None or \
                    self.owner.index.resolve(self.mi, d) not in _ALIAS_FQS:
                break
            expr = expr.args[0]
        return expr

    def _is_snapshot_call(self, expr: ast.expr) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        if isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "copy" and not expr.args:
            return True
        d = _dotted(expr.func)
        return d is not None and \
            self.owner.index.resolve(self.mi, d) in _SNAPSHOT_FQS

    def _value_id(self, expr: ast.expr) -> Optional[_Vid]:
        """The tracked identity of the buffer ``expr`` evaluates to, or
        None for fresh values (snapshots, computed results)."""
        expr = self._unwrap_alias(expr)
        if self._is_snapshot_call(expr):
            return None
        if isinstance(expr, ast.IfExp):
            # ``np.asarray(x) if donate else x``: whichever arm runs,
            # the name is safe to read post-dispatch exactly when the
            # snapshot arm covers the donating case — the sanctioned
            # fix idiom.  A snapshot in either arm makes the value
            # fresh.
            if self._is_snapshot_call(self._unwrap_alias(expr.body)) or \
                    self._is_snapshot_call(self._unwrap_alias(expr.orelse)):
                return None
            return self._value_id(expr.body)
        if isinstance(expr, ast.Name):
            if expr.id in self.alias_of:
                return self.alias_of[expr.id]
            return self._vid(("name", expr.id))
        if isinstance(expr, ast.Attribute):
            d = _dotted(expr)
            if d is not None:
                return self._vid(("attr", d))
        return None

    # -- rebinding ----------------------------------------------------------

    def _bump_prefixed(self, root: str) -> None:
        """Rebinding ``carry`` also retires ``carry.used``'s identity —
        donated entries keep their old (key, generation) vid, which no
        fresh read can match."""
        prefix = root + "."
        for key in list(self.gen):
            if key[0] == "attr" and key[1].startswith(prefix):
                self.gen[key] += 1

    def _rebind_name(self, name: str) -> None:
        self.alias_of.pop(name, None)
        self.tuple_bind.pop(name, None)
        key: _Key = ("name", name)
        self.gen[key] = self.gen.get(key, 0) + 1
        self._bump_prefixed(name)

    def _rebind_chain(self, dotted_ref: str) -> None:
        key: _Key = ("attr", dotted_ref)
        self.gen[key] = self.gen.get(key, 0) + 1
        self._bump_prefixed(dotted_ref)

    def _rebind_target(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self._rebind_name(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._rebind_target(elt)
        elif isinstance(target, ast.Starred):
            self._rebind_target(target.value)
        elif isinstance(target, ast.Attribute):
            d = _dotted(target)
            if d is not None:
                self._rebind_chain(d)

    # -- the walk -----------------------------------------------------------

    def run(self, body: list[ast.stmt]) -> None:
        self.callable_aliases = self._scope_callable_aliases(body)
        self._stmts(body)
        self._finalize()

    def _scope_callable_aliases(
            self, body: list[ast.stmt]) -> dict[str, DonatingCallable]:
        """``impl = _warm_repair_donating if donate else _warm_repair_jit``
        (either arm donating) and plain ``impl = _x_donating`` bindings,
        prescanned so dispatch-through-alias resolves."""
        out: dict[str, DonatingCallable] = {}
        for node in _walk_no_nested(body):
            if not (isinstance(node, ast.Assign) and
                    len(node.targets) == 1 and
                    isinstance(node.targets[0], ast.Name)):
                continue
            value = node.value
            arms = [value.body, value.orelse] \
                if isinstance(value, ast.IfExp) else [value]
            for arm in arms:
                d = _dotted(arm)
                if d is None:
                    continue
                dc = self.owner._registry_lookup(self.mi, d)
                if dc is not None:
                    out[node.targets[0].id] = dc
                    break
        return out

    def _stmts(self, body: list[ast.stmt]) -> None:
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        self.time += 1
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.owner._lint_body(
                self.mi, self.path, f"{self.symbol}.{st.name}", st.body)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._expr(st.value)
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value)
            for t in st.targets:
                self._assign_target(t, st.value, st.lineno)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value)
                self._assign_target(st.target, st.value, st.lineno)
            return
        if isinstance(st, ast.AugAssign):
            self._expr(st.value)
            if isinstance(st.target, ast.Name):
                self._check_name_read(st.target.id, st.lineno)
                self._rebind_name(st.target.id)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value)
            return
        if isinstance(st, ast.If):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter)
            self._rebind_target(st.target)
            self._stmts(st.body)
            self._stmts(st.orelse)
            return
        if isinstance(st, ast.While):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._rebind_target(item.optional_vars)
            self._stmts(st.body)
            return
        if isinstance(st, ast.Try):
            self._stmts(st.body)
            for handler in st.handlers:
                self._stmts(handler.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
            return
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc)
            return
        if isinstance(st, ast.Assert):
            self._expr(st.test)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._rebind_target(t)
            return
        if isinstance(st, ast.Match):
            self._expr(st.subject)
            for case in st.cases:
                self._stmts(case.body)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _assign_target(self, target: ast.expr, value: ast.expr,
                       line: int) -> None:
        if isinstance(target, ast.Name):
            self._rebind_name(target.id)
            if isinstance(value, ast.Tuple):
                self.tuple_bind[target.id] = list(value.elts)
                return
            unwrapped = self._unwrap_alias(value)
            if isinstance(unwrapped, ast.IfExp) or \
                    isinstance(unwrapped, (ast.Name, ast.Attribute)):
                vid = self._value_id(value)
                if vid is not None:
                    self.alias_of[target.id] = vid
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._rebind_target(elt)
            return
        if isinstance(target, ast.Attribute):
            d = _dotted(target)
            if d is not None:
                self._rebind_chain(d)
            self._record_escape(value, line,
                                d if d is not None else "an attribute")
            return
        if isinstance(target, ast.Subscript):
            base = _dotted(target.value)
            self._record_escape(
                value, line,
                f"{base}[...]" if base is not None else "a container")
            return
        if isinstance(target, ast.Starred):
            self._rebind_target(target.value)

    def _record_escape(self, value: ast.expr, line: int,
                       where: str) -> None:
        vid = self._value_id(value)
        if vid is not None:
            self.escapes.append((vid, self.time, line, where))

    # -- expression side: reads, snapshots, dispatches ----------------------

    def _expr(self, expr: ast.expr) -> None:
        self._read_walk(expr)
        for node in _walk_no_nested([expr]):
            if isinstance(node, ast.Call):
                dc = self._donating_callee(node)
                if dc is not None:
                    self._dispatch(node, dc)
                else:
                    self._call_escapes(node)

    def _donating_callee(self,
                         call: ast.Call) -> Optional[DonatingCallable]:
        d = _dotted(call.func)
        if d is None:
            return None
        if d in self.callable_aliases:
            return self.callable_aliases[d]
        return self.owner._registry_lookup(self.mi, d)

    def _donated_arg_exprs(
            self, call: ast.Call,
            dc: DonatingCallable) -> list[tuple[str, ast.expr, int]]:
        """(param, argument expr, line) per donated parameter bound at
        this call, expanding ``*tuple_name`` splats through tuple-literal
        bindings."""
        pos: list[Optional[ast.expr]] = []
        for a in call.args:
            if isinstance(a, ast.Starred):
                if isinstance(a.value, ast.Name) and \
                        a.value.id in self.tuple_bind:
                    pos.extend(self.tuple_bind[a.value.id])
                else:
                    break  # opaque splat: positions beyond it unknown
            else:
                pos.append(a)
        out: list[tuple[str, ast.expr, int]] = []
        for i, param in enumerate(dc.params):
            if param in dc.donated and i < len(pos):
                arg = pos[i]
                if arg is not None:
                    out.append((param, arg, call.lineno))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in dc.donated:
                out.append((kw.arg, kw.value, call.lineno))
        return out

    def _dispatch(self, call: ast.Call, dc: DonatingCallable) -> None:
        for param, arg, line in self._donated_arg_exprs(call, dc):
            vid = self._value_id(arg)
            if vid is None:
                continue  # fresh value (snapshot/computed): safe donation
            prior = self.donated.get(vid)
            if prior is not None:
                self._emit(
                    "DON003", line,
                    f"{self._describe(vid)!r} is dispatched through "
                    f"donating callable {dc.fq} but was already donated "
                    f"at line {prior[1]} (to {prior[2]}) without being "
                    f"rebound — the second dispatch donates an "
                    f"invalidated buffer")
            self.donated[vid] = (self.time, line, dc.fq, param)

    def _call_escapes(self, call: ast.Call) -> None:
        """``self.cache.store(prev)``-shaped publication of a reference
        into longer-lived state."""
        if not (isinstance(call.func, ast.Attribute) and
                call.func.attr in _ESCAPE_METHODS):
            return
        receiver = _dotted(call.func.value)
        if receiver is None or \
                receiver.split(".")[0] not in ("self", "cls"):
            return
        where = f"{receiver}.{call.func.attr}()"
        for arg in list(call.args) + \
                [kw.value for kw in call.keywords if kw.arg is not None]:
            self._record_escape(arg, call.lineno, where)

    # -- reads --------------------------------------------------------------

    def _don001(self, vid: _Vid, line: int, what: str) -> None:
        _t, dline, callee, param = self.donated[vid]
        self._emit(
            "DON001", line,
            f"{what} after its donating dispatch to {callee} at line "
            f"{dline} (donated as {param!r}) — the buffer is invalidated "
            f"on device backends (CPU only warns); snapshot host-side "
            f"before the dispatch (np.asarray) or rebind the name")

    def _check_name_read(self, name: str, line: int) -> None:
        for vid in (self._vid(("name", name)), self.alias_of.get(name)):
            if vid is not None and vid in self.donated:
                self._don001(vid, line, f"reads {name!r}")
                return
        for elt in self.tuple_bind.get(name, []):
            vid = self._value_id(elt)
            if vid is not None and vid in self.donated:
                self._don001(
                    vid, line,
                    f"reads {name!r}, which packs donated operand "
                    f"{self._describe(vid)!r},")
                return

    def _check_chain_read(self, dotted_ref: str, line: int) -> None:
        parts = dotted_ref.split(".")
        head = parts[0]
        self._check_name_read(head, line)
        for cut in range(2, len(parts) + 1):
            prefix = ".".join(parts[:cut])
            vid = self._vid(("attr", prefix))
            if vid in self.donated:
                self._don001(vid, line, f"reads {dotted_ref!r}")
                return

    def _read_walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.Call):
            # Host snapshot of a donated value: DON004, not DON001 —
            # the recipe is right, the placement (after the dispatch)
            # is the bug.
            snap_arg: Optional[ast.expr] = None
            if self._is_snapshot_call(node):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "copy" and not node.args:
                    snap_arg = node.func.value
                elif node.args:
                    snap_arg = node.args[0]
            if snap_arg is not None:
                vid = self._value_id(snap_arg)
                if vid is not None and vid in self.donated:
                    _t, dline, callee, _param = self.donated[vid]
                    self._emit(
                        "DON004", node.lineno,
                        f"host snapshot of donated operand "
                        f"{self._describe(vid)!r} AFTER its donating "
                        f"dispatch to {callee} at line {dline} — the "
                        f"snapshot reads invalidated memory; move it "
                        f"before the dispatch (the "
                        f"`np.asarray(x) if donate else x` idiom)")
                    for rest in node.args[1:]:
                        self._read_walk(rest)
                    return
            # The donated arguments of a donating dispatch ARE the
            # donation, not a use-after — suppress their root reads so
            # a re-dispatch reports one DON003, not DON001 noise on top.
            skip: set[int] = set()
            dc = self._donating_callee(node)
            if dc is not None:
                skip = {id(arg) for _p, arg, _l
                        in self._donated_arg_exprs(node, dc)}
            self._read_walk(node.func)
            for a in node.args:
                if dc is not None and isinstance(a, ast.Starred):
                    continue  # splat elements are covered by _dispatch
                if id(a) not in skip:
                    self._read_walk(a)
            for kw in node.keywords:
                if id(kw.value) not in skip:
                    self._read_walk(kw.value)
            return
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d is not None:
                if node.attr in _METADATA_ATTRS:
                    return  # shape/dtype metadata outlives the buffer
                self._check_chain_read(d, node.lineno)
                return
            self._read_walk(node.value)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self._check_name_read(node.id, node.lineno)
            return
        if isinstance(node, ast.IfExp):
            # Arms of the conditional-snapshot idiom are handled by
            # _value_id; reads inside still count.
            self._read_walk(node.test)
            self._read_walk(node.body)
            self._read_walk(node.orelse)
            return
        for child in ast.iter_child_nodes(node):
            self._read_walk(child)

    # -- scope end ----------------------------------------------------------

    def _finalize(self) -> None:
        for vid, t, line, where in self.escapes:
            record = self.donated.get(vid)
            if record is not None and t < record[0]:
                self._emit(
                    "DON002", line,
                    f"donated operand {self._describe(vid)!r} escapes "
                    f"into {where} before its donating dispatch to "
                    f"{record[2]} at line {record[1]} — the stored "
                    f"reference observes an invalidated buffer after "
                    f"the dispatch; store a host snapshot "
                    f"(np.asarray) or store the dispatch output instead")
