"""Await-atomicity race lint: torn invariants in the asyncio control plane.

Single-threaded asyncio gives one guarantee: everything between two
``await``s is atomic.  The orchestrator's correctness (PR 3's retries,
quarantine and recovery all mutate shared ``Orchestrator`` /
``NodeHealth`` / ``Chan`` state from cooperating tasks) rests entirely
on code respecting that window — and nothing checked it.  This pass
models the control plane's shared mutable state declaratively (the
:data:`SHARED_STATE` table — one entry per class, one attribute set per
entry; ``docs/DESIGN.md`` §5 documents the intent behind each) and
flags the three ways the window gets torn:

- RACE001 — **read-modify-write across an await**: a local is bound
  from a shared attribute, an ``await`` intervenes, and the attribute
  is then written from an expression using that stale local.  Another
  task's write inside the window is silently lost (the classic lost
  update).
- RACE002 — **stale guard**: a local is bound from a shared attribute
  (a state flag / channel like ``_paused`` or breaker state), an
  ``await`` intervenes, and the local is then *used* without re-reading
  the attribute.  The guard may no longer hold — the pause/resume/pause
  cycle against the supplier's captured ``_pause_ch`` was exactly this
  bug.  Re-binding from the attribute after the await (e.g. a
  revalidation loop) clears the finding.
- RACE003 — **multi-root unserialized mutation**: the same shared
  attribute is mutated from two or more distinct task entry points
  (methods spawned via ``_spawn``/``ensure_future``/``create_task``,
  plus the externally-called sync surface) of a task-owning class.
  Interleaving order between the roots is scheduler-chosen; the finding
  demands either a serialization point or a baseline entry stating the
  discipline that makes the shared access safe (e.g. append-only lists,
  whose appends are single-window atomic — then the schedule explorer's
  append-only invariant enforces the discipline dynamically).

RACE001/002 analyze ``async def`` bodies only (a sync function cannot
be preempted mid-body); the analysis is linear over execution order —
within an ``await expr``, the inner expression's reads happen *before*
the suspension, so ``await (x := self._flag).get()`` style re-reads are
ordered correctly.  RACE003 is whole-class.  Both deliberately track
only locals bound from a *plain attribute load* — guards derived
through method calls are invisible, which keeps the pass quiet enough
to gate CI (the false-positive budget goes to the explorer, which
checks the dynamic invariants the lint cannot).

Scope: the lint runs over any file it is handed, but only classes named
in the shared-state model produce findings, which confines it to the
control plane (``orchestrate/``, ``rebalance.py``) by construction.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator, Optional, Union

from . import Finding
from ._astutil import FindingEmitter, dotted as _dotted

__all__ = ["SHARED_STATE", "lint_file", "lint_source"]

# -- the shared-state model --------------------------------------------------
#
# class name -> attributes that are MUTABLE SHARED STATE: touched by more
# than one cooperating task (or by a task plus the app-facing sync control
# surface).  Immutable-after-init attributes (model, options, nodes_all,
# _rec, ...) are deliberately absent — a stale read of an immutable value
# cannot tear anything, and listing them would drown the signal.
# docs/DESIGN.md "Shared state & serialization points" is the prose twin
# of this table; keep them in sync.
SHARED_STATE: dict[str, frozenset[str]] = {
    "Orchestrator": frozenset({
        "_stop_ch", "_pause_ch", "_progress", "_tasks", "failures",
        "health", "_map_partition_to_next_moves", "_missing_mover_warned",
    }),
    "OrchestratorProgress": frozenset({"errors"}),
    "HealthTracker": frozenset({"_nodes"}),
    "NodeHealth": frozenset({
        "state", "consecutive_failures", "trips", "tripped_at",
        "probe_in_flight",
    }),
    "Chan": frozenset({"_getters", "_putters", "_closed"}),
    "NextMoves": frozenset({"next", "next_done_ch", "failed_at"}),
    # -- live telemetry plane (PR 6) ----------------------------------------
    # SloTracker is mutated by every mover task (the on_batch observer
    # hook) and read by the exposition server's snapshot path;
    # CostModel's tables are updated from span-finish callbacks on the
    # same tasks and read by the scheduler-facing predict().  Both rely
    # on the single-atomic-window discipline: every mutator is a plain
    # sync method with no await inside, so updates cannot interleave on
    # the event loop.  The lint's RACE001/002 passes watch any future
    # async method that breaks that discipline.
    "SloTracker": frozenset({
        "_placements", "_primaries", "_available", "moves_executed",
        "moves_failed", "_min_moves", "_t_last_progress", "_health",
        "_incident_t0", "_incident_moves0", "_incident_fails0",
        "_t_last_fail", "_first_converged_lags",
    }),
    "CostModel": frozenset({"_est", "_op_est", "_global", "_errors",
                            "_n_scored"}),
    # -- fleet plan service (PR 7) ------------------------------------------
    # PlanService's control state is touched by the app-facing surface
    # (submit/stop) and the dispatcher task; every mutation sits in one
    # no-await window, and the bounded queue is the only rendezvous.
    # The CarryCache is written ONLY from the dispatcher task (sessions
    # own private caches), a discipline this entry documents — any
    # future async method on either class puts it under RACE001/002.
    "PlanService": frozenset({"_queue", "_task", "_closed", "_executor",
                              "_deferred"}),
    "CarryCache": frozenset({"_entries", "_clock", "_bytes",
                             "evictions"}),
    # EncodeCache (ISSUE 14, plan/carry.py) is shared by N tenant
    # control-loop tasks.  Discipline: every method is synchronous (one
    # no-await window) and each KEY has a single writer — its tenant's
    # own task; cross-key interference is limited to LRU eviction,
    # which only ever costs the evicted key a cold re-encode.  A
    # planner holds its EncodedState object across its solve await, so
    # a concurrent eviction drops only the cache's reference; the
    # owner's next put re-inserts and re-enforces the budget.
    "EncodeCache": frozenset({"_entries", "_ticks", "_clock",
                              "evictions", "demotions"}),
    # -- converge-cycle engine + continuous-rebalance controller
    # (PR 10; engine extracted to blance_tpu/control.py in ISSUE 13) ---------
    # The CycleEngine's control state is touched by the app-facing
    # sync surface (submit/stop_soon) and the engine task.  The
    # discipline: every mutation sits in one no-await window (the sync
    # helpers _take_pending/_set_idle and the subclass hooks), the
    # pending list is taken atomically with the wake-event clear, and
    # the in-flight supersede decision re-reads _pending after every
    # wake.  The supersede explorer scenario (analysis/schedule.py
    # supersede_mid_rebalance) drives the windows dynamically.
    "CycleEngine": frozenset({
        "_pending", "_wake", "_idle", "_stopping", "_task",
    }),
    # RebalanceController adds the cluster-specific state; the engine
    # attrs it still touches from its own methods (_pending in the
    # supersede window, _stopping in the converge loop) are listed
    # again so the lint models them at this class too.
    "RebalanceController": frozenset({
        "_pending", "_idle", "_inflight", "_stopping",
        "current", "_nodes", "_removing", "_failed",
        "failures", "degraded_reports", "warnings",
    }),
    # -- fleet of control loops (ISSUE 13, blance_tpu/fleetloop.py) ----------
    # FleetController's tenant registry is mutated only from the
    # driving task (add_tenant/forget_tenant, sync windows); the rollup
    # registry is sync-window by the same discipline, read by the
    # exposition snapshot path.
    "FleetController": frozenset({"_tenants"}),
    "FleetSloRollup": frozenset({"_trackers"}),
    # -- critical-path move scheduler (ISSUE 12) -----------------------------
    # The bound scheduler's state is read by the supplier task (select)
    # and mutated by mover tasks (on_batch marks progress,
    # on_quarantine rebuilds the whole schedule) plus the supplier's
    # wind-down (finish).  Discipline: every mutator is a plain sync
    # method — _build recomputes ranks/plan/last_remaining in ONE
    # no-await window, so select can never observe a half-rebuilt
    # schedule, and the (plan, last_remaining) pair is always a
    # consistent snapshot (the reschedule_on_quarantine explorer
    # scenario checks that dynamically).  SloTracker's incident fields
    # follow its existing single-window discipline.
    "_CriticalPathBound": frozenset({
        "_rank", "plan", "last_remaining", "_quarantined",
        "_t_last_exec", "_first_predicted", "_finished", "reschedules",
    }),
    # -- durability journal (blance_tpu/durability) --------------------------
    # The Journal is written from the controller's cycle task (genesis/
    # delta/cycle/plan/strip/quiesce/snapshot) AND from every mover
    # task (the batch observer hook) — under the fleet tier, from N
    # tenant loops at once through their TenantViews.  Discipline:
    # append() is the single funnel and is plain sync code with no
    # awaits, so each record's seq/segment/snapshot-cadence update is
    # one atomic window on the event loop.  The EpochFence's counter is
    # read on every append and bumped only by recover() (sync, before
    # any successor task starts).
    "Journal": frozenset({
        "_seq", "_records_in_seg", "records_since_snapshot", "_f",
        "segment",
    }),
    "EpochFence": frozenset({"_epoch"}),
}

# Container mutators: a call to one of these on a shared attribute is a
# write for RACE003 purposes.
_MUTATING_METHODS = frozenset({
    "append", "extend", "add", "update", "clear", "pop", "remove",
    "insert", "discard", "setdefault", "popleft", "appendleft",
})

# Spawn spellings that make a method a task entry point.
_SPAWN_NAMES = frozenset({"_spawn", "ensure_future", "create_task"})

_EXTERNAL_ROOT = "<external>"

_FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef]


def _attr_path(node: ast.AST) -> Optional[str]:
    """Dotted path of a ``self.a.b`` attribute chain ("a.b"), or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None


# -- linear execution-order event stream (RACE001/002) -----------------------


@dataclass
class _Event:
    kind: str  # "bind" | "use" | "write" | "await"
    time: int
    local: Optional[str] = None  # bind/use
    attr: Optional[str] = None  # bind/write: the shared attribute path
    line: int = 0
    uses_locals: frozenset[str] = frozenset()  # write: locals in RHS


class _EventWalker:
    """Flatten one async function body into execution-ordered events.

    Ordering rules that matter here: an ``Assign``'s value is evaluated
    before its targets bind; an ``Await``'s inner expression is
    evaluated before the suspension point; nested function defs are
    opaque (they execute elsewhere).  Branches are concatenated — the
    analysis is path-insensitive by design, which can only merge a
    branch's events in source order; good enough for the guard/RMW
    patterns this pass exists to catch, and fixtures pin the behavior.
    """

    def __init__(self, shared: frozenset[str]) -> None:
        self.shared = shared
        self.events: list[_Event] = []
        self._t = 0

    def _tick(self) -> int:
        self._t += 1
        return self._t

    def walk_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    # -- statements --------------------------------------------------------

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # analyzed as their own scopes
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            for target in node.targets:
                self._bind_target(target, node.value, node.lineno)
            return
        if isinstance(node, ast.AugAssign):
            # self.x += <rhs>: CPython loads self.x BEFORE evaluating
            # the RHS, so `self.x += await f()` reads the attribute,
            # suspends, then writes it back — the torn RMW in one
            # statement.  Model the target read as a synthetic binding
            # so the write-after-await check sees the window.
            target: ast.expr = node.target
            while isinstance(target, ast.Subscript):
                target = target.value
            path = _attr_path(target)
            pseudo: Optional[str] = None
            if path is not None and path.split(".")[0] in self.shared:
                pseudo = f"<aug:{path}>"
                self.events.append(_Event(
                    kind="bind", time=self._tick(), local=pseudo,
                    attr=path, line=node.lineno))
            self._expr(node.value)
            if pseudo is not None and path is not None:
                used = frozenset(
                    n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name)) | {pseudo}
                self.events.append(_Event(
                    kind="write", time=self._tick(), attr=path,
                    line=node.lineno, uses_locals=used))
            else:
                self._write_target(node.target, node.value, node.lineno)
            return
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            self._expr(node.value)
            self._bind_target(node.target, node.value, node.lineno)
            return
        if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
            # Implicit suspension points: __anext__/__aenter__ awaits.
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child)
                elif isinstance(child, ast.withitem):
                    for sub in ast.iter_child_nodes(child):
                        if isinstance(sub, ast.expr):
                            self._expr(sub)
            self.events.append(_Event(kind="await", time=self._tick(),
                                      line=node.lineno))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._stmt(child)
            return
        # Compound statements: evaluate their tests/iterables, then walk
        # child statement lists in source order.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, (ast.ExceptHandler, ast.withitem)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.stmt):
                        self._stmt(sub)
                    elif isinstance(sub, ast.expr):
                        self._expr(sub)

    def _bind_target(self, target: ast.expr, value: ast.expr,
                     line: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, value, line)
            return
        if isinstance(target, ast.Name):
            attr = self._shared_attr(value)
            self.events.append(_Event(
                kind="bind", time=self._tick(), local=target.id,
                attr=attr, line=line))
            return
        self._write_target(target, value, line)

    def _write_target(self, target: ast.expr, value: ast.expr,
                      line: int) -> None:
        # self._shared[k] = v mutates the shared container just as
        # surely as self._shared = v replaces it.
        while isinstance(target, ast.Subscript):
            target = target.value
        path = _attr_path(target)
        if path is not None and path.split(".")[0] in self.shared:
            used = frozenset(
                n.id for n in ast.walk(value) if isinstance(n, ast.Name))
            self.events.append(_Event(
                kind="write", time=self._tick(), attr=path, line=line,
                uses_locals=used))

    def _shared_attr(self, value: ast.expr) -> Optional[str]:
        path = _attr_path(value)
        if path is not None and path.split(".")[0] in self.shared:
            return path
        return None

    # -- expressions (execution order: children first, await last) ---------

    def _expr(self, node: ast.expr) -> None:
        if isinstance(node, ast.Await):
            self._expr_children(node.value)
            self.events.append(_Event(kind="await", time=self._tick(),
                                      line=node.lineno))
            return
        self._expr_children(node)

    def _expr_children(self, node: ast.expr) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                # Nested awaits inside this expression: record in place.
                self.events.append(_Event(kind="await", time=self._tick(),
                                          line=sub.lineno))
            elif isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Load):
                self.events.append(_Event(
                    kind="use", time=self._tick(), local=sub.id,
                    line=sub.lineno))
            elif isinstance(sub, ast.NamedExpr) and \
                    isinstance(sub.target, ast.Name):
                attr = self._shared_attr(sub.value)
                self.events.append(_Event(
                    kind="bind", time=self._tick(), local=sub.target.id,
                    attr=attr, line=sub.lineno))


# -- per-class analysis ------------------------------------------------------


@dataclass
class _MutationSite:
    attr: str
    method: str  # enclosing method qualname (closures attributed up)
    line: int


@dataclass
class _ClassInfo:
    name: str
    shared: frozenset[str]
    methods: dict[str, _FuncDef] = field(default_factory=dict)
    calls: dict[str, set[str]] = field(default_factory=dict)  # m -> callees
    spawned: set[str] = field(default_factory=set)
    owns_spawns: bool = False
    mutations: list[_MutationSite] = field(default_factory=list)


def _iter_methods(cls: ast.ClassDef) -> Iterator[tuple[str, _FuncDef]]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node


def _collect_class(cls: ast.ClassDef,
                   shared: frozenset[str]) -> _ClassInfo:
    info = _ClassInfo(name=cls.name, shared=shared)
    for name, fn in _iter_methods(cls):
        info.methods[name] = fn
        # First pass: spawn sites.  A coroutine constructed as a spawn
        # argument (self._spawn(self.m(...))) runs as its OWN task — it
        # is a task root, not a call edge from the spawning method.
        spawn_args: set[int] = set()
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d is None or d.split(".")[-1] not in _SPAWN_NAMES:
                continue
            info.owns_spawns = True
            for arg in sub.args:
                if isinstance(arg, ast.Call):
                    spawn_args.add(id(arg))
                    ad = _dotted(arg.func)
                    if ad is not None and ad.startswith("self.") and \
                            "." not in ad[5:]:
                        info.spawned.add(ad[5:])
        callees: set[str] = set()
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or id(sub) in spawn_args:
                continue
            d = _dotted(sub.func)
            if d is not None and d.startswith("self.") and \
                    "." not in d[5:]:
                callees.add(d[5:])
        info.calls[name] = callees
        # Mutation sites (RACE003), closures attributed to the method.
        def unwrap(t: ast.expr) -> Optional[str]:
            # A subscript write/delete mutates the shared container.
            while isinstance(t, ast.Subscript):
                t = t.value
            return _attr_path(t)

        for sub in ast.walk(fn):
            path: Optional[str] = None
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.Delete)):
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, ast.Delete):
                    targets = [t for t in sub.targets
                               if isinstance(t, ast.Subscript)]
                else:
                    targets = [sub.target]
                for t in targets:
                    path = unwrap(t)
                    if path is not None and \
                            path.split(".")[0] in shared:
                        info.mutations.append(_MutationSite(
                            attr=path, method=name, line=sub.lineno))
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _MUTATING_METHODS:
                path = _attr_path(sub.func.value)
                if path is not None and path.split(".")[0] in shared:
                    info.mutations.append(_MutationSite(
                        attr=path, method=name, line=sub.lineno))
    return info


def _roots_per_method(info: _ClassInfo) -> dict[str, set[str]]:
    """Task roots (spawned methods + the external sync surface) that can
    reach each method through the intra-class call graph."""
    roots: dict[str, set[str]] = {m: set() for m in info.methods}

    def flood(root_label: str, start: str) -> None:
        seen: set[str] = set()
        frontier = [start]
        while frontier:
            m = frontier.pop()
            if m in seen or m not in roots:
                continue
            seen.add(m)
            roots[m].add(root_label)
            frontier.extend(info.calls.get(m, ()))

    for spawned in info.spawned:
        flood(spawned, spawned)
    # Everything is also callable from outside (the app's control
    # surface: stop/pause/resume and the constructor path) — but only
    # methods NOT exclusively internal matter; treating every method as
    # externally rooted would make every pair "two roots".  External
    # root = methods nobody in the class calls and nobody spawns
    # (entry-shaped), e.g. _start, stop, pause/resume.
    called_by_someone: set[str] = set()
    for callees in info.calls.values():
        called_by_someone |= callees
    for m in info.methods:
        if m not in called_by_someone and m not in info.spawned:
            flood(_EXTERNAL_ROOT, m)
    return roots


def _analyze_async_method(em: FindingEmitter, cls_name: str, qualname: str,
                          fn: ast.AsyncFunctionDef,
                          shared: frozenset[str]) -> None:
    """RACE001 + RACE002 over one async method, linear in events."""
    walker = _EventWalker(shared)
    walker.walk_body(fn.body)
    events = walker.events

    # Latest binding per local, in execution order.
    binding: dict[str, _Event] = {}
    await_times: list[int] = []
    race001: list[tuple[int, str]] = []  # (line, message)
    race002: list[tuple[int, str]] = []
    seen_002: set[tuple[str, int]] = set()
    seen_001: set[tuple[str, int]] = set()

    def awaits_between(t0: int, t1: int) -> bool:
        return any(t0 < t < t1 for t in await_times)

    for ev in events:
        if ev.kind == "await":
            await_times.append(ev.time)
        elif ev.kind == "bind":
            if ev.local is not None:
                if ev.attr is not None:
                    binding[ev.local] = ev
                else:
                    binding.pop(ev.local, None)  # rebound to non-shared
        elif ev.kind == "use":
            b = binding.get(ev.local or "")
            if b is None or b.attr is None:
                continue
            if awaits_between(b.time, ev.time):
                key = (b.local or "", b.line)
                if key not in seen_002:
                    seen_002.add(key)
                    race002.append((ev.line, (
                        f"stale guard: {b.local!r} was bound from shared "
                        f"{cls_name}.{b.attr} at line {b.line}, an await "
                        f"suspended the task in between, and the stale "
                        f"local is used here — another task (or the "
                        f"app's control surface) may have replaced the "
                        f"attribute inside the window; re-read "
                        f"self.{b.attr} after the await (revalidation "
                        f"loop) or serialize the writers")))
        elif ev.kind == "write":
            # RACE001: write derives from a local bound from the SAME
            # attribute before an intervening await.
            for local in ev.uses_locals:
                b = binding.get(local)
                if b is None or b.attr != ev.attr:
                    continue
                if awaits_between(b.time, ev.time):
                    key = (ev.attr or "", ev.line)
                    if key not in seen_001:
                        seen_001.add(key)
                        shown = ("its own pre-await value"
                                 if local.startswith("<aug:")
                                 else repr(local))
                        race001.append((ev.line, (
                            f"read-modify-write across an await: "
                            f"{cls_name}.{ev.attr} is written from "
                            f"{shown} (read at line {b.line}) with an "
                            f"await in between — a concurrent update "
                            f"inside the window is silently lost; "
                            f"re-read and write within one atomic "
                            f"window, or route through a single owner "
                            f"task")))

    # A torn RMW's stale read would also register as a stale-guard use
    # on the same line; report the sharper RACE001 alone there.
    rmw_lines = {line for line, _ in race001}
    for line, msg in race001:
        em.emit("RACE001", line, qualname, msg)
    for line, msg in race002:
        if line not in rmw_lines:
            em.emit("RACE002", line, qualname, msg)


def _analyze_race003(em: FindingEmitter, info: _ClassInfo) -> None:
    if not info.owns_spawns:
        # Only task-owning classes have task entry points; passive
        # shared structures (Chan, NodeHealth) are covered by RACE001/2
        # plus the explorer's dynamic invariants.
        return
    roots = _roots_per_method(info)
    by_attr: dict[str, list[_MutationSite]] = {}
    for site in info.mutations:
        by_attr.setdefault(site.attr, []).append(site)
    for attr, sites in sorted(by_attr.items()):
        attr_roots: set[str] = set()
        for site in sites:
            attr_roots |= roots.get(site.method, set())
        task_roots = attr_roots - {_EXTERNAL_ROOT}
        if len(attr_roots) < 2 or not task_roots:
            continue
        anchor = min(sites, key=lambda s: s.line)
        names = ", ".join(sorted(
            r if r != _EXTERNAL_ROOT else "the external sync surface"
            for r in attr_roots))
        em.emit(
            "RACE003", anchor.line, f"{info.name}.{anchor.method}",
            f"shared {info.name}.{attr} is mutated from "
            f"{len(attr_roots)} distinct task entry points ({names}) "
            f"with no serialization point the lint can see — the "
            f"interleaving of those mutations is scheduler-chosen; "
            f"either serialize them (single owner task / channel) or "
            f"baseline this with the discipline that makes it safe "
            f"(e.g. append-only, atomic single-window updates)")


# -- entry points ------------------------------------------------------------


def lint_source(
    src: str,
    path: str,
    repo_root: str,
    shared_state: Optional[dict[str, frozenset[str]]] = None,
) -> list[Finding]:
    model = SHARED_STATE if shared_state is None else shared_state
    em = FindingEmitter(path, repo_root)
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        em.emit("RACE000", e.lineno or 0, "",
                f"file does not parse: {e.msg}")
        return em.findings

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        shared = model.get(node.name)
        if shared is None:
            continue
        info = _collect_class(node, shared)
        for name, fn in info.methods.items():
            if isinstance(fn, ast.AsyncFunctionDef):
                _analyze_async_method(
                    em, node.name, f"{node.name}.{name}", fn, shared)
        _analyze_race003(em, info)
    em.findings.sort(key=lambda f: (f.line, f.rule))
    return em.findings


def lint_file(
    path: str,
    repo_root: str,
    shared_state: Optional[dict[str, frozenset[str]]] = None,
) -> list[Finding]:
    with open(path) as f:
        return lint_source(f.read(), path, repo_root,
                           shared_state=shared_state)
