"""Determinism lint: static taint analysis for the bit-identical-replay
contract.

The keystone dynamic guarantee — committed traces (sim event logs, fleet
logs, crash-storm journals, rendered exposition text) replay
byte-identically — is enforced by diffing artifacts, which finds a
nondeterminism bug long after the offending line merged.  This pass is
the static twin (TOAST's thesis, arXiv:2508.15010): every source of
replay nondeterminism becomes a build-failing finding the moment it is
written.

The pass builds the shared cross-module call graph
(:class:`._astutil.ModuleIndex`) rooted at the declarative
:data:`REPLAY_ROOTS` table — the converge-cycle engine, the
simulator/fleetsim/crashsim runners, the durability journal/recovery,
and the canonical log/exposition renderers — and walks the reachable
set (``self.method`` edges included; replay code is method-heavy):

- **DET001** wall-clock call (``time.time/monotonic/perf_counter``,
  ``datetime.now``, raw ``loop.time()``) outside a declared
  :data:`CLOCK_SEAMS` entry.  Replayed time must come from the injected
  clock (``Recorder.now`` / ``DeterministicLoop`` virtual time) or the
  single host perf seam (``utils.hostclock.perf_now``).
- **DET002** unseeded randomness: ``random`` module-level functions,
  ``random.Random()`` with no seed, ``numpy.random.*``, ``uuid.*``,
  ``os.urandom``, ``secrets.*``.  Seeded ``random.Random(seed)``
  construction is the sanctioned pattern.
- **DET003** unordered iteration flowing into a serialization sink: a
  ``set``/``frozenset``-provenance value passed to a
  :data:`SERIALIZED_SINKS` entry (journal append, ``canonical_*_text``,
  ``render_prometheus``, ``atomic_write_*``) without ``sorted()`` on the
  path.
- **DET004** ``json.dumps`` without ``sort_keys=True`` (package-wide:
  every dumps in this codebase feeds a canonical artifact, an HTTP
  payload or the CLI).  A pass-through ``sort_keys=sort_keys`` keyword
  is clean — the decision is the caller's.
- **DET005** ordering keyed on ``hash()`` / ``id()`` — ``sorted`` /
  ``.sort`` / ``min`` / ``max`` with a key that calls either — the
  PYTHONHASHSEED / allocator hazard.  Identity uses of ``id()`` outside
  ordering are fine.
- **DET006** ``os.environ`` / ``os.getenv`` read outside the declared
  :data:`CONFIG_KNOBS` table: an undeclared knob is ambient state a
  replay cannot pin.

Findings fold through ``analysis/baseline.toml`` exactly like
JIT/ASY/RACE rules.  The tables are reality-guarded by
``tests/test_analysis.py`` (every entry must resolve to a real symbol),
the same pattern as the race lint's ``SHARED_STATE``.
"""

from __future__ import annotations

import ast
from typing import Optional

from . import Finding
from ._astutil import FuncInfo, ModuleIndex, ModuleInfo
from ._astutil import dotted as _dotted

__all__ = ["DeterminismPass", "REPLAY_ROOTS", "CLOCK_SEAMS",
           "SERIALIZED_SINKS", "CONFIG_KNOBS"]


# -- the declarative tables --------------------------------------------------
#
# Dotted-prefix matching throughout: an entry covers the named symbol and
# everything nested under it (a module entry covers the whole module, a
# class entry every method).

#: Replay-rooted code: everything reachable from these must be
#: deterministic given (scenario, seed, journal).  fq prefix -> why.
REPLAY_ROOTS: dict[str, str] = {
    "blance_tpu.control":
        "CycleEngine: every control loop's debounce/converge machine",
    "blance_tpu.rebalance":
        "RebalanceController drives planning/orchestration under the "
        "injected clock; its event stream is journaled",
    "blance_tpu.fleetloop":
        "fleet controller: N tenants' cycles coalesced into shared "
        "dispatches; feeds the fleet log",
    "blance_tpu.plan.service":
        "shared plan service: admission windows and batch solves on the "
        "replayed event loop",
    "blance_tpu.orchestrate.orchestrator":
        "move orchestration: progress stream is asserted byte-stable "
        "across schedule explorations",
    "blance_tpu.durability":
        "journal encode/replay/recovery: the crash-replay artifact "
        "itself",
    "blance_tpu.testing.simulate":
        "scenario runner: produces the committed sim event logs",
    "blance_tpu.testing.fleetsim":
        "fleet scenario runner: produces the committed fleet logs",
    "blance_tpu.testing.crashsim":
        "crash-storm runner: produces the committed crash logs",
    "blance_tpu.testing.scenarios":
        "scenario builders: seed -> identical event list is the replay "
        "premise",
    "blance_tpu.obs.expo.render_prometheus":
        "canonical exposition text: diffed byte-for-byte in tests",
    "blance_tpu.utils.trace.PhaseTimer":
        "phase report shape is pinned by tests; timing must flow "
        "through the host perf seam",
}

#: Declared clock boundaries: the only places reachable-from-a-root code
#: may read a clock that is not replayed state.  fq prefix -> why.
CLOCK_SEAMS: dict[str, str] = {
    "blance_tpu.utils.hostclock":
        "THE host perf-clock seam: perf_now() wraps the injectable "
        "clock; host-phase timing is diagnostic, never replayed",
    "blance_tpu.plan.service.PlanService._admit_batch":
        "loop.time() reads the INJECTED event loop's clock for the "
        "admission deadline — virtual time under DeterministicLoop",
    "blance_tpu.testing.simulate._sim_main":
        "loop.time() is DeterministicLoop virtual time (the loop is "
        "constructed by run_scenario)",
    "blance_tpu.testing.fleetsim._fleet_main":
        "loop.time() is DeterministicLoop virtual time (the loop is "
        "constructed by run_fleet_scenario)",
    "blance_tpu.testing.crashsim._run_life":
        "loop.time() is DeterministicLoop virtual time (the loop is "
        "constructed by run_crash_scenario)",
}

#: Serialization sinks: what reaches these ends up in a canonical
#: artifact, so iteration order on the way in must be pinned.  Matching
#: is by dotted suffix (``journal.append`` also matches
#: ``self._journal.append``; leading underscores are ignored per
#: segment).  suffix -> what the sink writes.
SERIALIZED_SINKS: dict[str, str] = {
    "journal.append": "durability journal records (replayed on recovery)",
    "canonical_log_text": "committed sim event log",
    "canonical_fleet_log_text": "committed fleet event log",
    "crash_log_text": "committed crash-storm log",
    "render_prometheus": "canonical exposition text",
    "atomic_write_json": "persisted JSON artifact",
    "atomic_write_text": "persisted text artifact",
}

#: Declared environment knobs: the only functions reachable from a
#: replay root that may read ``os.environ``.  fq prefix -> the knob.
CONFIG_KNOBS: dict[str, str] = {
    "blance_tpu.utils.atomicio.fsync_enabled":
        "BLANCE_WAL_FSYNC: durability/latency trade-off, read per "
        "write on purpose so crash tests can flip it mid-run",
    "blance_tpu.ops._tiles.tile_env":
        "BLANCE_*_TILE_* tile-size overrides: compile-time tuning "
        "knobs, read at trace time only — never inside replayed state",
}


# -- rule constants ----------------------------------------------------------

_WALL_CLOCK = {
    "time.time": "wall-clock read",
    "time.time_ns": "wall-clock read",
    "time.monotonic": "wall-clock read",
    "time.monotonic_ns": "wall-clock read",
    "time.perf_counter": "host perf-clock read",
    "time.perf_counter_ns": "host perf-clock read",
    "datetime.datetime.now": "wall-clock read",
    "datetime.datetime.utcnow": "wall-clock read",
    "datetime.date.today": "wall-clock read",
}

_RANDOM_PREFIXES = {
    "random.": "module-level random shares global unseeded state",
    "numpy.random.": "numpy global PRNG is process state, not scenario "
                     "state",
    "uuid.": "uuid draws host entropy",
    "secrets.": "secrets draws host entropy",
    "os.urandom": "host entropy",
}

_ORDERING_FNS = {"sorted", "min", "max"}


def _suffix_matches(dotted_ref: str, entry: str) -> bool:
    """True when ``dotted_ref``'s trailing segments equal ``entry``'s
    (leading underscores stripped per segment, so ``self._journal.append``
    matches ``journal.append``)."""
    want = entry.split(".")
    got = dotted_ref.split(".")
    if len(got) < len(want):
        return False
    tail = got[len(got) - len(want):]
    return all(g.lstrip("_") == w for g, w in zip(tail, want))


class DeterminismPass:
    """Whole-program pass: index, root at REPLAY_ROOTS, walk, lint.

    The table keyword arguments exist for the fixture tests — the real
    CLI always runs the module-level tables."""

    def __init__(self, files: list[str], repo_root: str, *,
                 replay_roots: Optional[dict[str, str]] = None,
                 clock_seams: Optional[dict[str, str]] = None,
                 serialized_sinks: Optional[dict[str, str]] = None,
                 config_knobs: Optional[dict[str, str]] = None) -> None:
        self.index = ModuleIndex(files, repo_root)
        self.replay_roots = REPLAY_ROOTS if replay_roots is None \
            else replay_roots
        self.clock_seams = CLOCK_SEAMS if clock_seams is None \
            else clock_seams
        self.serialized_sinks = SERIALIZED_SINKS if serialized_sinks is None \
            else serialized_sinks
        self.config_knobs = CONFIG_KNOBS if config_knobs is None \
            else config_knobs
        self.findings: list[Finding] = []
        for rel, line, msg in self.index.parse_errors:
            self.findings.append(Finding(
                rule="DET000", path=rel, line=line, symbol="",
                message=f"file does not parse: {msg}"))

    # -- matching helpers ---------------------------------------------------

    @staticmethod
    def _prefix_entry(fq: str, table: dict[str, str]) -> Optional[str]:
        for key in table:
            if fq == key or fq.startswith(key + "."):
                return key
        return None

    def _sink_entry(self, dotted_ref: str) -> Optional[str]:
        for key in self.serialized_sinks:
            if _suffix_matches(dotted_ref, key):
                return key
        return None

    def _roots(self) -> list[FuncInfo]:
        return [fn for mi in self.index.modules.values()
                for fn in mi.functions.values()
                if self._prefix_entry(fn.fq, self.replay_roots) is not None]

    # -- driver -------------------------------------------------------------

    def run(self) -> list[Finding]:
        reached = self.index.reachable(self._roots(), self_edges=True)
        for fn in reached:
            self._lint_function(fn)
        self._lint_json_dumps()
        return self.findings

    def _emit(self, rule: str, path: str, line: int, symbol: str,
              message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=path, line=line, symbol=symbol,
            message=message))

    # -- per-function rules (replay-reachable set) --------------------------

    def _lint_function(self, fn: FuncInfo) -> None:
        mi = self.index.modules[fn.module]
        in_clock_seam = self._prefix_entry(fn.fq, self.clock_seams) \
            is not None
        in_knob = self._prefix_entry(fn.fq, self.config_knobs) is not None
        provenance = self._set_provenance(fn)

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and not in_knob:
                ref = _dotted(node.value)
                if ref is not None and \
                        self.index.resolve(mi, ref) == "os.environ":
                    self._emit(
                        "DET006", fn.path, node.lineno, fn.qualname,
                        "os.environ read in replay-rooted code outside "
                        "the declared CONFIG_KNOBS table — an undeclared "
                        "knob is ambient state a replay cannot pin")
            if not isinstance(node, ast.Call):
                continue
            ref = _dotted(node.func)
            fq = self.index.resolve(mi, ref) if ref is not None else None

            if fq is not None:
                if not in_clock_seam:
                    self._det001(fn, node, ref or "", fq)
                self._det002(fn, node, fq)
                if not in_knob and fq in ("os.getenv", "os.environ.get"):
                    self._emit(
                        "DET006", fn.path, node.lineno, fn.qualname,
                        f"{fq} read in replay-rooted code outside the "
                        f"declared CONFIG_KNOBS table — an undeclared "
                        f"knob is ambient state a replay cannot pin")

            self._det005(fn, node)
            if ref is not None:
                sink = self._sink_entry(ref)
                if sink is not None:
                    self._det003(fn, node, sink, provenance)

    def _det001(self, fn: FuncInfo, node: ast.Call, ref: str,
                fq: str) -> None:
        why = _WALL_CLOCK.get(fq)
        segs = ref.split(".")
        is_loop_time = len(segs) >= 2 and segs[-1] == "time" and \
            segs[-2].lstrip("_") == "loop"
        if why is None and not is_loop_time:
            return
        what = f"raw loop.time() ({ref})" if why is None else f"{fq}: {why}"
        self._emit(
            "DET001", fn.path, node.lineno, fn.qualname,
            f"{what} reached from a replay root outside the declared "
            f"CLOCK_SEAMS — replayed time must come from the injected "
            f"clock (Recorder.now / DeterministicLoop) or "
            f"utils.hostclock.perf_now")

    def _det002(self, fn: FuncInfo, node: ast.Call, fq: str) -> None:
        if fq == "random.Random":
            if not node.args and not node.keywords:
                self._emit(
                    "DET002", fn.path, node.lineno, fn.qualname,
                    "random.Random() without a seed in replay-rooted "
                    "code — pass an explicit scenario-derived seed")
            return  # seeded construction is the sanctioned pattern
        for prefix, why in _RANDOM_PREFIXES.items():
            hit = fq == prefix or (prefix.endswith(".") and
                                   fq.startswith(prefix))
            if hit:
                self._emit(
                    "DET002", fn.path, node.lineno, fn.qualname,
                    f"call to {fq} in replay-rooted code: {why}; draw "
                    f"from a seeded random.Random(seed) instead")
                return

    def _det003(self, fn: FuncInfo, call: ast.Call, sink: str,
                provenance: set[str]) -> None:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            exempt = self._names_under_sorted(arg)
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in provenance \
                        and sub.id not in exempt:
                    self._emit(
                        "DET003", fn.path, call.lineno, fn.qualname,
                        f"set-provenance value {sub.id!r} flows into "
                        f"serialization sink {sink!r} "
                        f"({self.serialized_sinks[sink]}) without "
                        f"sorted() on the path — set iteration order is "
                        f"not replay-stable")
                elif isinstance(sub, (ast.Set, ast.SetComp)) or (
                        isinstance(sub, ast.Call) and
                        isinstance(sub.func, ast.Name) and
                        sub.func.id in ("set", "frozenset")):
                    if id(sub) not in self._nodes_under_sorted(arg):
                        self._emit(
                            "DET003", fn.path, call.lineno, fn.qualname,
                            f"inline set expression flows into "
                            f"serialization sink {sink!r} "
                            f"({self.serialized_sinks[sink]}) without "
                            f"sorted() on the path — set iteration "
                            f"order is not replay-stable")

    def _det005(self, fn: FuncInfo, node: ast.Call) -> None:
        is_ordering = (isinstance(node.func, ast.Name) and
                       node.func.id in _ORDERING_FNS) or \
            (isinstance(node.func, ast.Attribute) and
             node.func.attr == "sort")
        if not is_ordering:
            return
        for kw in node.keywords:
            if kw.arg != "key":
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id in ("hash", "id"):
                    self._emit(
                        "DET005", fn.path, node.lineno, fn.qualname,
                        f"ordering keyed on {sub.func.id}(): "
                        f"{'PYTHONHASHSEED' if sub.func.id == 'hash' else 'allocator address'}"
                        f"-dependent order is not replay-stable — key on "
                        f"the value's own fields")
                    break

    # -- set-provenance tracking (intra-function, one propagation hop) ------

    def _set_provenance(self, fn: FuncInfo) -> set[str]:
        assigns: list[tuple[str, ast.expr]] = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                assigns.append((node.targets[0].id, node.value))
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name) and \
                    node.value is not None:
                assigns.append((node.target.id, node.value))
        tainted: set[str] = set()
        for _ in range(2):  # one extra round: x = set(); y = list(x)
            for name, value in assigns:
                if self._is_set_expr(value, tainted):
                    tainted.add(name)
                elif self._clears_provenance(value):
                    tainted.discard(name)
        return tainted

    @staticmethod
    def _is_set_expr(value: ast.expr, tainted: set[str]) -> bool:
        if isinstance(value, (ast.Set, ast.SetComp)):
            return True
        if isinstance(value, ast.Name):
            return value.id in tainted
        if isinstance(value, ast.Call):
            f = value.func
            if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
                return True
            # list(x)/tuple(x) of a tainted name stays unordered-derived.
            if isinstance(f, ast.Name) and f.id in ("list", "tuple") and \
                    value.args and isinstance(value.args[0], ast.Name) and \
                    value.args[0].id in tainted:
                return True
        if isinstance(value, ast.BinOp) and \
                isinstance(value.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            # set algebra on a tainted operand
            for side in (value.left, value.right):
                if isinstance(side, ast.Name) and side.id in tainted:
                    return True
        return False

    @staticmethod
    def _clears_provenance(value: ast.expr) -> bool:
        return isinstance(value, ast.Call) and \
            isinstance(value.func, ast.Name) and value.func.id == "sorted"

    @staticmethod
    def _names_under_sorted(arg: ast.expr) -> set[str]:
        out: set[str] = set()
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id == "sorted":
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Name):
                        out.add(inner.id)
        return out

    @staticmethod
    def _nodes_under_sorted(arg: ast.expr) -> set[int]:
        out: set[int] = set()
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id == "sorted":
                for inner in ast.walk(sub):
                    out.add(id(inner))
        return out

    # -- DET004: json.dumps hygiene (package-wide) --------------------------

    def _lint_json_dumps(self) -> None:
        for mi in self.index.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                ref = _dotted(node.func)
                if ref is None or \
                        self.index.resolve(mi, ref) != "json.dumps":
                    continue
                kwargs = {kw.arg for kw in node.keywords}
                if None in kwargs:
                    continue  # **kwargs: cannot prove either way
                sk = next((kw.value for kw in node.keywords
                           if kw.arg == "sort_keys"), None)
                bad = "sort_keys" not in kwargs or (
                    isinstance(sk, ast.Constant) and sk.value is False)
                if bad:
                    self._emit(
                        "DET004", mi.path, node.lineno,
                        self._enclosing(mi, node.lineno),
                        "json.dumps without sort_keys=True: dict order "
                        "is insertion order, so two code paths building "
                        "the same mapping serialize differently — every "
                        "dumps on a persisted/canonical path must pin "
                        "key order")

    @staticmethod
    def _enclosing(mi: ModuleInfo, lineno: int) -> str:
        best = ""
        best_span = None
        for fn in mi.functions.values():
            node = fn.node
            end = getattr(node, "end_lineno", None)
            start = getattr(node, "lineno", None)
            if start is None or end is None:
                continue
            if start <= lineno <= end:
                span = end - start
                if best_span is None or span < best_span:
                    best, best_span = fn.qualname, span
        return best
