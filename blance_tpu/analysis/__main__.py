"""CLI: ``python -m blance_tpu.analysis [--ci] [paths...]``.

Exit status is the contract CI consumes: 0 when every finding is either
fixed or pinned in analysis/baseline.toml, nonzero when any NEW finding
exists (or an analyzer itself crashed).  ``--ci`` is the full gate (AST
lints + eval_shape audit + the device retrace-budget check + the AOT
HBM-budget check) and additionally promotes stale baseline entries to
hard errors, so a fix that removes a finding must delete its
suppression in the same change; the default run skips the shape audit
and the retrace/membudget checks so the editor loop stays sub-second
and jax-import-free (``--shape-audit`` / ``--retrace`` /
``--membudget`` force them back on individually).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m blance_tpu.analysis",
        description="blance_tpu static contract checks "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the blance_tpu "
                         "package)")
    ap.add_argument("--ci", action="store_true",
                    help="the full CI gate: AST lints + the jax.eval_shape "
                         "contract audit")
    ap.add_argument("--shape-audit", action="store_true",
                    help="run the eval_shape audit without the rest of "
                         "the --ci strictness")
    ap.add_argument("--retrace", action="store_true",
                    help="run the device-side retrace-budget check "
                         "(analysis/retrace.py) without the rest of "
                         "the --ci strictness")
    ap.add_argument("--determinism", action="store_true",
                    help="run ONLY the replay-determinism pass "
                         "(analysis/determinism.py), still folded "
                         "through the baseline")
    ap.add_argument("--donation", action="store_true",
                    help="run ONLY the use-after-donation pass "
                         "(analysis/donation.py), still folded "
                         "through the baseline")
    ap.add_argument("--membudget", action="store_true",
                    help="run the AOT HBM-budget check "
                         "(analysis/membudget.py) without the rest of "
                         "the --ci strictness")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="allowlist file (default: "
                         "blance_tpu/analysis/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the allowlist")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON object)")
    args = ap.parse_args(argv)

    only_mode = args.determinism or args.donation
    shape = (args.ci or args.shape_audit) and not only_mode
    retrace = (args.ci or args.retrace) and not only_mode
    membudget = (args.ci or args.membudget) and not only_mode
    if shape or retrace or membudget:
        # The sharded contracts want a multi-device mesh; force 8 virtual
        # CPU devices BEFORE jax first imports (same trick as
        # tests/conftest.py).  No-op when jax is already in.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from . import run_all

    result = run_all(
        paths=args.paths or None,
        baseline_path=("/dev/null" if args.no_baseline else args.baseline),
        shape_audit=shape,
        retrace=retrace,
        membudget=membudget,
        determinism_only=args.determinism,
        donation_only=args.donation,
    )

    if args.determinism:
        # Only the determinism pass ran: JIT/ASY/RACE/DON pins are
        # unused by construction, not stale.
        result.unused_baseline = [
            e for e in result.unused_baseline if e.rule.startswith("DET")]
    if args.donation:
        # Only the donation pass ran: every other pass's pins are
        # unused by construction, not stale.
        result.unused_baseline = [
            e for e in result.unused_baseline if e.rule.startswith("DON")]

    # Stale pins are warnings in the editor loop but HARD ERRORS under
    # --ci: a fixed finding must delete its suppression in the same
    # change, or dead entries accumulate and mask the next real finding
    # that happens to match them.
    stale_fails = args.ci and bool(result.unused_baseline)
    failed = bool(result.new) or bool(result.errors) or stale_fails
    if args.json:
        print(json.dumps({
            "new": [f.__dict__ for f in result.new],
            "baselined": [
                {**f.__dict__, "reason": reason}
                for f, reason in result.baselined
            ],
            "unused_baseline": [e.render() for e in result.unused_baseline],
            "checked_files": result.checked_files,
            "shape_entries": result.shape_entries,
            "retrace_entries": result.retrace_entries,
            "membudget_entries": result.membudget_entries,
            "errors": result.errors,
            "pass": not failed,
        }, indent=2, sort_keys=True))
    else:
        for f in result.new:
            print(f.render())
        for e in result.errors:
            print(f"ERROR: {e}")
        for e in result.unused_baseline:
            prefix = "ERROR" if args.ci else "warning"
            print(f"{prefix}: stale baseline entry (matched nothing): "
                  f"{e.render()}"
                  + (" — delete it" if args.ci else ""))
        n_base = len(result.baselined)
        print(f"blance_tpu.analysis: {result.checked_files} files, "
              f"{result.shape_entries} shape contracts, "
              f"{result.retrace_entries} retrace budgets, "
              f"{result.membudget_entries} HBM budgets, "
              f"{len(result.new)} new finding(s), {n_base} baselined"
              + (" — FAIL" if failed else " — OK"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
