"""Retrace budgets: the jit-cache contract as a declarative CI table.

The PR-2 shape-bucketing guarantee — repeated solves against drifting
cluster sizes hit the jit cache instead of recompiling — was previously
enforced only by tests/conftest.py's per-MODULE recompile budgets.  This
module promotes it to a per-ENTRY-POINT contract: ``RETRACE_BUDGETS``
declares, for one canonical CPU workload (cold solve, warm repair,
bucketed plan, fleet cold+warm batch, sharded dispatch), the maximum
number of XLA compilations each owning entry point may trigger.  The
workload runs under :class:`blance_tpu.obs.device.CompileMonitor` with
the dispatch sites' :func:`~blance_tpu.obs.device.entry` attribution,
so the count per entry is exact — and a change that makes a solver
entry point retrace per call (a static becoming traced, a new dynamic
shape, a cache key that stopped matching) fails ``python -m
blance_tpu.analysis --ci`` with the entry named, instead of surfacing
as an unexplained slowdown three PRs later.

Budgets are ceilings for the workload run STANDALONE in a cold process;
a warm process (the full --ci run, the device-obs CLI) compiles
strictly less.  Recalibrate by running ``python -m
blance_tpu.obs.device_check --check`` and reading the per-entry counts it
prints on failure, then update the table — the same workflow as the
conftest fixture's ``BLANCE_RECOMPILE_CALIBRATE=1``.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only
    from . import Finding

__all__ = ["RETRACE_BUDGETS", "run_retrace_check"]

# Per-entry compile ceilings for run_retrace_check()'s workload,
# calibrated standalone on jax 0.4.37 / CPU (8 virtual devices) with
# ~50% headroom for jax-internal helper jits.  "other" absorbs eager-op
# and jax-internal programs that fire outside any dispatch site —
# deliberately generous, since its population varies across jax patch
# versions; the solver entries are the contract.
RETRACE_BUDGETS: dict[str, int] = {
    # Calibrated: 1 compile each (the workload dispatches each entry 4x
    # at one shape, so the jit cache absorbs calls 2..4; a per-call
    # retrace quadruples the count and blows the +1 headroom).
    "solve_dense.cold": 2,
    "solve_dense.carry": 2,
    "solve_dense.warm": 2,
    "solve_dense.bucketed": 2,
    # Sparse shortlist solve: the cold entry owns TWO programs (the
    # jitted shortlist builder + the converged sparse fixpoint), each
    # dispatched 4x at one (shape, K) — a per-call retrace more than
    # doubles the count.  The warm entry reuses the builder's cache
    # entry and compiles only the repair program.
    "sparse.cold": 3,
    "sparse.warm": 2,
    # The critical-path scheduler's rank sweep (orchestrate/sched/
    # ranks.py): one jitted program per [P, L] shape, dispatched 4x at
    # one shape in the workload.
    "sched.ranks": 2,
    "fleet.cold": 3,
    "fleet.warm": 3,
    # The shard_map dispatch legitimately compiles many sub-programs
    # (calibrated 18 on the 8-virtual-device host, both dispatches);
    # a per-dispatch retrace doubles it.
    "sharded.cold": 26,
    # Fused single-dispatch plan pipeline (plan/tensor.py): one program
    # per mode; four dispatches each in the workload, so a per-call
    # retrace quadruples the count.
    "pipeline.cold": 2,
    "pipeline.warm": 2,
    # The sharded pipeline dispatch is memoized + jitted per (mesh,
    # statics) (parallel/sharded._pipeline_sharded_fn), so repeat
    # dispatches compile NOTHING: calibrated 1 compile for the
    # workload's two cold dispatches, headroom for a warm program.
    "sharded.pipeline": 4,
    # jax-internal eager helper jits (asarray converts, carry scatters);
    # population varies across jax patch versions, so generous.
    "other": 48,
}


def _workload() -> None:
    """The canonical retrace workload: every budgeted entry point
    dispatched at least twice per shape, so a per-call retrace doubles
    its count and blows the budget.  Small shapes, CPU-friendly,
    deterministic."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..core.types import PlanOptions
    from ..plan.fleet import TenantProblem, solve_fleet
    from ..plan.tensor import (
        carry_from_assignment,
        solve_dense_converged,
        solve_dense_warm,
    )

    P, N, S, R = 48, 8, 2, 1
    rng = np.random.default_rng(7)
    prev = np.full((P, S, R), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    pw = np.ones(P, np.float32)
    nw = np.ones(N, np.float32)
    valid = np.ones(N, bool)
    stick = np.full((P, S), 1.5, np.float32)
    gids = np.stack([np.arange(N, dtype=np.int32),
                     np.arange(N, dtype=np.int32) // 4,
                     np.zeros(N, np.int32)])
    gv = np.ones((3, N), bool)
    constraints = (1, 1)
    rules = ((), ((2, 1),))
    dev = [jnp.asarray(a)
           for a in (prev, pw, nw, valid, stick, gids, gv)]

    # solve_dense.cold — four dispatches of one shape: every call after
    # the first must ride the jit cache, so a per-call retrace lands at
    # 4x the budgeted count, far past the +1 headroom.
    out = solve_dense_converged(*dev, constraints, rules, record=False)
    for _ in range(3):
        solve_dense_converged(*dev, constraints, rules, record=False)

    # solve_dense.carry + solve_dense.warm — seed a carry off the cold
    # fixpoint, repair a 1-partition delta, twice.  The carry is rebuilt
    # per attempt (it is consumed either way, by contract).
    dirty = np.zeros(P, bool)
    dirty[0] = True
    cur = out
    for _ in range(4):
        carry = carry_from_assignment(cur, dev[1], dev[2])
        res, _next_carry = solve_dense_warm(
            cur, *dev[1:], constraints, rules, dirty=dirty, carry=carry,
            record=False)
        if res is not None:
            cur = jnp.asarray(res)
    cfix = carry_from_assignment(cur, dev[1], dev[2])
    for _ in range(4):
        solve_dense_converged(cur, *dev[1:], constraints, rules,
                              record=False, carry_used=cfix.used)

    # solve_dense.bucketed — the pure-path entry with shape bucketing:
    # two cluster sizes inside one bucket must share one program.
    from .. import Partition, model
    from ..core.types import HierarchyRule
    from ..plan.tensor import plan_next_map_tpu

    m = model(primary=(0, 1), replica=(1, 1))
    for n_real in (17, 18, 17, 18):  # one shared bucket, two real sizes
        nodes = [f"n{i:03d}" for i in range(n_real)]
        hier = {n: f"r{i // 4}" for i, n in enumerate(nodes)}
        hier.update({f"r{i}": "z0" for i in range((n_real + 3) // 4)})
        opts = PlanOptions(shape_bucketing=True, node_hierarchy=hier,
                           hierarchy_rules={"replica": [HierarchyRule(2, 1)]})
        pmap = {str(i): Partition(str(i), {
            "primary": [nodes[i % n_real]],
            "replica": [nodes[(i + 1) % n_real]]}) for i in range(24)}
        plan_next_map_tpu(pmap, pmap, nodes, [], [], m, opts)

    # sched.ranks — the scheduler's device rank sweep: four dispatches
    # of one [P, L] cost matrix; the device threshold is forced to 0 so
    # the jitted path runs regardless of the move count.
    from ..orchestrate.sched.ranks import upward_ranks

    chain_costs = [[0.5, 1.0, 0.25]] * 16 + [[2.0, 0.5]] * 16
    for _ in range(4):
        upward_ranks(chain_costs, device_threshold=0)

    # sparse.cold + sparse.warm — the shortlist engine at one
    # (shape, K): four cold dispatches (builder + fixpoint compile once,
    # calls 2..4 ride the jit cache), then four warm one-sweep repairs
    # consuming a fresh carry each (the carry is single-use by
    # contract, like the dense warm loop above).
    from ..plan.tensor import solve_sparse, solve_sparse_warm

    s_out = solve_sparse(prev, pw, nw, valid, stick, gids, gv,
                         constraints, rules, k=4, record=False)
    for _ in range(3):
        solve_sparse(prev, pw, nw, valid, stick, gids, gv,
                     constraints, rules, k=4, record=False)
    s_cur = s_out
    for _ in range(4):
        s_carry = carry_from_assignment(
            jnp.asarray(s_cur), dev[1], dev[2])
        s_res, _nc = solve_sparse_warm(
            s_cur, pw, nw, valid, stick, gids, gv, constraints, rules,
            dirty=dirty, carry=s_carry, k=4, record=False)
        if s_res is not None:
            s_cur = s_res

    # fleet.cold + fleet.warm — two dispatches per mode, one class.
    def tenant(i, carry=None, dirty=None):
        t_rng = np.random.default_rng(100 + i)
        t_prev = np.full((P, S, R), -1, np.int32)
        t_prev[:, 0, 0] = t_rng.integers(0, N, P)
        t_prev[:, 1, 0] = (t_prev[:, 0, 0] + 1
                           + t_rng.integers(0, N - 1, P)) % N
        return TenantProblem(
            key=f"t{i}", prev=t_prev, partition_weights=pw,
            node_weights=nw, valid_node=valid, stickiness=stick,
            gids=gids, gid_valid=gv, constraints=constraints,
            rules=rules, carry=carry, dirty=dirty)

    cold = [tenant(i) for i in range(3)]
    res1 = solve_fleet(cold, record=False)
    for _ in range(3):
        solve_fleet(cold, record=False)
    warm = [TenantProblem(
        key=r.key, prev=r.assign, partition_weights=pw, node_weights=nw,
        valid_node=valid, stickiness=stick, gids=gids, gid_valid=gv,
        constraints=constraints, rules=rules, carry=r.carry, dirty=dirty)
        for r in res1]
    for _ in range(4):
        res_w = solve_fleet(warm, record=False)
        warm = [TenantProblem(
            key=r.key, prev=r.assign, partition_weights=pw,
            node_weights=nw, valid_node=valid, stickiness=stick,
            gids=gids, gid_valid=gv, constraints=constraints,
            rules=rules, carry=r.carry, dirty=dirty) for r in res_w]

    # pipeline.cold + pipeline.warm — the fused single-dispatch plan
    # pipeline through the session fast path (the real dispatch sites):
    # one cold dispatch, then four warm delta cycles riding the carry.
    # Every dispatch after the first per mode must hit the jit cache.
    from ..plan.session import PlannerSession

    s_nodes = [f"n{i:03d}" for i in range(N)]
    sess = PlannerSession(m, s_nodes, [str(i) for i in range(P)],
                          opts=PlanOptions())
    sess.replan_with_moves()
    sess.apply()
    for i in range(4):
        sess.remove_nodes([s_nodes[i]])
        sess.replan_with_moves()
        sess.apply()

    # sharded.cold / sharded.pipeline — tiny 2-shard mesh dispatches,
    # twice each (skipped on a single-device host; the budgets are then
    # trivially met).
    if len(jax.devices()) >= 2:
        from ..parallel.sharded import (
            make_mesh,
            solve_dense_sharded,
            solve_pipeline_sharded,
        )

        mesh = make_mesh(2)
        for _ in range(2):
            solve_dense_sharded(mesh, prev, pw, nw, valid, stick, gids,
                                gv, constraints, rules)
        for _ in range(2):
            solve_pipeline_sharded(mesh, prev, pw, nw, valid, stick,
                                   gids, gv, constraints, rules)


def run_retrace_check() -> tuple[list["Finding"], int]:
    """Run the workload under a counting monitor; one Finding per entry
    over budget (DEV001) or compiled-but-unbudgeted (DEV002).  Returns
    (findings, table size)."""
    from ..obs.device import CompileMonitor
    from . import Finding

    with CompileMonitor(emit=False) as mon:
        _workload()
    findings: list[Finding] = []
    counts = dict(mon.by_entry)
    path = "blance_tpu/analysis/retrace.py"
    for ent, count in sorted(counts.items()):
        if ent.endswith("+aot"):
            # Cost-analysis AOT compiles (obs/device.maybe_publish_cost)
            # are observation overhead, not retraces: with the
            # observatory's cost analysis armed during the check (the
            # device-obs CLI), they must not eat the live budgets.
            continue
        budget = RETRACE_BUDGETS.get(ent)
        if budget is None:
            findings.append(Finding(
                rule="DEV002", path=path, line=1, symbol=ent,
                message=f"entry point {ent!r} compiled {count}x during "
                        f"the retrace workload but has no budget in "
                        f"RETRACE_BUDGETS — add one (docs/"
                        f"STATIC_ANALYSIS.md, 'Retrace budgets')"))
        elif count > budget:
            findings.append(Finding(
                rule="DEV001", path=path, line=1, symbol=ent,
                message=f"entry point {ent!r} triggered {count} XLA "
                        f"compilations, over its budget of {budget}: a "
                        f"solver entry point is retracing more than the "
                        f"shape-bucketing/static-args contract allows "
                        f"(per-fn: {dict(sorted(mon.by_fn.items()))})"))
    return findings, len(RETRACE_BUDGETS)
