"""HBM budgets: the device-memory contract as a declarative CI table.

The dense-memory guard (``plan/tensor.check_dense_memory``) rejects a
solve whose projected score matrix cannot fit the device — but it only
models the ONE dominant [P, S, N] allocation, and nothing bounds what a
program actually allocates end to end (temps, the fused pipeline's
diff/pack stages, the fleet's stacked [B, ...] batches).  GSPMD's
memory-driven contracts (arXiv:2105.04663) argue for budgeting programs,
not formulas.  This module promotes the per-entry HBM ceilings from
DESIGN.md §4b prose into ``HBM_BUDGETS``: for every solver dispatch
entry (the ``obs/device.entry`` labels the retrace budgets already pin),
the maximum peak allocation the AOT-compiled program may report at each
declared bucket-shape class.  The check rides the PR-8 cost-analysis
path — ``jax.jit(...).lower(...).compile()`` on ``ShapeDtypeStruct``
operands, then ``memory_analysis()`` via ``obs/device._extract_cost`` —
so ZERO solver FLOPs execute and no concrete arrays are materialized.

Rules (all fold through analysis/baseline.toml like every other pass):

- MEM001 — an entry's compiled peak allocation exceeds its budget.
- MEM002 — table drift: a measured entry with no budget row, a budget
  row with no measurable builder, or a budget row for a mesh-exempt
  entry.
- MEM003 — a budget row the dense-memory guard would already have
  rejected at that class's (P, N): the runtime guard refuses such a
  solve before dispatch, so the row is dead — and letting it exist
  would let the two ceilings drift apart.

Shape classes: the ``smoke`` class runs in every ``--ci`` / CI static
tier; the ``north`` class (the BASELINE.json 100k x 10k north-star, for
the sparse-engine entries the dense guard permits there) is opt-in via
``BLANCE_MEMBUDGET_NORTH=1`` because its AOT compiles cost minutes of
CPU, not seconds.

Budgets are ceilings calibrated on the pinned jax (0.4.37) CPU backend
with ~25% headroom over the measured peak (argument + output + temp
bytes — the backend-independent allocation model XLA's
``memory_analysis()`` reports).  Recalibrate after an intentional
change with ``BLANCE_MEMBUDGET_CALIBRATE=1 python -m
blance_tpu.analysis --membudget``, which prints the measured-vs-budget
table, then update the row — the same workflow as the retrace budgets'
``BLANCE_RECOMPILE_CALIBRATE=1``.

The sharded entries (``sharded.*``, ``sparse.sharded.*``) are
deliberately exempt (``MESH_EXEMPT``): their per-device peak scales with
the mesh actually constructed, so a number measured on CI's 8 virtual
CPU devices would pin the wrong artifact for every real TPU topology.
Their memory story is the per-shard slice of the same budgeted bodies.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Callable, Optional

from .shape_audit import Dims

if TYPE_CHECKING:  # annotation-only
    from . import Finding

__all__ = [
    "HBM_BUDGETS",
    "SHAPE_CLASSES",
    "MESH_EXEMPT",
    "run_membudget_check",
    "measure_budget_table",
]

_PATH = "blance_tpu/analysis/membudget.py"

# -- shape classes -----------------------------------------------------------

# "smoke": a production-shaped but CPU-cheap class (every --ci run);
# "north": the BASELINE.json north-star 100k x 10k, sparse entries only
# (the dense guard rejects a 100k x 10k score matrix, and MEM003
# enforces that no dense row claims otherwise) — opt-in, see module
# docstring.
SHAPE_CLASSES: dict[str, Dims] = {
    "smoke": Dims(P=512, S=2, N=64, R=2, L=2),
    "north": Dims(P=100_000, S=2, N=10_000, R=2, L=2),
}

_NORTH_ENV = "BLANCE_MEMBUDGET_NORTH"
_CALIBRATE_ENV = "BLANCE_MEMBUDGET_CALIBRATE"


def _classes_to_run() -> list[str]:
    out = ["smoke"]
    if os.environ.get(_NORTH_ENV):
        out.append("north")
    return out


# -- builders ----------------------------------------------------------------

# entry label -> abstract-operand builder, reusing the shape-audit
# builders so the measured program IS the audited contract.  Keys are
# the live ``obs/device.entry`` labels (reality-guarded by
# tests/test_analysis.py against the dispatch sites' string literals).
_Builder = Callable[[Dims], "tuple[object, tuple, dict]"]


def _build_dense_bucketed(d: Dims):
    import numpy as np

    from . import shape_audit as sa

    db = sa._bucketed_dims(d)
    fn, args, kwargs = sa._build_converged(db)
    kwargs["p_real"] = sa._sds((), np.float32)
    return fn, args, kwargs


def _build_sparse_pipeline(d: Dims):
    from ..plan.tensor import _pipeline_sparse_cold_impl
    from . import shape_audit as sa

    return _pipeline_sparse_cold_impl, sa._solver_args(d, None), {
        "constraints": d.constraints, "rules": d.rules,
        "max_iterations": 4, "shortlist_k": sa._sparse_k(d),
        "sparse_impl": "xla", "favor_min_nodes": False}


def _builders() -> dict[str, _Builder]:
    # Imported lazily (pulls jax transitively) so the editor-loop lints
    # never pay for it; the shape-audit builders do the same internally.
    from . import shape_audit as sa

    return {
        "solve_dense.cold": lambda d: sa._build_converged(d),
        "solve_dense.carry": lambda d: sa._build_converged(d, carry=True),
        "solve_dense.bucketed": _build_dense_bucketed,
        "solve_dense.warm": lambda d: sa._build_warm(d),
        "sparse.cold": lambda d: sa._build_sparse_cold(d),
        "sparse.carry": lambda d: sa._build_sparse_cold(d, carry=True),
        "sparse.warm": lambda d: sa._build_sparse_warm(d),
        "sparse.pipeline": _build_sparse_pipeline,
        "pipeline.cold": lambda d: sa._build_pipeline_cold(d),
        "pipeline.warm": lambda d: sa._build_pipeline_warm(d),
        "fleet.cold": lambda d: sa._build_fleet_cold(d),
        "fleet.warm": lambda d: sa._build_fleet_warm(d),
        "sched.ranks": lambda d: sa._build_sched_ranks(d),
    }


# Entries whose peak allocation scales with the constructed mesh: a
# budget measured on CI's 8 virtual CPU devices would pin the wrong
# number for every real topology, so they are exempt BY NAME (a budget
# row for one of these is MEM002 table drift).  Their bodies are the
# same budgeted impls above, sliced per shard.
MESH_EXEMPT: frozenset[str] = frozenset({
    "sharded.cold",
    "sharded.warm",
    "sharded.pipeline",
    "sparse.sharded.cold",
    "sparse.sharded.warm",
})

# Entries whose program traces the dense [P, S, N] score matrix: MEM003
# cross-checks their budget rows against the runtime dense-memory
# guard's projection so the static table can never admit a class the
# guard rejects at dispatch.
_DENSE_ENTRIES: frozenset[str] = frozenset({
    "solve_dense.cold",
    "solve_dense.carry",
    "solve_dense.bucketed",
    "solve_dense.warm",
    "pipeline.cold",
    "pipeline.warm",
    "fleet.cold",
    "fleet.warm",
})

# The dense guard's reference ceiling for MEM003: the v5e 16 GiB HBM at
# plan/tensor._HBM_BUDGET_FRACTION, FIXED here rather than read from
# _device_hbm_bytes() so the static verdict cannot vary with the CI
# host (the runtime guard keeps its live device query).
_DENSE_GUARD_REF_BYTES = int(0.6 * 16 * 2**30)

# -- the table ---------------------------------------------------------------

# entry -> class -> peak-allocation ceiling in bytes.  Calibrated
# standalone (see module docstring); measured peaks on jax 0.4.37 CPU
# are noted inline so the next recalibration can see the drift.
HBM_BUDGETS: dict[str, dict[str, int]] = {
    # Dense converged fixpoint at smoke: ~355 KB measured (the
    # [P, S, N] f32 score matrix + operands + assign outputs).
    "solve_dense.cold": {"smoke": 450_000},
    "solve_dense.carry": {"smoke": 450_000},  # ~355 KB measured
    # The bucketed program pads (P, N) to bucket boundaries and adds the
    # traced p_real scalar: same peak as cold at this class (~355 KB).
    "solve_dense.bucketed": {"smoke": 450_000},
    # One-sweep repair: carry_used operand + masked sweep temps
    # (~344 KB measured).
    "solve_dense.warm": {"smoke": 430_000},
    # Sparse shortlist fixpoint: no dense matrix; [P, K] shortlist
    # gathers dominate (~142 KB measured at smoke).  North-star rows
    # are the point of the sparse engine — the only entries the dense
    # guard admits at 100k x 10k (~24.6 MB measured: linear in P, not
    # P*N).
    "sparse.cold": {"smoke": 180_000, "north": 31_000_000},
    "sparse.carry": {"smoke": 180_000, "north": 31_000_000},
    "sparse.warm": {"smoke": 165_000, "north": 30_000_000},
    # Fused sparse pipeline (shortlist -> solve -> diff -> pack in one
    # program): the diff op-list [P, 2*S*R] i32 triple rides on top
    # (~173 KB smoke / ~30.4 MB north measured).
    "sparse.pipeline": {"smoke": 220_000, "north": 38_000_000},
    # Fused dense pipeline: dense matrix + diff/pack stages (~396 KB /
    # ~387 KB measured).
    "pipeline.cold": {"smoke": 500_000},
    "pipeline.warm": {"smoke": 490_000},
    # Fleet batch programs: B=4 stacked bucket-class operands, vmapped
    # over the same converged/warm bodies (~3.09 MB / ~1.41 MB
    # measured).
    "fleet.cold": {"smoke": 3_900_000},
    "fleet.warm": {"smoke": 1_800_000},
    # Critical-path rank sweep: [P, 4] in / [P, 4] out (~33 KB
    # measured — XLA's CPU scan temps, not the 16 KB operand pair).
    "sched.ranks": {"smoke": 42_000},
}


# -- measurement -------------------------------------------------------------


def _measure_entry(entry: str, d: Dims, builder: _Builder) -> float:
    """AOT-compile one entry at one class and return the peak
    allocation ``memory_analysis()`` reports.  Zero FLOPs: operands are
    ShapeDtypeStructs end to end."""
    from functools import partial

    import jax

    from ..obs.device import _extract_cost

    fn, args, kwargs = builder(d)
    # Static (non-array) kwargs ride a partial closure, exactly like the
    # shape audit's eval_shape runner: a tuple/str static must stay a
    # concrete Python value at trace time.
    statics = {k: v for k, v in kwargs.items()
               if not isinstance(v, jax.ShapeDtypeStruct)}
    arrays = {k: v for k, v in kwargs.items()
              if isinstance(v, jax.ShapeDtypeStruct)}
    compiled = jax.jit(partial(fn, **statics)).lower(
        *args, **arrays).compile()
    cost = _extract_cost(compiled)
    return float(cost["peak_alloc_bytes"])


def measure_budget_table(
        classes: Optional[list[str]] = None) -> list[dict[str, object]]:
    """Measure every budgeted (entry, class) row; returns dicts with
    entry/class/measured/budget/ok — the artifact bench.py embeds as
    ``detail.membudget`` and the calibration workflow prints.  Rows
    whose AOT compile raises carry ``error`` instead of ``measured``."""
    builders = _builders()
    rows: list[dict[str, object]] = []
    for ent in sorted(HBM_BUDGETS):
        for klass in sorted(HBM_BUDGETS[ent]):
            if classes is not None and klass not in classes:
                continue
            builder = builders.get(ent)
            dims = SHAPE_CLASSES.get(klass)
            if builder is None or dims is None:
                continue  # run_membudget_check reports these as MEM002
            budget = HBM_BUDGETS[ent][klass]
            row: dict[str, object] = {"entry": ent, "class": klass,
                                      "budget": budget}
            try:
                measured = _measure_entry(ent, dims, builder)
            except Exception as e:
                first = (str(e).splitlines() or [""])[0][:200]
                row["error"] = f"{type(e).__name__}: {first}"
                row["ok"] = False
            else:
                row["measured"] = measured
                row["ok"] = measured <= budget
            rows.append(row)
    return rows


def run_membudget_check() -> tuple[list["Finding"], int]:
    """The --membudget / --ci pass: structural table checks (MEM002 /
    MEM003, host-only) plus AOT measurement of every budgeted row at
    the classes in play (MEM001).  Returns (findings, rows measured)."""
    from . import Finding

    findings: list[Finding] = []
    builders = _builders()
    classes = _classes_to_run()

    # MEM002: table drift, both directions, plus exemption violations.
    for ent in sorted(builders):
        if ent not in HBM_BUDGETS:
            findings.append(Finding(
                rule="MEM002", path=_PATH, line=1, symbol=ent,
                message=f"dispatch entry {ent!r} has a measurable "
                        f"builder but no row in HBM_BUDGETS — every "
                        f"solver entry carries an HBM ceiling (docs/"
                        f"STATIC_ANALYSIS.md, 'HBM budgets')"))
    for ent in sorted(HBM_BUDGETS):
        if ent in MESH_EXEMPT:
            findings.append(Finding(
                rule="MEM002", path=_PATH, line=1, symbol=ent,
                message=f"budget row for mesh-exempt entry {ent!r}: "
                        f"its peak scales with the constructed mesh, "
                        f"so a fixed ceiling pins the wrong artifact "
                        f"— remove the row (MESH_EXEMPT)"))
        elif ent not in builders:
            findings.append(Finding(
                rule="MEM002", path=_PATH, line=1, symbol=ent,
                message=f"budget row {ent!r} matches no measurable "
                        f"builder — a renamed/removed dispatch entry "
                        f"leaves a dead ceiling; update the row"))
        for klass in sorted(HBM_BUDGETS[ent]):
            if klass not in SHAPE_CLASSES:
                findings.append(Finding(
                    rule="MEM002", path=_PATH, line=1,
                    symbol=f"{ent}@{klass}",
                    message=f"budget row {ent!r} names unknown shape "
                            f"class {klass!r} (declared: "
                            f"{sorted(SHAPE_CLASSES)})"))

    # MEM003: a dense-engine row at a class the runtime dense-memory
    # guard would reject before dispatch — the row is dead and lets the
    # two ceilings drift.
    from ..plan.tensor import projected_score_bytes

    for ent in sorted(HBM_BUDGETS):
        if ent not in _DENSE_ENTRIES:
            continue
        for klass in sorted(HBM_BUDGETS[ent]):
            dims = SHAPE_CLASSES.get(klass)
            if dims is None:
                continue
            projected = projected_score_bytes(dims.P, dims.N)
            if projected > _DENSE_GUARD_REF_BYTES:
                findings.append(Finding(
                    rule="MEM003", path=_PATH, line=1,
                    symbol=f"{ent}@{klass}",
                    message=f"budget row {ent!r} at class {klass!r} "
                            f"({dims.P}x{dims.N}): check_dense_memory "
                            f"projects {projected} score-matrix bytes, "
                            f"over the {_DENSE_GUARD_REF_BYTES}-byte "
                            f"reference ceiling — the runtime guard "
                            f"rejects this solve before dispatch, so "
                            f"the row is dead; use the sparse engine "
                            f"entries at this class"))

    # MEM001: measure what the table budgets, at the classes in play.
    rows = measure_budget_table(classes)
    if os.environ.get(_CALIBRATE_ENV):
        print("membudget calibration (peak_alloc_bytes):")
        for row in rows:
            got = row.get("measured", row.get("error"))
            print(f"  {row['entry']:<24} {row['class']:<6} "
                  f"measured={got} budget={row['budget']} "
                  f"ok={row['ok']}")
    for row in rows:
        ent = str(row["entry"])
        klass = str(row["class"])
        if "error" in row:
            findings.append(Finding(
                rule="MEM001", path=_PATH, line=1,
                symbol=f"{ent}@{klass}",
                message=f"AOT compile for {ent!r} at class {klass!r} "
                        f"failed, so its budget is unverifiable: "
                        f"{row['error']}"))
        elif not row["ok"]:
            findings.append(Finding(
                rule="MEM001", path=_PATH, line=1,
                symbol=f"{ent}@{klass}",
                message=f"entry {ent!r} at class {klass!r} peaks at "
                        f"{row['measured']:.0f} bytes, over its "
                        f"{row['budget']}-byte HBM budget — recalibrate "
                        f"deliberately (BLANCE_MEMBUDGET_CALIBRATE=1) "
                        f"or shrink the program"))
    return findings, len(rows)
