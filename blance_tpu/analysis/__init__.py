"""Repo-specific static analysis: the contracts CI can actually enforce.

The solver's correctness rests on invariants no generic tool checks:
trace-time purity and stable tie-break bits in ``plan/tensor.py`` (the
warm-replan identity contract), cancellation- and waiter-safety in
``orchestrate/`` (the cancelled-waiter bug class), and the ``[P, S, N, R]``
shape conventions that otherwise live only in comments.  TOAST
(arXiv:2508.15010) makes the case that principled static analysis is the
scalable way to validate partitioning systems; GSPMD (arXiv:2105.04663)
leans on statically propagated shape/sharding contracts.  This package is
blance_tpu's own static layer, run as the ``static`` CI tier:

- :mod:`.jit_purity` — AST lint over functions reachable from
  ``jax.jit`` / ``shard_map`` trace roots: host nondeterminism, Python
  branching on traced values, device-sync coercions, captured-state
  mutation, malformed static args.
- :mod:`.asyncio_lint` — AST lint over the asyncio control plane:
  fire-and-forget tasks, blocking calls in ``async def``, silent broad
  exception swallows, un-deadlined app-callback awaits.
- :mod:`.race_lint` — await-atomicity race lint over the control
  plane's declared shared state: read-modify-writes spanning an
  ``await``, stale guard flags, multi-task mutation without a
  serialization point (RACE0xx).
- :mod:`.determinism` — taint-style replay-contract lint over code
  reachable from the declared ``REPLAY_ROOTS``: wall-clock reads
  outside ``CLOCK_SEAMS``, unseeded randomness, set-order flow into
  ``SERIALIZED_SINKS``, unsorted ``json.dumps``, hash/id ordering,
  undeclared env knobs (DET00x).
- :mod:`.donation` — use-after-donation liveness lint over every
  ``jax.jit(..., donate_argnames/argnums=...)`` dispatch site: reads of
  a donated operand after its dispatch (incl. aliases, attribute
  roots, packed tuples, returns), pre-dispatch escapes into
  longer-lived state, double dispatch without rebinding, post-dispatch
  host snapshots (DON00x).
- :mod:`.membudget` — the declarative per-entry HBM ceiling table
  (``HBM_BUDGETS``), checked against AOT ``memory_analysis()`` peak
  bytes at smoke shapes with zero FLOPs executed (MEM00x), so the
  device-memory contract rides the same baseline/CI machinery.
- :mod:`.schedule` — the dynamic companion: deterministic schedule
  exploration (``python -m blance_tpu.analysis.schedule``) replaying
  orchestrator scenarios under seeded and bounded-exhaustive
  interleavings against declared invariants, built on
  :mod:`blance_tpu.testing.sched`.
- :mod:`.shape_audit` — a declarative shape-contract table for the
  solver's public entry points, checked with ``jax.eval_shape`` across a
  (P, S, N, R) x bucketing x carry matrix: zero FLOPs, seconds of
  wall-clock, catches shape/dtype drift before any device sees it.
- :mod:`.retrace` — the device-side jit-cache contract: per-entry-point
  XLA compile budgets for a canonical workload, counted with
  ``obs/device.py``'s attributed CompileMonitor (DEV001 over budget,
  DEV002 unbudgeted entry).
- :mod:`.baseline` — the accepted-findings allowlist
  (``analysis/baseline.toml``): pre-existing findings are pinned with a
  reason; any NEW finding fails the build.

CLI: ``python -m blance_tpu.analysis [--ci]`` (see __main__.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

__all__ = [
    "Finding",
    "run_lints",
    "run_all",
    "PACKAGE_ROOT",
    "REPO_ROOT",
]

import os

# The package directory the lints walk by default, and the repo root the
# paths in findings/baseline entries are relative to.
PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PACKAGE_ROOT)


@dataclass(frozen=True)
class Finding:
    """One static-analysis finding.

    ``symbol`` is the enclosing function's qualname (empty at module
    level); baseline entries match on (rule, path, symbol) so accepted
    findings survive unrelated line drift, with ``line`` available for
    disambiguation when one symbol trips a rule twice.
    """

    rule: str  # e.g. "JIT001"
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str
    message: str

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym}: {self.message}"


@dataclass
class AnalysisResult:
    """Findings split by baseline status, plus bookkeeping for the CLI."""

    new: list[Finding]  # non-baselined findings (these fail the build)
    baselined: list[tuple[Finding, str]]  # (finding, reason) pairs
    # BaselineEntry objects that matched nothing (typed loosely: the
    # baseline module is imported lazily to keep the editor loop light)
    unused_baseline: list[Any]
    checked_files: int = 0
    shape_entries: int = 0
    retrace_entries: int = 0
    membudget_entries: int = 0
    # analyzer crashes (fatal)
    errors: list[str] = field(default_factory=list)


def _iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # The analysis package lints the product code, not itself
                # (its own fixtures would trip the rules by design), and
                # never descends into build trash.
                dirs[:] = [d for d in dirs if d not in
                           ("__pycache__", "_native_build", "analysis")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def run_lints(
        paths: Optional[list[str]] = None,
        determinism_only: bool = False,
        donation_only: bool = False) -> tuple[list[Finding], int]:
    """Run the AST passes over ``paths`` (default: the package).

    Returns (findings, checked_file_count).  Pure host work — safe to
    call from anywhere (no jax import).  ``determinism_only`` /
    ``donation_only`` are the ``--determinism`` / ``--donation`` CLI
    modes: just that one pass.
    """
    from .asyncio_lint import lint_file as asyncio_lint_file
    from .determinism import DeterminismPass
    from .donation import DonationPass
    from .jit_purity import JitPurityPass
    from .race_lint import lint_file as race_lint_file

    files = _iter_py_files(paths or [PACKAGE_ROOT])
    findings: list[Finding] = []
    run_every = not determinism_only and not donation_only
    # jit purity, determinism and donation need the whole module set up
    # front (cross-module call resolution); the asyncio and race lints
    # are per-file (the race lint's shared-state model keys on class
    # names, so it is inert outside the control plane by construction).
    if run_every:
        jit_pass = JitPurityPass(files, repo_root=REPO_ROOT)
        findings.extend(jit_pass.run())
    if not donation_only:
        findings.extend(DeterminismPass(files, repo_root=REPO_ROOT).run())
    if not determinism_only:
        findings.extend(DonationPass(files, repo_root=REPO_ROOT).run())
    if run_every:
        for f in files:
            findings.extend(asyncio_lint_file(f, repo_root=REPO_ROOT))
            findings.extend(race_lint_file(f, repo_root=REPO_ROOT))
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings, len(files)


def run_all(
    paths: Optional[list[str]] = None,
    baseline_path: Optional[str] = None,
    shape_audit: bool = True,
    retrace: bool = False,
    membudget: bool = False,
    determinism_only: bool = False,
    donation_only: bool = False,
) -> AnalysisResult:
    """Lints + (optionally) the eval_shape audit, the retrace-budget
    check and the HBM-budget check, folded through the baseline.  The
    CLI and the CI gate both call this."""
    from .baseline import Baseline

    findings, nfiles = run_lints(paths, determinism_only=determinism_only,
                                 donation_only=donation_only)
    shape_entries = 0
    retrace_entries = 0
    membudget_entries = 0
    errors: list[str] = []
    if shape_audit:
        from .shape_audit import run_shape_audit

        try:
            shape_findings, shape_entries = run_shape_audit()
            findings.extend(shape_findings)
        except Exception as e:  # an analyzer crash is itself a failure
            errors.append(f"shape audit crashed: {type(e).__name__}: {e}")
    if retrace:
        from .retrace import run_retrace_check

        try:
            retrace_findings, retrace_entries = run_retrace_check()
            findings.extend(retrace_findings)
        except Exception as e:
            errors.append(
                f"retrace check crashed: {type(e).__name__}: {e}")
    if membudget:
        from .membudget import run_membudget_check

        try:
            mb_findings, membudget_entries = run_membudget_check()
            findings.extend(mb_findings)
        except Exception as e:
            errors.append(
                f"membudget check crashed: {type(e).__name__}: {e}")

    if baseline_path is None:
        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "baseline.toml")
    baseline = Baseline.load(baseline_path)
    new, accepted = baseline.split(findings)
    return AnalysisResult(
        new=new,
        baselined=accepted,
        unused_baseline=baseline.unused(),
        checked_files=nfiles,
        shape_entries=shape_entries,
        retrace_entries=retrace_entries,
        membudget_entries=membudget_entries,
        errors=errors,
    )
