"""jit-purity lint: contracts for code that runs under a JAX trace.

Everything inside a ``jax.jit`` / ``shard_map`` trace must be pure and
shape-deterministic, or the warm-replan identity contract (plan/tensor.py:
bit-identical warm vs cold solves, pinned tie-break bits) silently breaks:
host nondeterminism bakes a one-off value into the compiled program,
Python branching on traced values either crashes at trace time or forks
the cache, and host coercions force device syncs mid-dispatch.

The pass builds a cross-module call graph rooted at every function handed
to ``jax.jit`` (decorator, ``jax.jit(f)``, ``partial(jax.jit, ...)``) or
to a ``shard_map``-shaped wrapper (including through ``partial`` aliases,
the idiom parallel/sharded.py uses), then walks the reachable set:

- JIT001 (all reached code): host nondeterminism — ``time.*``,
  ``random.*`` / ``numpy.random.*``, ``datetime.now``, ``os.urandom``,
  ``uuid.*``.  A traced call bakes ONE sample into the compiled program;
  every later call replays it.
- JIT002 (trace roots, where static args are declared): Python ``if`` /
  ``while`` branching directly on a traced parameter.  ``is None`` /
  ``is not None`` tests are exempt (argument *presence* is static).
- JIT003 (trace roots): ``float()`` / ``int()`` / ``bool()`` applied to a
  traced parameter — a forced device sync (and a trace-time error under
  jit).
- JIT004 (all reached code): mutation of captured state — ``global`` /
  ``nonlocal`` declarations, or mutating method calls
  (append/extend/update/...) on names not bound in the local scope.
  Traced mutations of captured Python state run ONCE, at trace time.
- JIT005 (jit call sites): static-arg hygiene — ``static_argnames`` /
  ``donate_argnames`` naming a parameter the wrapped function does not
  have (jit raises only when the name is actually passed), static
  parameters whose declared default is an unhashable literal, and
  ``donate_argnums`` indices that fall outside the wrapped function's
  positional parameters or land on a declared static (jax rejects both
  only at dispatch time, so the misdeclaration hides until a call site
  exercises it).

Helpers reached from a root get JIT001/JIT004 only: without the root's
``static_argnames`` there is no ground truth for which helper parameters
are traced, and guessing would drown the signal in false positives (the
analysis/baseline.toml workflow exists for the cases the pass cannot
prove).
"""

from __future__ import annotations

import ast
from typing import Optional

from . import Finding
from ._astutil import FuncInfo, ModuleIndex, ModuleInfo
from ._astutil import dotted as _dotted

__all__ = ["JitPurityPass"]

# fq-prefix -> why it is impure under a trace.
_NONDET_PREFIXES = {
    "time.time": "wall-clock read",
    "time.time_ns": "wall-clock read",
    "time.perf_counter": "wall-clock read",
    "time.monotonic": "wall-clock read",
    "time.sleep": "host sleep",
    "random.": "host PRNG (use jax.random with an explicit key)",
    "numpy.random.": "host PRNG (use jax.random with an explicit key)",
    "datetime.datetime.now": "wall-clock read",
    "datetime.date.today": "wall-clock read",
    "os.urandom": "host entropy",
    "uuid.": "host entropy",
}

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "add", "discard", "popitem",
}

_COERCIONS = {"float", "int", "bool"}

# Callables that wrap a function for tracing.  Matching is by resolved
# dotted suffix so both ``jax.experimental.shard_map.shard_map`` and a
# local ``_shard_map`` shim qualify.
_TRACE_WRAPPER_SUFFIXES = ("shard_map",)


def _literal_strings(node: ast.AST, constants: dict[str, object]
                     ) -> Optional[list[str]]:
    """Extract a tuple/list of string literals, following one level of
    module-constant indirection (the ``_WARM_STATICS`` idiom)."""
    if isinstance(node, ast.Name) and node.id in constants:
        val = constants[node.id]
        if isinstance(val, (tuple, list)) and \
                all(isinstance(x, str) for x in val):
            return list(val)
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    return None


def _literal_ints(node: ast.AST, constants: dict[str, object]
                  ) -> Optional[list[int]]:
    """Extract a tuple/list of int literals (or a single int), following
    one level of module-constant indirection — the ``donate_argnums``
    twin of :func:`_literal_strings`."""
    if isinstance(node, ast.Name) and node.id in constants:
        val = constants[node.id]
        if isinstance(val, int) and not isinstance(val, bool):
            return [val]
        if isinstance(val, (tuple, list)) and \
                all(isinstance(x, int) and not isinstance(x, bool)
                    for x in val):
            return [int(x) for x in val]
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[int] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, int) and \
                    not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and \
            not isinstance(node.value, bool):
        return [node.value]
    return None


class JitPurityPass:
    """Whole-program pass: build the index, find roots, walk, lint."""

    def __init__(self, files: list[str], repo_root: str) -> None:
        self.repo_root = repo_root
        self.index = ModuleIndex(files, repo_root)
        self.modules: dict[str, ModuleInfo] = self.index.modules
        self.findings: list[Finding] = []
        for rel, line, msg in self.index.parse_errors:
            self.findings.append(Finding(
                rule="JIT000", path=rel, line=line, symbol="",
                message=f"file does not parse: {msg}"))

    # -- root discovery -----------------------------------------------------

    def _is_jit_ref(self, mi: ModuleInfo, node: ast.AST) -> bool:
        dotted = _dotted(node)
        if dotted is None:
            return False
        fq = self.index.resolve(mi, dotted)
        return fq in ("jax.jit", "jax.pjit", "jax.jit.jit") or \
            fq.endswith(".jit") and fq.startswith("jax")

    def _is_trace_wrapper_ref(self, mi: ModuleInfo, node: ast.AST) -> bool:
        dotted = _dotted(node)
        if dotted is None:
            return False
        fq = self.index.resolve(mi, dotted)
        # lstrip("_"): version-portability shims are conventionally the
        # wrapped name with a leading underscore (parallel/sharded.py's
        # ``_shard_map``).
        leaf = fq.split(".")[-1].lstrip("_")
        return any(leaf == s for s in _TRACE_WRAPPER_SUFFIXES)

    def _mark_root(self, mi: ModuleInfo, func_ref: ast.AST,
                   statics: set[str], aliases: dict[str, str]) -> None:
        """func_ref names (possibly via a partial alias) a function."""
        target = None
        if isinstance(func_ref, ast.Call):
            # partial(f, ...) inline
            inner = self.index.partial_target(mi, func_ref)
            if inner is not None:
                target = inner
        else:
            dotted = _dotted(func_ref)
            if dotted is not None:
                if dotted in aliases:
                    dotted = aliases[dotted]
                target = self.index.lookup_function(mi, dotted)
        if target is not None:
            target.is_root = True
            target.statics |= statics

    def _jit_statics(self, mi: ModuleInfo, call: ast.Call,
                     wrapped) -> set[str]:
        """Parse static_argnames/donate_argnames off a jit(...) call,
        emitting JIT005 findings against the wrapped function.  Only
        static argnames are returned (donated args are still traced)."""
        statics: set[str] = set()
        for kw in call.keywords:
            if kw.arg not in ("static_argnames", "donate_argnames"):
                continue
            names = _literal_strings(kw.value, mi.constants)
            if names is None:
                continue
            if kw.arg == "static_argnames":
                statics |= set(names)
            if wrapped is not None:
                missing = [n for n in names if n not in wrapped.params]
                for n in missing:
                    self.findings.append(Finding(
                        rule="JIT005", path=mi.path, line=call.lineno,
                        symbol=wrapped.qualname,
                        message=f"{kw.arg} names {n!r} which is not a "
                                f"parameter of {wrapped.qualname}() — jit "
                                f"only raises when the name is passed, so "
                                f"this typo hides until a call site uses "
                                f"it"))
        self._jit_argnums(mi, call, wrapped, statics)
        return statics

    def _jit_argnums(self, mi: ModuleInfo, call: ast.Call, wrapped,
                     statics: set[str]) -> None:
        """donate_argnums hygiene: positional indices are resolved by jax
        only at dispatch time, so an out-of-range index or one landing on
        a declared static parameter (jax refuses to donate statics)
        hides until a call site exercises the donating path."""
        if wrapped is None:
            return
        args = wrapped.node.args
        pos = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            nums = _literal_ints(kw.value, mi.constants)
            if nums is None:
                continue
            for i in nums:
                if i < 0 or i >= len(pos):
                    self.findings.append(Finding(
                        rule="JIT005", path=mi.path, line=call.lineno,
                        symbol=wrapped.qualname,
                        message=f"donate_argnums index {i} is outside "
                                f"{wrapped.qualname}()'s "
                                f"{len(pos)} positional parameter(s) — "
                                f"jit only raises at dispatch time, so "
                                f"the bad index hides until the donating "
                                f"path runs"))
                elif pos[i] in statics:
                    self.findings.append(Finding(
                        rule="JIT005", path=mi.path, line=call.lineno,
                        symbol=wrapped.qualname,
                        message=f"donate_argnums index {i} names "
                                f"{pos[i]!r} which is also declared in "
                                f"static_argnames — a static argument "
                                f"has no device buffer to donate, and "
                                f"jax rejects the overlap only at "
                                f"dispatch time"))

    def _find_roots(self) -> None:
        for mi in self.modules.values():
            # partial aliases: var = partial(f, ...) / var = f, per module
            # (function-local aliases are collected per function below).
            aliases = self._collect_aliases(mi, mi.tree)
            # 1) decorators
            for fn in mi.functions.values():
                for dec in fn.node.decorator_list:
                    self._root_from_decorator(mi, fn, dec)
            # 2) any jit(...) / shard_map-ish call anywhere
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_jit_ref(mi, node.func):
                    wrapped = None
                    if node.args:
                        dotted = _dotted(node.args[0])
                        if dotted is not None:
                            dotted = aliases.get(dotted, dotted)
                            wrapped = self.index.lookup_function(mi, dotted)
                    statics = self._jit_statics(mi, node, wrapped)
                    if wrapped is not None:
                        wrapped.is_root = True
                        wrapped.statics |= statics
                elif isinstance(node.func, ast.Call):
                    # partial(jax.jit, static_argnames=...)(f)
                    inner = node.func
                    if isinstance(inner, ast.Call) and inner.args and \
                            self._is_jit_ref(mi, inner.args[0]) and \
                            self.index.resolve(
                                mi, _dotted(inner.func) or "") == \
                            "functools.partial":
                        wrapped = None
                        if node.args:
                            dotted = _dotted(node.args[0])
                            if dotted is not None:
                                dotted = aliases.get(dotted, dotted)
                                wrapped = self.index.lookup_function(mi, dotted)
                        statics = self._jit_statics(mi, inner, wrapped)
                        if wrapped is not None:
                            wrapped.is_root = True
                            wrapped.statics |= statics
                if self._is_trace_wrapper_ref(mi, node.func):
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        self._mark_root(mi, arg, set(), aliases)
                # partial(_shard_map, body, ...): treat as a wrapper call
                dotted = _dotted(node.func)
                if dotted is not None and \
                        self.index.resolve(mi, dotted) == "functools.partial" \
                        and node.args and \
                        self._is_trace_wrapper_ref(mi, node.args[0]):
                    for arg in list(node.args[1:]) + \
                            [kw.value for kw in node.keywords]:
                        self._mark_root(mi, arg, set(), aliases)

    def _root_from_decorator(self, mi: ModuleInfo, fn: FuncInfo,
                             dec: ast.AST) -> None:
        if self._is_jit_ref(mi, dec):  # @jax.jit
            fn.is_root = True
            return
        if isinstance(dec, ast.Call):
            if self._is_jit_ref(mi, dec.func):  # @jax.jit(...)
                fn.is_root = True
                fn.statics |= self._jit_statics(mi, dec, fn)
            elif dec.args and self._is_jit_ref(mi, dec.args[0]) and \
                    self.index.resolve(mi, _dotted(dec.func) or "") == \
                    "functools.partial":  # @partial(jax.jit, ...)
                fn.is_root = True
                fn.statics |= self._jit_statics(mi, dec, fn)

    def _collect_aliases(self, mi: ModuleInfo,
                         tree: ast.AST) -> dict[str, str]:
        """name -> dotted function reference, for ``x = partial(f, ...)``
        and ``x = f`` bindings."""
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            val = node.value
            if isinstance(val, ast.Call):
                info = self.index.partial_target(mi, val)
                if info is not None and info.module == mi.name:
                    aliases[tgt.id] = info.qualname
                elif info is not None:
                    aliases[tgt.id] = info.fq
            else:
                dotted = _dotted(val)
                if dotted is not None and \
                        self.index.lookup_function(mi, dotted) is not None:
                    aliases[tgt.id] = dotted
        return aliases

    # -- reachability -------------------------------------------------------

    def _reachable(self) -> list[FuncInfo]:
        roots = [fn for mi in self.modules.values()
                 for fn in mi.functions.values() if fn.is_root]
        return self.index.reachable(roots)

    # -- the lint -----------------------------------------------------------

    def run(self) -> list[Finding]:
        self._find_roots()
        for fn in self._reachable():
            self._lint_function(fn)
        return self.findings

    def _emit(self, fn: FuncInfo, rule: str, line: int,
              message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=fn.path, line=line, symbol=fn.qualname,
            message=message))

    def _local_names(self, fn: FuncInfo) -> set[str]:
        names = set(fn.params)
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for sub in ast.walk(t):
                        # Only true bindings: in ``x[k] = v`` / ``x.a = v``
                        # the base name is a Load — x stays captured.
                        if isinstance(sub, ast.Name) and \
                                isinstance(sub.ctx, ast.Store):
                            names.add(sub.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            elif isinstance(node, ast.comprehension):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        for sub in ast.walk(item.optional_vars):
                            if isinstance(sub, ast.Name):
                                names.add(sub.id)
        return names

    def _lint_function(self, fn: FuncInfo) -> None:
        mi = self.modules[fn.module]
        local = self._local_names(fn)
        traced = set(fn.params) - fn.statics - fn.defaulted - \
            {"self", "cls"}

        for node in ast.walk(fn.node):
            # JIT004: captured-state mutation
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                self._emit(fn, "JIT004", node.lineno,
                           f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                           f"mutation inside traced code runs once, at "
                           f"trace time — not per call")
                continue
            if not isinstance(node, ast.Call):
                continue

            dotted = _dotted(node.func)
            if dotted is not None:
                fq = self.index.resolve(mi, dotted)
                # JIT001: host nondeterminism
                for prefix, why in _NONDET_PREFIXES.items():
                    hit = fq == prefix or (prefix.endswith(".") and
                                           fq.startswith(prefix))
                    if hit:
                        self._emit(
                            fn, "JIT001", node.lineno,
                            f"call to {fq} under a jit trace: {why}; the "
                            f"traced value is baked into the compiled "
                            f"program and replayed on every call")
                        break

            # JIT004: mutating a captured name
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATING_METHODS and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id not in local:
                self._emit(
                    fn, "JIT004", node.lineno,
                    f"mutating call {node.func.value.id}."
                    f"{node.func.attr}() targets captured state — under "
                    f"a trace this runs once, at trace time")

            # JIT003: device-sync coercion of a traced param (roots only)
            if fn.is_root and isinstance(node.func, ast.Name) and \
                    node.func.id in _COERCIONS and len(node.args) == 1:
                arg = node.args[0]
                names = {n.id for n in ast.walk(arg)
                         if isinstance(n, ast.Name)}
                # int(x.shape[0]) coerces a STATIC fact about x, not x.
                under_attr = {
                    n.id
                    for sub in ast.walk(arg)
                    if isinstance(sub, ast.Attribute)
                    for n in ast.walk(sub.value)
                    if isinstance(n, ast.Name)
                }
                hits = (names - under_attr) & traced
                if hits:
                    self._emit(
                        fn, "JIT003", node.lineno,
                        f"{node.func.id}() on traced value "
                        f"{sorted(hits)[0]!r}: a forced device sync "
                        f"(TracerConversionError under jit)")

        # JIT002: branching on traced values (roots only)
        if fn.is_root:
            for node in ast.walk(fn.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                hits = self._traced_branch_names(node.test, traced)
                if hits:
                    self._emit(
                        fn, "JIT002", node.lineno,
                        f"Python {'if' if isinstance(node, ast.If) else 'while'} "
                        f"on traced parameter {sorted(hits)[0]!r}: trace-"
                        f"time branching forks the compile cache or "
                        f"raises TracerBoolConversionError; use lax.cond/"
                        f"jnp.where, or declare it static")

    def _traced_branch_names(self, test: ast.AST,
                             traced: set[str]) -> set[str]:
        """Direct traced-parameter references in a branch test, minus
        ``x is None`` / ``x is not None`` presence checks and attribute
        accesses (``x.shape`` etc. are static under tracing)."""
        exempt: set[str] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
                for sub in [node.left] + node.comparators:
                    if isinstance(sub, ast.Name):
                        exempt.add(sub.id)
            elif isinstance(node, ast.Attribute):
                # x.shape / x.ndim / x.dtype: static facts about x
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        exempt.add(sub.id)
            elif isinstance(node, ast.Call):
                fnode = node.func
                if isinstance(fnode, ast.Name) and \
                        fnode.id in ("isinstance", "len", "hasattr"):
                    for a in node.args:
                        for sub in ast.walk(a):
                            if isinstance(sub, ast.Name):
                                exempt.add(sub.id)
        names = {n.id for n in ast.walk(test)
                 if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
        return (names & traced) - exempt
