"""Abstract shape audit: the solver's [P, S, N, R] contracts, enforced.

The dense solver's shape conventions — assign[P, S, R] int32 with -1
empties, carry.used[S, N] float32, prices[N], the bucketed-pad and
shard_map layouts — live in docstrings and comments; nothing fails when
an entry point drifts.  This module pins them in a declarative contract
table checked with ``jax.eval_shape``: every public solver entry point is
traced abstractly across a (P, S, N, R) x bucketing x carry matrix, so
shape/dtype drift is caught in seconds with ZERO FLOPs and no device
(GSPMD's insight in reverse: if the shapes are static contracts, check
the contracts statically).

Covered entry points (acceptance contract):

- ``solve_dense``            — cold, carry-seeded, bucketed, bucketed+carry
- ``solve_dense_converged``  — via ``_solve_dense_converged_impl`` (the
  public wrapper adds host-side recording only), cold + carry
- ``solve_dense_warm``       — via ``_warm_repair`` (the public wrapper
  adds host gates around exactly this traced core)
- sharded solve              — ``solve_dense`` under ``shard_map`` with
  the partition axis sharded, the layout solve_dense_sharded builds
- fleet batch solves         — ``plan.fleet._fleet_cold_batch`` /
  ``_fleet_warm_batch``, the vmapped bucket-class programs the
  multi-tenant tier dispatches (stacked ``[B, ...]`` layouts)
- fused plan pipeline        — ``plan.tensor._pipeline_cold_impl`` /
  ``_pipeline_warm_impl`` (cold/carry/bucketed/warm: the one-dispatch
  solve→diff→pack programs), plus both under ``shard_map`` with specs
  derived from ``parallel/sharded``'s declarative layout tables
- sparse shortlist solve     — ``plan.tensor._solve_sparse_converged_impl``
  (cold + carry: (assign, sweeps, exhausted)), ``_warm_repair_sparse``
  ((assign, used, ok, exhausted)), the same body under ``shard_map``
  with specs from ``SOLVER_IN_LAYOUT + SPARSE_EXTRA_LAYOUT``, the
  shortlist builder (``core.shortlist.build_shortlist_core``: [P, K]
  int32, saturating K -> [P, N]), and a concrete host-side check of the
  per-row dense exhaustion fallback (fills flagged rows audit-clean)
- carry construction         — ``carry_from_assignment`` / ``_carry_used_jit``
- ``encode_problem`` / ``decode_assignment`` — dense-encoding dtypes and
  the decode round trip (tiny concrete problem; host-only, milliseconds)
- ``bucket_size`` / ``pad_to`` — the bucketing algebra (monotone, >= x,
  bounded overhead)

Failures surface as findings: SHP001 (shape/dtype mismatch), SHP002
(entry point raised under abstract evaluation), SHP003 (host-side
contract violation).  Add a new entry point by appending to CONTRACTS —
the table IS the documentation of the contract.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, NamedTuple

from . import Finding

__all__ = ["run_shape_audit", "CONTRACTS", "Dims"]

_PATH = "blance_tpu/analysis/shape_audit.py"


class Dims(NamedTuple):
    """One point in the audit matrix."""

    P: int
    S: int
    N: int
    R: int
    L: int = 1  # hierarchy levels (gids rows)

    @property
    def constraints(self) -> tuple[int, ...]:
        # Full-depth slots for every state; max(constraints) == R by
        # construction, the solver's own validity precondition.
        return (self.R,) * self.S

    @property
    def rules(self) -> tuple[tuple[tuple[int, int], ...], ...]:
        # One (include, exclude) rule on the last state when there is
        # more than one hierarchy level, else rule-free.
        if self.L < 2 or self.S < 2:
            return ((),) * self.S
        return ((),) * (self.S - 1) + (((1, 0),),)


@dataclass(frozen=True)
class ShapeContract:
    """One declarative entry-point contract.

    ``build(d)`` returns (callable, args, kwargs) with array arguments as
    ``jax.ShapeDtypeStruct``; ``expect(d)`` returns the expected output
    as a pytree of (shape, dtype) pairs.  The runner eval_shapes the
    callable and compares structurally.
    """

    entry: str  # reported entry-point name
    variant: str  # "cold" / "carry" / "bucketed" / ...
    build: Callable[..., object]
    expect: Callable[..., object]


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _solver_args(d: Dims, jnp):
    """The eight positional array args every solver entry shares."""
    import numpy as np

    return (
        _sds((d.P, d.S, d.R), np.int32),  # prev
        _sds((d.P,), np.float32),  # pweights
        _sds((d.N,), np.float32),  # nweights
        _sds((d.N,), np.bool_),  # valid
        _sds((d.P, d.S), np.float32),  # stickiness
        _sds((d.L, d.N), np.int32),  # gids
        _sds((d.L, d.N), np.bool_),  # gid_valid
    )


def _expect_assign(d: Dims):
    import numpy as np

    return ((d.P, d.S, d.R), np.int32)


def _expect_used(d: Dims):
    import numpy as np

    return ((d.S, d.N), np.float32)


# -- builders ---------------------------------------------------------------


def _build_solve_dense(d: Dims, carry: bool = False, bucketed: bool = False):
    import numpy as np

    import jax.numpy as jnp

    from ..plan.tensor import solve_dense

    kwargs = {"constraints": d.constraints, "rules": d.rules,
              "fused_score": "off"}
    if carry:
        kwargs["carry_used"] = _sds((d.S, d.N), np.float32)
    if bucketed:
        # Bucketed solves trace the REAL partition count as a scalar
        # operand so intra-bucket drift cannot retrigger compilation.
        kwargs["p_real"] = _sds((), np.float32)
    return solve_dense, _solver_args(d, jnp), kwargs


def _build_converged(d: Dims, carry: bool = False):
    import numpy as np

    import jax.numpy as jnp

    from ..plan.tensor import _solve_dense_converged_impl

    kwargs = {"constraints": d.constraints, "rules": d.rules,
              "fused_score": "off", "max_iterations": 4}
    if carry:
        kwargs["carry_used"] = _sds((d.S, d.N), np.float32)
    return _solve_dense_converged_impl, _solver_args(d, jnp), kwargs


def _build_warm(d: Dims):
    import numpy as np

    import jax.numpy as jnp

    from ..plan.tensor import _warm_repair

    args = _solver_args(d, jnp) + (
        _sds((d.P,), np.bool_),  # dirty
        _sds((d.S, d.N), np.float32),  # carry_used
    )
    return _warm_repair, args, {"constraints": d.constraints,
                                "rules": d.rules, "fused_score": "off"}


def _build_carry_used(d: Dims):
    import numpy as np

    import jax.numpy as jnp

    from ..plan.tensor import _carry_used_jit

    return _carry_used_jit, (
        _sds((d.P, d.S, d.R), np.int32),
        _sds((d.P,), np.float32),
        _sds((d.N,), np.float32),
    ), {}


def _build_sharded(d: Dims):
    """solve_dense under shard_map, the exact in/out layout
    solve_dense_sharded builds — in_specs derived from the SAME
    declarative layout table the runtime dispatch uses
    (parallel/sharded.SOLVER_IN_LAYOUT), so the audited layout cannot
    drift from the dispatched one."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    from ..parallel.sharded import (
        PARTITION_AXIS,
        SOLVER_IN_LAYOUT,
        _build_checked,
        _shard_map,
        layout_specs,
        make_mesh,
    )
    from ..plan.tensor import solve_dense

    n_dev = len(jax.devices())
    shards = n_dev if d.P % n_dev == 0 else 1
    mesh = make_mesh(shards)
    shard = PartitionSpec(PARTITION_AXIS)
    body = partial(solve_dense, constraints=d.constraints, rules=d.rules,
                   axis_name=PARTITION_AXIS, fused_score="off")
    sm = partial(_shard_map, body, mesh=mesh,
                 in_specs=layout_specs(SOLVER_IN_LAYOUT),
                 out_specs=shard)
    # Same replication-checker policy as solve_dense_sharded: pre-vma
    # JAX has no replication rule for the auction while_loop.
    has_vma = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")
    fn = _build_checked(sm, has_vma)
    return fn, _solver_args(d, jnp), {}


def _diff_len(d: Dims) -> int:
    """The device move-diff's padded op-list length (moves/batch.py)."""
    return 2 * d.S * d.R


def _expect_pipeline_cold(d: Dims):
    import numpy as np

    L = _diff_len(d)
    return (
        _expect_assign(d),  # assign
        ((), "int32"),  # sweeps
        ((d.N,), np.float32),  # prices
        _expect_used(d),  # used
        ((d.P, L), np.int32),  # d_nodes
        ((d.P, L), np.int32),  # d_states
        ((d.P, L), np.int32),  # d_ops
        _expect_assign(d),  # packed
        ((d.P, d.S), np.int32),  # counts
    )


def _expect_pipeline_warm(d: Dims):
    import numpy as np

    L = _diff_len(d)
    return (
        _expect_assign(d),
        ((d.N,), np.float32),  # prices
        _expect_used(d),
        ((), "bool"),  # ok
        ((d.P, L), np.int32),
        ((d.P, L), np.int32),
        ((d.P, L), np.int32),
        _expect_assign(d),
        ((d.P, d.S), np.int32),
    )


def _build_pipeline_cold(d: Dims, carry: bool = False,
                         bucketed: bool = False):
    import numpy as np

    from ..plan.tensor import _pipeline_cold_impl

    kwargs = {"constraints": d.constraints, "rules": d.rules,
              "fused_score": "off", "max_iterations": 4,
              "favor_min_nodes": False}
    if carry:
        kwargs["carry_used"] = _sds((d.S, d.N), np.float32)
    if bucketed:
        kwargs["p_real"] = _sds((), np.float32)
    return _pipeline_cold_impl, _solver_args(d, None), kwargs


def _build_pipeline_warm(d: Dims):
    import numpy as np

    from ..plan.tensor import _pipeline_warm_impl

    args = _solver_args(d, None) + (
        _sds((d.P,), np.bool_),  # dirty
        _sds((d.S, d.N), np.float32),  # carry_used
    )
    return _pipeline_warm_impl, args, {
        "constraints": d.constraints, "rules": d.rules,
        "fused_score": "off", "favor_min_nodes": False}


def _build_pipeline_sharded(d: Dims, warm: bool = False):
    """The fused pipeline under shard_map, in/out specs straight from
    the runtime's declarative layout tables — the exact dispatch
    solve_pipeline_sharded builds."""
    from functools import partial

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..parallel.sharded import (
        PARTITION_AXIS,
        PIPELINE_COLD_OUT_LAYOUT,
        PIPELINE_WARM_OUT_LAYOUT,
        SOLVER_IN_LAYOUT,
        WARM_EXTRA_LAYOUT,
        _build_checked,
        _shard_map,
        layout_specs,
        make_mesh,
    )
    from ..plan.tensor import _pipeline_cold_impl, _pipeline_warm_impl

    n_dev = len(jax.devices())
    shards = n_dev if d.P % n_dev == 0 else 1
    mesh = make_mesh(shards)
    if warm:
        body = partial(_pipeline_warm_impl, constraints=d.constraints,
                       rules=d.rules, axis_name=PARTITION_AXIS,
                       fused_score="off", favor_min_nodes=False)
        in_layout = SOLVER_IN_LAYOUT + WARM_EXTRA_LAYOUT
        out_layout = PIPELINE_WARM_OUT_LAYOUT
        extra = (_sds((d.P,), np.bool_), _sds((d.S, d.N), np.float32))
    else:
        body = partial(_pipeline_cold_impl, constraints=d.constraints,
                       rules=d.rules, axis_name=PARTITION_AXIS,
                       max_iterations=4, fused_score="off",
                       favor_min_nodes=False)
        in_layout = SOLVER_IN_LAYOUT
        out_layout = PIPELINE_COLD_OUT_LAYOUT
        extra = ()
    sm = partial(_shard_map, body, mesh=mesh,
                 in_specs=layout_specs(in_layout),
                 out_specs=layout_specs(out_layout))
    fn = _build_checked(sm, False)  # checker off: psum'd replicated outs
    return fn, _solver_args(d, jnp) + extra, {}


def _sparse_k(d: Dims) -> int:
    """A K < N candidate width for the sparse contracts (saturation is
    covered separately by the builder contract)."""
    return max(1, min(d.N - 1, d.R + 2))


def _build_sparse_cold(d: Dims, carry: bool = False):
    import numpy as np

    from ..plan.tensor import _solve_sparse_converged_impl

    args = _solver_args(d, None) + (
        _sds((d.P, _sparse_k(d)), np.int32),)  # shortlist
    kwargs = {"constraints": d.constraints, "rules": d.rules,
              "max_iterations": 4, "sparse_impl": "xla"}
    if carry:
        kwargs["carry_used"] = _sds((d.S, d.N), np.float32)
    return _solve_sparse_converged_impl, args, kwargs


def _expect_sparse_cold(d: Dims):
    import numpy as np

    return (_expect_assign(d), ((), "int32"), ((d.P,), np.bool_))


def _build_sparse_warm(d: Dims):
    import numpy as np

    from ..plan.tensor import _warm_repair_sparse

    args = _solver_args(d, None) + (
        _sds((d.P, _sparse_k(d)), np.int32),  # shortlist
        _sds((d.P,), np.bool_),  # dirty
        _sds((d.S, d.N), np.float32),  # carry_used
    )
    return _warm_repair_sparse, args, {
        "constraints": d.constraints, "rules": d.rules,
        "sparse_impl": "xla"}


def _expect_sparse_warm(d: Dims):
    import numpy as np

    return (_expect_assign(d), _expect_used(d), ((), "bool"),
            ((d.P,), np.bool_))


def _build_sparse_sharded(d: Dims):
    """The sparse converged solve under shard_map, in/out specs from
    the runtime's declarative layout tables (SPARSE_EXTRA_LAYOUT /
    SPARSE_COLD_OUT_LAYOUT) — the exact dispatch solve_sparse_sharded
    builds."""
    from functools import partial

    import numpy as np

    import jax

    from ..parallel.sharded import (
        PARTITION_AXIS,
        SOLVER_IN_LAYOUT,
        SPARSE_COLD_OUT_LAYOUT,
        SPARSE_EXTRA_LAYOUT,
        _build_checked,
        _shard_map,
        layout_specs,
        make_mesh,
    )
    from ..plan.tensor import _solve_sparse_converged_impl

    n_dev = len(jax.devices())
    shards = n_dev if d.P % n_dev == 0 else 1
    mesh = make_mesh(shards)
    body = partial(_solve_sparse_converged_impl,
                   constraints=d.constraints, rules=d.rules,
                   axis_name=PARTITION_AXIS, max_iterations=4,
                   sparse_impl="xla")
    sm = partial(_shard_map, body, mesh=mesh,
                 in_specs=layout_specs(SOLVER_IN_LAYOUT
                                       + SPARSE_EXTRA_LAYOUT),
                 out_specs=layout_specs(SPARSE_COLD_OUT_LAYOUT))
    has_vma = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")
    fn = _build_checked(sm, has_vma)
    return fn, _solver_args(d, None) + (
        _sds((d.P, _sparse_k(d)), np.int32),), {}


def _build_shortlist_builder(d: Dims, saturating: bool = False):
    import numpy as np

    from ..core.shortlist import build_shortlist_core

    k = d.N + 2 if saturating else _sparse_k(d)
    args = (
        _sds((d.P, d.S, d.R), np.int32),  # prev
        _sds((d.P,), np.float32),  # pweights
        _sds((d.N,), np.float32),  # nweights
        _sds((d.N,), np.bool_),  # valid
        _sds((d.L, d.N), np.int32),  # gids
        _sds((d.L, d.N), np.bool_),  # gid_valid
    )
    return build_shortlist_core, args, {
        "constraints": d.constraints, "rules": d.rules, "k": k}


def _expect_shortlist(d: Dims, saturating: bool = False):
    import numpy as np

    k = d.N if saturating else _sparse_k(d)
    return ((d.P, k), np.int32)


def _bucketed_dims(d: Dims) -> Dims:
    from ..core.encode import bucket_size

    return Dims(P=bucket_size(d.P), S=d.S, N=bucket_size(d.N), R=d.R,
                L=d.L)


_FLEET_B = 4  # batch width for the fleet contracts


def _fleet_args(d: Dims, b: int):
    """The stacked [B, ...] operands a fleet batch class solves."""
    import numpy as np

    return (
        _sds((b, d.P, d.S, d.R), np.int32),  # prev
        _sds((b, d.P), np.float32),  # pweights
        _sds((b, d.N), np.float32),  # nweights
        _sds((b, d.N), np.bool_),  # valid
        _sds((b, d.P, d.S), np.float32),  # stickiness
        _sds((b, d.L, d.N), np.int32),  # gids
        _sds((b, d.L, d.N), np.bool_),  # gid_valid
    )


def _build_fleet_cold(d: Dims, b: int = _FLEET_B):
    """plan.fleet._fleet_cold_batch: the vmapped converged fixpoint
    over one bucket class — (assign, sweeps, carry-used) per element."""
    import numpy as np

    from ..plan.fleet import _fleet_cold_batch

    db = _bucketed_dims(d)
    args = _fleet_args(db, b) + (_sds((b,), np.float32),)  # p_real
    return _fleet_cold_batch, args, {
        "constraints": db.constraints, "rules": db.rules,
        "max_iterations": 4, "fused_score": "off"}


def _build_fleet_warm(d: Dims, b: int = _FLEET_B):
    """plan.fleet._fleet_warm_batch: the vmapped one-sweep repair —
    (assign, new_used, accept flag) per element."""
    import numpy as np

    from ..plan.fleet import _fleet_warm_batch

    db = _bucketed_dims(d)
    args = _fleet_args(db, b) + (
        _sds((b, db.P), np.bool_),  # dirty
        _sds((b, db.S, db.N), np.float32),  # carry_used
        _sds((b,), np.float32),  # p_real
    )
    return _fleet_warm_batch, args, {
        "constraints": db.constraints, "rules": db.rules,
        "fused_score": "off"}


def _expect_fleet_cold(d: Dims, b: int = _FLEET_B):
    import numpy as np

    db = _bucketed_dims(d)
    return (((b, db.P, db.S, db.R), np.int32), ((b,), np.int32),
            ((b, db.S, db.N), np.float32))


def _expect_fleet_warm(d: Dims, b: int = _FLEET_B):
    import numpy as np

    db = _bucketed_dims(d)
    return (((b, db.P, db.S, db.R), np.int32),
            ((b, db.S, db.N), np.float32), ((b,), np.bool_))


# -- the table --------------------------------------------------------------

def _build_sched_ranks(d: Dims):
    import numpy as np

    from ..orchestrate.sched.ranks import rank_levels

    # The critical-path scheduler's device rank sweep: [P, L] per-move
    # costs (chains x levels, zero-padded) -> [P, L] upward ranks.  L
    # here is a representative 4-move chain depth (promote/add + del +
    # repair is 3; 4 covers a demote leg).
    return rank_levels, (_sds((d.P, 4), np.float32),), {}


def _expect_sched_ranks(d: Dims):
    import numpy as np

    return ((d.P, 4), np.float32)


# The audit matrix: small/typical/awkward sizes.  P values are multiples
# of 8 so the sharded variant exercises a real multi-shard mesh on the 8
# virtual CPU devices CI forces (a non-divisible P still audits, on a
# 1-shard mesh).
_MATRIX = (
    Dims(P=8, S=1, N=5, R=1),
    Dims(P=16, S=2, N=8, R=2, L=2),
    Dims(P=24, S=3, N=9, R=3, L=2),
)

CONTRACTS: tuple[ShapeContract, ...] = tuple(
    [
        ShapeContract(
            entry="solve_dense", variant=f"cold@{d.P}x{d.N}",
            build=(lambda d=d: _build_solve_dense(d)),
            expect=(lambda d=d: _expect_assign(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense", variant=f"carry@{d.P}x{d.N}",
            build=(lambda d=d: _build_solve_dense(d, carry=True)),
            expect=(lambda d=d: _expect_assign(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense", variant=f"bucketed@{d.P}x{d.N}",
            build=(lambda d=d: _build_solve_dense(
                _bucketed_dims(d), bucketed=True)),
            expect=(lambda d=d: _expect_assign(_bucketed_dims(d))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense", variant=f"bucketed+carry@{d.P}x{d.N}",
            build=(lambda d=d: _build_solve_dense(
                _bucketed_dims(d), carry=True, bucketed=True)),
            expect=(lambda d=d: _expect_assign(_bucketed_dims(d))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense_converged", variant=f"cold@{d.P}x{d.N}",
            build=(lambda d=d: _build_converged(d)),
            # (assign, executed-sweep count)
            expect=(lambda d=d: (_expect_assign(d), ((), "int32"))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense_converged", variant=f"carry@{d.P}x{d.N}",
            build=(lambda d=d: _build_converged(d, carry=True)),
            expect=(lambda d=d: (_expect_assign(d), ((), "int32"))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense_warm", variant=f"repair@{d.P}x{d.N}",
            build=(lambda d=d: _build_warm(d)),
            # (assign, new_used, accept flag)
            expect=(lambda d=d: (_expect_assign(d), _expect_used(d),
                                 ((), "bool"))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="carry_from_assignment", variant=f"used@{d.P}x{d.N}",
            build=(lambda d=d: _build_carry_used(d)),
            expect=(lambda d=d: _expect_used(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_dense_sharded", variant=f"1d@{d.P}x{d.N}",
            build=(lambda d=d: _build_sharded(d)),
            expect=(lambda d=d: _expect_assign(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="fleet_cold_batch",
            variant=f"B{_FLEET_B}@{d.P}x{d.N}",
            build=(lambda d=d: _build_fleet_cold(d)),
            expect=(lambda d=d: _expect_fleet_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="fleet_warm_batch",
            variant=f"B{_FLEET_B}@{d.P}x{d.N}",
            build=(lambda d=d: _build_fleet_warm(d)),
            expect=(lambda d=d: _expect_fleet_warm(d)))
        for d in _MATRIX
    ] + [
        # -- sparse shortlist solve (ISSUE 11) -------------------------
        ShapeContract(
            entry="solve_sparse", variant=f"cold@{d.P}x{d.N}",
            build=(lambda d=d: _build_sparse_cold(d)),
            expect=(lambda d=d: _expect_sparse_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_sparse", variant=f"carry@{d.P}x{d.N}",
            build=(lambda d=d: _build_sparse_cold(d, carry=True)),
            expect=(lambda d=d: _expect_sparse_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_sparse_warm", variant=f"repair@{d.P}x{d.N}",
            build=(lambda d=d: _build_sparse_warm(d)),
            expect=(lambda d=d: _expect_sparse_warm(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="solve_sparse_sharded", variant=f"1d@{d.P}x{d.N}",
            build=(lambda d=d: _build_sparse_sharded(d)),
            expect=(lambda d=d: _expect_sparse_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="build_shortlist", variant=f"topk@{d.P}x{d.N}",
            build=(lambda d=d: _build_shortlist_builder(d)),
            expect=(lambda d=d: _expect_shortlist(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="build_shortlist", variant=f"saturating@{d.P}x{d.N}",
            build=(lambda d=d: _build_shortlist_builder(
                d, saturating=True)),
            expect=(lambda d=d: _expect_shortlist(d, saturating=True)))
        for d in _MATRIX
    ] + [
        # -- fused single-dispatch plan pipeline (solve→diff→pack) -----
        ShapeContract(
            entry="plan_pipeline", variant=f"cold@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_cold(d)),
            expect=(lambda d=d: _expect_pipeline_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="plan_pipeline", variant=f"carry@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_cold(d, carry=True)),
            expect=(lambda d=d: _expect_pipeline_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="plan_pipeline", variant=f"bucketed@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_cold(
                _bucketed_dims(d), bucketed=True)),
            expect=(lambda d=d: _expect_pipeline_cold(_bucketed_dims(d))))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="plan_pipeline", variant=f"warm@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_warm(d)),
            expect=(lambda d=d: _expect_pipeline_warm(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="plan_pipeline_sharded", variant=f"cold@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_sharded(d)),
            expect=(lambda d=d: _expect_pipeline_cold(d)))
        for d in _MATRIX
    ] + [
        ShapeContract(
            entry="plan_pipeline_sharded", variant=f"warm@{d.P}x{d.N}",
            build=(lambda d=d: _build_pipeline_sharded(d, warm=True)),
            expect=(lambda d=d: _expect_pipeline_warm(d)))
        for d in _MATRIX
    ] + [
        # -- critical-path scheduler device rank kernel (ISSUE 12) -----
        ShapeContract(
            entry="sched_rank_levels", variant=f"chains@{d.P}",
            build=(lambda d=d: _build_sched_ranks(d)),
            expect=(lambda d=d: _expect_sched_ranks(d)))
        for d in _MATRIX
    ]
)


# -- runner -----------------------------------------------------------------


def _flatten_expect(exp):
    """(shape, dtype) | tuple thereof -> flat list, mirroring how
    eval_shape output tuples flatten."""
    if isinstance(exp, tuple) and len(exp) == 2 and \
            isinstance(exp[0], tuple) and \
            all(isinstance(x, int) for x in exp[0]):
        return [exp]
    out = []
    for e in exp:
        out.extend(_flatten_expect(e))
    return out


def _check_one(contract: ShapeContract) -> list[Finding]:
    import numpy as np

    import jax

    findings: list[Finding] = []
    label = f"{contract.entry}[{contract.variant}]"
    try:
        fn, args, kwargs = contract.build()
        # Static (non-array) kwargs ride a partial closure: eval_shape
        # abstracts every operand it is handed, and a tuple/str static
        # must stay a concrete Python value at trace time.
        from functools import partial

        statics = {k: v for k, v in kwargs.items()
                   if not isinstance(v, jax.ShapeDtypeStruct)}
        arrays = {k: v for k, v in kwargs.items()
                  if isinstance(v, jax.ShapeDtypeStruct)}
        out = jax.eval_shape(partial(fn, **statics), *args, **arrays)
    except Exception as e:
        first = (str(e).splitlines() or [""])[0][:200]
        findings.append(Finding(
            rule="SHP002", path=_PATH, line=0, symbol=label,
            message=f"entry point raised under jax.eval_shape "
                    f"({type(e).__name__}: {first})"))
        return findings

    got = jax.tree_util.tree_leaves(out)
    want = _flatten_expect(contract.expect())
    if len(got) != len(want):
        findings.append(Finding(
            rule="SHP001", path=_PATH, line=0, symbol=label,
            message=f"output arity drift: expected {len(want)} arrays, "
                    f"got {len(got)}"))
        return findings
    for i, (g, (shape, dtype)) in enumerate(zip(got, want)):
        if tuple(g.shape) != tuple(shape) or \
                np.dtype(g.dtype) != np.dtype(dtype):
            findings.append(Finding(
                rule="SHP001", path=_PATH, line=0, symbol=label,
                message=f"output #{i} drifted: expected "
                        f"{tuple(shape)} {np.dtype(dtype).name}, got "
                        f"{tuple(g.shape)} {np.dtype(g.dtype).name}"))
    return findings


def _check_encode_decode() -> list[Finding]:
    """Concrete (tiny) encode/decode round trip: dense dtypes + map
    shape.  Host-only, milliseconds."""
    import numpy as np

    from ..core.encode import decode_assignment, encode_problem
    from ..core.types import Partition, PartitionModelState, PlanOptions

    findings: list[Finding] = []
    label = "encode_problem/decode_assignment"
    try:
        model = {
            "primary": PartitionModelState(priority=0, constraints=1),
            "replica": PartitionModelState(priority=1, constraints=1),
        }
        nodes = ["a", "b", "c"]
        pmap = {
            "00": Partition("00", {"primary": ["a"], "replica": ["b"]}),
            "01": Partition("01", {"primary": ["b"], "replica": ["c"]}),
        }
        problem = encode_problem(pmap, pmap, nodes, None, model,
                                 PlanOptions())
        expect = {
            "prev": ((2, 2, 1), np.int32),
            "constraints": ((2,), np.int32),
            "partition_weights": ((2,), np.float32),
            "node_weights": ((3,), np.float32),
            "valid_node": ((3,), np.bool_),
            "stickiness": ((2, 2), np.float32),
            "gids": ((1, 3), np.int32),
            "gid_valid": ((1, 3), np.bool_),
        }
        for field_name, (shape, dtype) in expect.items():
            arr = getattr(problem, field_name)
            if tuple(arr.shape) != shape or \
                    np.dtype(arr.dtype) != np.dtype(dtype):
                findings.append(Finding(
                    rule="SHP001", path=_PATH, line=0, symbol=label,
                    message=f"DenseProblem.{field_name} drifted: "
                            f"expected {shape} {np.dtype(dtype).name}, "
                            f"got {tuple(arr.shape)} {arr.dtype}"))
        decoded, warns = decode_assignment(problem, problem.prev, pmap)
        if set(decoded) != set(pmap) or warns:
            findings.append(Finding(
                rule="SHP001", path=_PATH, line=0, symbol=label,
                message=f"decode(encode(m).prev) did not round-trip the "
                        f"partition set cleanly (warnings: {warns})"))
        elif decoded["00"].nodes_by_state != pmap["00"].nodes_by_state:
            findings.append(Finding(
                rule="SHP001", path=_PATH, line=0, symbol=label,
                message="decode(encode(m).prev) changed placements"))
    except Exception as e:
        first = (str(e).splitlines() or [""])[0][:200]
        findings.append(Finding(
            rule="SHP002", path=_PATH, line=0, symbol=label,
            message=f"encode/decode audit raised "
                    f"({type(e).__name__}: {first})"))
    return findings


def _check_bucketing_algebra() -> list[Finding]:
    """bucket_size/pad_to host contracts: result >= x, monotone,
    overhead bounded by 1/granularity, idempotent."""
    import numpy as np

    from ..core.encode import _BUCKET_GRANULARITY, bucket_size, pad_to

    findings: list[Finding] = []
    label = "bucket_size/pad_to"
    prev = 0
    for x in list(range(1, 200)) + [255, 256, 257, 1000, 1007, 4096,
                                    99_999, 100_001]:
        b = bucket_size(x)
        if b < x:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"bucket_size({x}) = {b} < x: padding would "
                        f"TRUNCATE the axis"))
            break
        if bucket_size(b) != b:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"bucket_size not idempotent at {x}: "
                        f"bucket_size({b}) = {bucket_size(b)}"))
            break
        if x > _BUCKET_GRANULARITY and \
                (b - x) * _BUCKET_GRANULARITY > b:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"bucket_size({x}) = {b}: padding overhead "
                        f"exceeds the 1/{_BUCKET_GRANULARITY} bound"))
            break
        if b < prev:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"bucket_size not monotone at {x}"))
            break
        prev = b
    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    padded = pad_to(arr, 1, 5, -1)
    if padded.shape != (2, 5) or not (padded[:, 3:] == -1).all() or \
            not (padded[:, :3] == arr).all():
        findings.append(Finding(
            rule="SHP003", path=_PATH, line=0, symbol=label,
            message="pad_to contract violated (shape/fill/prefix)"))
    if pad_to(arr, 1, 2, -1) is not arr:
        findings.append(Finding(
            rule="SHP003", path=_PATH, line=0, symbol=label,
            message="pad_to must be a no-op when already long enough"))
    return findings


def _check_sparse_fallback() -> list[Finding]:
    """Concrete host contract of the per-row dense exhaustion fallback:
    a row flagged exhausted (its shortlist was all removed nodes) must
    come back with every feasible slot filled, duplicate-free, off
    removed nodes, and untouched rows bit-unchanged.  Tiny problem,
    host + one small solve, milliseconds."""
    import numpy as np

    findings: list[Finding] = []
    label = "sparse_fallback"
    try:
        from ..plan.tensor import _sparse_fallback_rows

        P, S, R, N = 6, 2, 1, 8
        rng = np.random.default_rng(5)
        prev = np.full((P, S, R), -1, np.int32)
        prev[:, 0, 0] = rng.integers(0, N, P)
        prev[:, 1, 0] = (prev[:, 0, 0] + 1) % N
        assign = prev.copy()
        assign[0] = -1  # the exhausted row the sparse solve left empty
        valid = np.ones(N, bool)
        valid[prev[0, 0, 0]] = False
        gids = np.stack([np.arange(N, dtype=np.int32),
                         np.arange(N, dtype=np.int32) // 2,
                         np.zeros(N, np.int32)])
        out = _sparse_fallback_rows(
            assign, np.array([0]), prev, np.ones(P, np.float32),
            np.ones(N, np.float32), valid,
            np.full((P, S), 1.5, np.float32), gids,
            np.ones((3, N), bool), (1, 1), ((), ((2, 1),)))
        row = out[0]
        if (row < 0).any():
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"fallback left feasible slots empty: {row}"))
        held = row[row >= 0]
        if held.size and (~valid[held]).any():
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message="fallback placed a copy on a removed node"))
        if held.size != np.unique(held).size:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"fallback duplicated a node in one row: {row}"))
        if not np.array_equal(out[1:], assign[1:]):
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message="fallback mutated rows it was not asked to"))
    except Exception as e:
        first = (str(e).splitlines() or [""])[0][:200]
        findings.append(Finding(
            rule="SHP002", path=_PATH, line=0, symbol=label,
            message=f"sparse fallback audit raised "
                    f"({type(e).__name__}: {first})"))
    return findings


def _check_encode_residency() -> list[Finding]:
    """Concrete host contract of the encode-residency delta kernels
    (plan/resident.py, ISSUE 14): strip_prev_rows must equal
    strip-the-map-then-re-encode bit-exactly (new array, untouched rows
    byte-identical), and pack_slot_rows must be the decode pack —
    non-negative prefix in original slot order with exact counts.
    Tiny problem, host-only, milliseconds."""
    import numpy as np

    findings: list[Finding] = []
    label = "encode_residency"
    try:
        from ..core.encode import (
            encode_problem,
            pack_slot_rows,
            strip_prev_rows,
        )
        from ..core.types import Partition, PartitionModelState, PlanOptions

        model = {
            "primary": PartitionModelState(priority=0, constraints=1),
            "replica": PartitionModelState(priority=1, constraints=2),
        }
        nodes = [f"n{i}" for i in range(5)]
        pmap = {
            f"{i:02d}": Partition(f"{i:02d}", {
                "primary": [nodes[i % 5]],
                "replica": [nodes[(i + 1) % 5], nodes[(i + 2) % 5]]})
            for i in range(7)
        }
        problem = encode_problem(pmap, pmap, nodes, [], model,
                                 PlanOptions())
        dark = {"n1"}
        ids = np.array([1], np.int32)
        patched, dirty = strip_prev_rows(problem.prev, ids)
        stripped = {
            name: Partition(name, {
                s: [n for n in ns if n not in dark]
                for s, ns in p.nodes_by_state.items()})
            for name, p in pmap.items()}
        want = encode_problem(stripped, stripped, nodes, sorted(dark),
                              model, PlanOptions())
        if patched.shape != want.prev.shape or \
                not np.array_equal(patched, want.prev):
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message="strip_prev_rows != strip-map-then-re-encode"))
        if patched is problem.prev:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message="strip_prev_rows returned the input array — "
                        "identity memos would serve stale hits"))
        if dirty.shape != (problem.P,) or not dirty.any():
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message="strip_prev_rows dirty mask drifted"))
        rows = np.array([[[2, -1, 0], [-1, -1, 4]]], np.int32)
        packed, counts = pack_slot_rows(rows)
        if packed.tolist() != [[[2, 0, -1], [4, -1, -1]]] or \
                counts.tolist() != [[2, 1]]:
            findings.append(Finding(
                rule="SHP003", path=_PATH, line=0, symbol=label,
                message=f"pack_slot_rows drifted: {packed.tolist()} "
                        f"{counts.tolist()}"))
    except Exception as e:
        first = (str(e).splitlines() or [""])[0][:200]
        findings.append(Finding(
            rule="SHP002", path=_PATH, line=0, symbol=label,
            message=f"encode-residency audit raised "
                    f"({type(e).__name__}: {first})"))
    return findings


def run_shape_audit() -> tuple[list[Finding], int]:
    """Run the whole table.  Returns (findings, entries_checked)."""
    findings: list[Finding] = []
    for contract in CONTRACTS:
        findings.extend(_check_one(contract))
    findings.extend(_check_encode_decode())
    findings.extend(_check_bucketing_algebra())
    findings.extend(_check_sparse_fallback())
    findings.extend(_check_encode_residency())
    return findings, len(CONTRACTS) + 4
