"""Move calculus: diff two per-partition assignments into ordered state ops.

Reference: /root/reference/moves.go:17-136.  Pure functions; the orchestrator
consumes the op lists, and the batched on-device variant lives in
blance_tpu.moves.batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from ..core.setops import strings_intersect, strings_remove
from ..plan.greedy import flatten_nodes_by_state

__all__ = ["NodeStateOp", "calc_partition_moves"]


@dataclass(frozen=True)
class NodeStateOp:
    """One node's state transition for a partition (moves.go:17-21).

    op is one of "add", "del", "promote", "demote"; a del carries state "".
    """

    node: str
    state: str
    op: str


def _find_state_changes(
    beg_idx: int,
    end_idx: int,
    state: str,
    states: Sequence[str],
    beg: dict[str, list[str]],
    end: dict[str, list[str]],
) -> list[str]:
    """Nodes in end[state] that began in states[beg_idx:end_idx] — the
    promote/demote detector (moves.go:121-136)."""
    rv: list[str] = []
    for node in end.get(state, []):
        for i in range(beg_idx, end_idx):
            for n in beg.get(states[i], []):
                if n == node:
                    rv.append(node)
    return rv


def calc_partition_moves(
    states: Sequence[str],
    beg_nodes_by_state: dict[str, list[str]],
    end_nodes_by_state: dict[str, list[str]],
    favor_min_nodes: bool = False,
) -> list[NodeStateOp]:
    """Step-by-step moves from beg to end for one partition (moves.go:41-119).

    states must be ordered superior-first (e.g. ["primary", "replica"]).

    favor_min_nodes=False (availability-first): iterate states superior to
    inferior, emitting promote, demote, add, del per state — builds happen
    before teardowns so the partition stays served on multiple nodes.

    favor_min_nodes=True (min-copies-first): iterate inferior to superior,
    emitting del, demote, promote, add — the partition occupies the fewest
    nodes at any time, even if that leaves moments with no primary.

    A node gets at most one op per partition (the seen set, moves.go:49-58);
    a relocation is therefore two ops: add on the new node, del on the old.
    """
    moves: list[NodeStateOp] = []
    seen: set[str] = set()

    def add_moves(nodes: list[str], state: str, op: str) -> None:
        for node in nodes:
            if node not in seen:
                seen.add(node)
                moves.append(NodeStateOp(node, state, op))

    beg_nodes = flatten_nodes_by_state(beg_nodes_by_state)
    end_nodes = flatten_nodes_by_state(end_nodes_by_state)

    adds = strings_remove(end_nodes, beg_nodes)
    dels = strings_remove(beg_nodes, end_nodes)

    if not favor_min_nodes:
        for state_i, state in enumerate(states):
            add_moves(
                _find_state_changes(state_i + 1, len(states), state, states,
                                    beg_nodes_by_state, end_nodes_by_state),
                state, "promote")
            add_moves(
                _find_state_changes(0, state_i, state, states,
                                    beg_nodes_by_state, end_nodes_by_state),
                state, "demote")
            add_moves(
                strings_intersect(
                    strings_remove(end_nodes_by_state.get(state, []),
                                   beg_nodes_by_state.get(state, [])),
                    adds),
                state, "add")
            add_moves(
                strings_intersect(
                    strings_remove(beg_nodes_by_state.get(state, []),
                                   end_nodes_by_state.get(state, [])),
                    dels),
                "", "del")
    else:
        for state_i in range(len(states) - 1, -1, -1):
            state = states[state_i]
            add_moves(
                strings_intersect(
                    strings_remove(beg_nodes_by_state.get(state, []),
                                   end_nodes_by_state.get(state, [])),
                    dels),
                "", "del")
            add_moves(
                _find_state_changes(0, state_i, state, states,
                                    beg_nodes_by_state, end_nodes_by_state),
                state, "demote")
            add_moves(
                _find_state_changes(state_i + 1, len(states), state, states,
                                    beg_nodes_by_state, end_nodes_by_state),
                state, "promote")
            add_moves(
                strings_intersect(
                    strings_remove(end_nodes_by_state.get(state, []),
                                   beg_nodes_by_state.get(state, [])),
                    adds),
                state, "add")

    return moves
