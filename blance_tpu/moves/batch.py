"""Batched on-device move calculus: diff whole maps at once.

The host-side calc_partition_moves (moves/calc.py, reference moves.go:41-119)
is O(S^2 R^2) per partition with tiny constants — fine for one partition,
slow in Python for 100k.  This module computes the SAME ordered op lists for
every partition in one jitted computation over dense assignments:

Each node involved in a partition has exactly one (beg_state, end_state)
pair, which determines its op:
  beg absent          -> add     (at end state)
  end absent          -> del     (emitted at beg state's turn)
  beg_state >  end    -> promote (moving up; emitted at end state's turn)
  beg_state <  end    -> demote  (moving down; emitted at end state's turn)
and an ordering key replicating the reference's two emission orders
(availability-first: promote, demote, add, del per state superior-first;
min-copies-first: del, demote, promote, add per state inferior-first), with
ties following slot order within a state.

Op codes: 0=add 1=del 2=promote 3=demote; -1 = empty.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..core.types import PartitionMap, PartitionModel
from ..obs import get_recorder
from .calc import NodeStateOp

__all__ = ["diff_assignments", "calc_all_moves", "moves_from_arrays",
           "OP_NAMES"]

OP_NAMES = ["add", "del", "promote", "demote"]
_OP_ADD, _OP_DEL, _OP_PROMOTE, _OP_DEMOTE = 0, 1, 2, 3


@partial(jax.jit, static_argnames=("favor_min_nodes",))
def diff_assignments(
    beg: jnp.ndarray,  # [P, S, R] int32 node ids
    end: jnp.ndarray,  # [P, S, R] int32 node ids
    n: int = 0,  # unused, kept for API compatibility (NOT static: old
    #              callers passing varying node counts must not retrace)
    favor_min_nodes: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Diff two dense assignments into ordered per-partition op lists.

    Returns (nodes[P, L], states[P, L], ops[P, L]) with -1 padding at the
    tail; L = 2*S*R.  states[i] is -1 for del ops (the reference's "" state).
    """
    p, s, r = beg.shape
    L = 2 * s * r

    # State of each flat slot position (si-major), and each side's state
    # for every entry of the other side, by all-pairs compare over the
    # tiny SR axis (no [P, N] scratch, no node-count specialization).
    bflat = beg.reshape(p, s * r)
    eflat = end.reshape(p, s * r)
    pos_state = (jnp.arange(s * r, dtype=jnp.int32) // r)[None, :]

    def lookup(entries, other):
        """State holding each entry's node on the other side, -1 if absent
        (superior/lowest state wins on duplicates, like the reference's
        superior-first scans)."""
        match = (entries[:, :, None] == other[:, None, :]) & \
            (entries >= 0)[:, :, None]
        st = jnp.where(match, jnp.broadcast_to(pos_state[:, None, :],
                                               match.shape), s)
        found = jnp.min(st, axis=2)
        return jnp.where(found == s, -1, found).astype(jnp.int32)

    beg_state_of_end = lookup(eflat, bflat)  # [P, SR]
    end_state_of_beg = lookup(bflat, eflat)  # [P, SR]

    def op_and_key(b, e):
        """Op code + emission key for one (beg_state, end_state) pair."""
        is_add = (b < 0) & (e >= 0)
        is_del = (b >= 0) & (e < 0)
        is_pro = (b >= 0) & (e >= 0) & (b > e)
        is_dem = (b >= 0) & (e >= 0) & (b < e)
        op = jnp.where(is_add, _OP_ADD,
             jnp.where(is_del, _OP_DEL,
             jnp.where(is_pro, _OP_PROMOTE,
             jnp.where(is_dem, _OP_DEMOTE, -1))))
        # Emission state: the end state's turn, except del at the beg state.
        emit_state = jnp.where(is_del, b, e)
        if not favor_min_nodes:
            rank = jnp.where(is_pro, 0,
                   jnp.where(is_dem, 1,
                   jnp.where(is_add, 2, 3)))
            key = emit_state * 4 + rank
        else:
            rank = jnp.where(is_del, 0,
                   jnp.where(is_dem, 1,
                   jnp.where(is_pro, 2, 3)))
            key = (s - 1 - emit_state) * 4 + rank
        return op, key

    # Gather per-entry info from the end side (promote/demote/add) and the
    # beg side (del).  Each real node appears on exactly one side's slots
    # unless unchanged (same state -> no op).
    entries_node = []
    entries_state = []
    entries_op = []
    entries_key = []

    def add_entries(slots_flat, other_state_of_entry, side_is_end):
        for si in range(s):
            for ri in range(r):
                fi = si * r + ri
                node = slots_flat[:, fi]
                valid = node >= 0
                # An entry's own-side state is just its slot's state index.
                own = jnp.where(valid, jnp.int32(si), -1)
                other = jnp.where(valid, other_state_of_entry[:, fi], -1)
                b, e = (other, own) if side_is_end else (own, other)
                op, key = op_and_key(b, e)
                if side_is_end:
                    keep = valid & (op >= 0) & (op != _OP_DEL)
                else:
                    keep = valid & (op == _OP_DEL)
                # Slot order breaks ties within (state, rank).
                full_key = jnp.where(keep, key * (r + 1) + ri, jnp.int32(2**30))
                out_state = jnp.where(op == _OP_DEL, -1, e)
                entries_node.append(jnp.where(keep, node, -1))
                entries_state.append(jnp.where(keep, out_state, -1))
                entries_op.append(jnp.where(keep, op, -1))
                entries_key.append(full_key)

    add_entries(eflat, beg_state_of_end, True)
    add_entries(bflat, end_state_of_beg, False)

    nodes = jnp.stack(entries_node, axis=1)  # [P, 2*S*R]
    states = jnp.stack(entries_state, axis=1)
    ops = jnp.stack(entries_op, axis=1)
    keys = jnp.stack(entries_key, axis=1)

    order = jnp.argsort(keys, axis=1)
    take = jnp.take_along_axis
    return (take(nodes, order, 1)[:, :L],
            take(states, order, 1)[:, :L],
            take(ops, order, 1)[:, :L])


def moves_from_arrays(
    partition_names: "list[str]",
    state_names: "list[str]",
    node_names: "list[str]",
    d_nodes: np.ndarray,  # [P, L] int32 node ids, -1 padding
    d_states: np.ndarray,  # [P, L] int32 state ids, -1 = "" (del)
    d_ops: np.ndarray,  # [P, L] int32 op codes, -1 padding
) -> dict[str, list[NodeStateOp]]:
    """Materialize device diff tensors into per-partition ordered
    NodeStateOp lists — THE host step of the batched move calculus,
    shared by calc_all_moves and the fused plan pipeline
    (plan/tensor.plan_pipeline), so the two paths cannot drift.

    Valid entries sort to the front of each row (the device diff's
    invalid keys are 2^30), so row pi's moves are its first counts[pi]
    flat entries.  One pass over the ~total-op count instead of P x L
    Python iterations.  Returns a dict keyed by ``partition_names``
    order; records ``moves.total_ops`` on the ambient Recorder.
    """
    d_nodes = np.asarray(d_nodes)
    d_states = np.asarray(d_states)
    d_ops = np.asarray(d_ops)
    P = len(partition_names)
    mask = d_ops >= 0
    counts = mask.sum(axis=1)
    flat = mask.reshape(-1)
    node_arr = np.asarray(node_names, dtype=object)[
        d_nodes.reshape(-1)[flat]]
    state_arr = np.asarray(list(state_names) + [""], dtype=object)
    state_vals = state_arr[d_states.reshape(-1)[flat]]  # -1 wraps to ""
    op_arr = np.asarray(OP_NAMES, dtype=object)
    op_vals = op_arr[d_ops.reshape(-1)[flat]]
    flat_moves = [NodeStateOp(n_, s_, o_) for n_, s_, o_ in
                  zip(node_arr.tolist(), state_vals.tolist(),
                      op_vals.tolist())]
    offsets = np.zeros(P + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    out = {name: flat_moves[offsets[pi]:offsets[pi + 1]]
           for pi, name in enumerate(partition_names)}
    get_recorder().count("moves.total_ops", int(counts.sum()))
    return out


def calc_all_moves(
    beg_map: PartitionMap,
    end_map: PartitionMap,
    model: PartitionModel,
    favor_min_nodes: bool = False,
) -> dict[str, list[NodeStateOp]]:
    """Whole-map diff on device; returns per-partition ordered op lists.

    Produces the same ops as running calc_partition_moves per partition
    (cross-checked in tests); use this for 100k-partition rebalances where
    the host loop is the bottleneck.
    """
    if beg_map.keys() != end_map.keys():
        # The host path (orchestrate_moves) raises KeyError on a partition
        # missing from end_map; silently emitting del-everything here would
        # be a behavior divergence between the two modes.
        missing = beg_map.keys() ^ end_map.keys()
        raise KeyError(
            f"beg_map/end_map partition sets differ: {sorted(missing)[:5]}")

    rec = get_recorder()
    with rec.span("moves.calc_all_moves", partitions=len(beg_map)):
        return _calc_all_moves(beg_map, end_map, model, favor_min_nodes, rec)


def _calc_all_moves(
    beg_map: PartitionMap,
    end_map: PartitionMap,
    model: PartitionModel,
    favor_min_nodes: bool,
    rec,
) -> dict[str, list[NodeStateOp]]:
    from ..plan.greedy import sort_state_names, sorted_by_partition_name

    states = sort_state_names(model)
    state_index = {sname: i for i, sname in enumerate(states)}

    # Planner iteration order (zero-padded numeric names), so device-diff
    # op logs replay in the same partition order the planner used — not
    # plain lexicographic (cf. orchestrate.go:264-287 trace reproducibility).
    names = sorted_by_partition_name(beg_map.keys())
    nodes: list[str] = []
    node_index: dict[str, int] = {}

    def intern(node: str) -> int:
        if node not in node_index:
            node_index[node] = len(nodes)
            nodes.append(node)
        return node_index[node]

    with rec.span("moves.encode"):
        r_max = 1
        for m in (beg_map, end_map):
            for partition in m.values():
                for sname, ns in partition.nodes_by_state.items():
                    if sname in state_index:
                        r_max = max(r_max, len(ns))

        P, S = len(names), len(states)
        beg = np.full((P, S, r_max), -1, np.int32)
        end = np.full((P, S, r_max), -1, np.int32)
        # Partitions where a node appears in more than one state on either
        # side need the host diff: the reference's per-state scan + seen-set
        # has order-dependent behavior there that the dense
        # one-state-per-node encoding cannot express (moves.go:49-58).
        irregular: set[str] = set()
        for pi, name in enumerate(names):
            for arr, m in ((beg, beg_map), (end, end_map)):
                partition = m[name]  # key equality enforced above
                seen_nodes: set[str] = set()
                for sname, ns in partition.nodes_by_state.items():
                    si = state_index.get(sname)
                    if si is None:
                        continue
                    for ri, node in enumerate(ns[:r_max]):
                        if node in seen_nodes:
                            irregular.add(name)
                        seen_nodes.add(node)
                        arr[pi, si, ri] = intern(node)

    if P == 0 or not nodes:
        return {name: [] for name in names}

    rec.count("moves.diff_partitions", P)
    rec.count("moves.irregular_partitions", len(irregular))

    with rec.span("moves.device_diff", P=P, S=S, R=r_max):
        # Pad P to the next power of two so repeated diffs of
        # different-sized maps hit the jit cache (padding rows are all
        # -1 -> zero ops).
        p_pad = 1 << max(P - 1, 0).bit_length()
        if p_pad != P:
            pad = np.full((p_pad - P,) + beg.shape[1:], -1, np.int32)
            beg = np.concatenate([beg, pad])
            end = np.concatenate([end, pad])

        d_nodes, d_states, d_ops = diff_assignments(
            jnp.asarray(beg), jnp.asarray(end),
            favor_min_nodes=favor_min_nodes)
        d_nodes = np.asarray(d_nodes)[:P]
        d_states = np.asarray(d_states)[:P]
        d_ops = np.asarray(d_ops)[:P]

    from .calc import calc_partition_moves

    with rec.span("moves.materialize"):
        out = moves_from_arrays(names, states, nodes,
                                d_nodes, d_states, d_ops)
        for name in irregular:
            out[name] = calc_partition_moves(
                states,
                beg_map[name].nodes_by_state,
                end_map[name].nodes_by_state,
                favor_min_nodes)
        return out
