"""blance_tpu.moves subpackage."""
