"""``python -m blance_tpu.obs.device_check`` — the device-obs CI gate.

A thin delegate over :func:`blance_tpu.obs.device.main` (same flags:
``--check``, ``--trace-out``).  The package ``__init__`` imports
``obs.device`` eagerly, so ``python -m blance_tpu.obs.device`` would
execute the module a SECOND time under runpy (the 'found in
sys.modules' RuntimeWarning) with its own copy of the observatory
state; this shim is imported by nothing, so running it executes once
and arms the canonical instance — the same pattern as
``obs/__main__.py``."""

import sys

from .device import main

if __name__ == "__main__":
    sys.exit(main())
