"""Device-side performance observatory: compile accounting + XLA costs.

The telemetry plane (PR 6) covers the orchestration side; this module is
the SOLVER/DEVICE side — the place the 304 ms north-star solve actually
spends its time.  Three instruments, all fed through the same Recorder
as everything else:

- **Compile & retrace accounting** (:class:`CompileMonitor`).  JAX
  announces every XLA compilation on its own loggers when
  ``jax_log_compiles`` is on; the monitor taps that stream (the same
  one tests/conftest.py's recompile-budget fixture counts) and
  attributes each compile to the OWNING ENTRY POINT — ``solve_dense``
  cold/warm/bucketed, the fleet batch classes, the sharded dispatch —
  via the :func:`entry` contextvar the dispatch sites set.  Counts land
  as ``device.compiles{entry=...}`` counters and compile durations as
  ``device.compile_s{entry=...}`` histograms.  The per-entry retrace
  BUDGETS live in ``analysis/retrace.py`` (a declarative table checked
  by ``python -m blance_tpu.analysis --ci``), the promotion of the
  test-fixture budgets into a CI contract.
- **Static cost & memory gauges** (:func:`maybe_publish_cost`).  At the
  first dispatch per (entry, bucket-shape) — memoized, so steady state
  pays nothing — the entry point's jitted callable is lowered and
  AOT-compiled once more and XLA's own ``cost_analysis()`` /
  ``memory_analysis()`` are published as ``device.flops`` /
  ``device.hbm_bytes`` / ``device.peak_alloc_bytes`` gauges labeled
  ``{entry=,klass=}``: the Prometheus endpoint and the bench artifact
  then show exactly what each bucket class costs on device, per the
  GSPMD argument (arXiv:2105.04663) that bucketed compilation is only a
  win if retraces and per-class costs are actually measured.
- **Sweep-level convergence traces** (:func:`record_sweep_trace`).  The
  converged solve's fixpoint loop is fused into one device program, so
  per-sweep host spans cannot exist; instead the solver (with
  ``trace_sweeps``) accumulates each sweep's accepted-bid fraction
  in-graph and this module emits them as a ``device.sweep_accept_frac``
  Chrome counter track, with samples interpolated across the solve's
  host span so the track sits under the ``device_profile`` slices it
  belongs to.

Everything is OFF by default: attribution contextvars are always set
(they cost a token swap), but no logging handler is installed, no AOT
compile runs, and no extra solver outputs exist until :func:`enable` —
so the tier-1 recompile budgets and the timed bench loops see byte-for-
byte identical behavior unless a caller opted in (bench stages, the CI
``device-obs`` step, and the device-obs tests do).

CLI (the CI step)::

    python -m blance_tpu.obs.device --check [--trace-out PATH]

runs the retrace-budget workload + a cost-analysis smoke on CPU and
exits nonzero when a budget is blown or the gauges fail to publish;
``--trace-out`` captures the run as a Chrome trace for the artifact
upload.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import re
import threading
from typing import Any, Callable, Iterator, Optional

from .recorder import Recorder, escape_label_value as _lbl, get_recorder

__all__ = [
    "entry",
    "current_entry",
    "CompileMonitor",
    "enable",
    "disable",
    "enabled",
    "cost_enabled",
    "sweep_trace_enabled",
    "maybe_publish_cost",
    "cost_summaries",
    "reset_cost_cache",
    "record_sweep_trace",
    "main",
]


# -- entry-point attribution --------------------------------------------------

_entry_var: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("blance_device_entry", default=None)

# Fallback classification for compiles that fire outside any entry
# scope (jax-internal eager helper jits, test-local functions).
_DEFAULT_ENTRY = "other"


@contextlib.contextmanager
def entry(label: str) -> Iterator[None]:
    """Attribute every XLA compile inside the body to ``label``.

    FIRST WINS: a nested entry (solve_dense_converged tracing inside the
    sharded dispatch) does not re-label the outer scope — the outermost
    dispatch site owns the compile.  Always active (a contextvar swap),
    whether or not a monitor is installed."""
    if _entry_var.get() is not None:
        yield
        return
    token = _entry_var.set(label)
    try:
        yield
    finally:
        _entry_var.reset(token)


def current_entry() -> str:
    """The owning entry label for a compile happening right now."""
    return _entry_var.get() or _DEFAULT_ENTRY


def ambient_entry() -> Optional[str]:
    """The enclosing entry scope, or None outside any — for inner
    layers whose OWN label must yield to an outer dispatch site's (the
    bucketed plan path labels solve_dense_converged's cost gauges)."""
    return _entry_var.get()


# -- the jit-cache monitor ----------------------------------------------------

# jax announces compiles on two loggers (verified against the pinned
# jax 0.4.37; the conftest fixture parses the same stream):
#   jax._src.interpreters.pxla:  "Compiling <name> with global shapes..."
#                                "Compiling <name> (<id>) for <n> devices..."
#   jax._src.dispatch:           "Finished XLA compilation of jit(<name>)
#                                 in <secs> sec"
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_DISPATCH_LOGGER = "jax._src.dispatch"
_FINISHED_RE = re.compile(
    r"Finished XLA compilation of (?:jit\()?([^)\s]+)\)? "
    r"in ([0-9.eE+-]+) sec")


class _Tap(logging.Handler):
    """Routes matching log records into the owning monitor."""

    def __init__(self, monitor: "CompileMonitor") -> None:
        super().__init__()
        self._monitor = monitor

    def emit(self, record: logging.LogRecord) -> None:
        # Runs on the COMPILING thread, so current_entry() sees the
        # dispatch site's attribution contextvar.
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self._monitor._on_compile(msg.split(" ", 2)[1])
            return
        m = _FINISHED_RE.match(msg)
        if m:
            try:
                secs = float(m.group(2))
            except ValueError:
                return
            self._monitor._on_compile_done(m.group(1), secs)


class CompileMonitor:
    """Process-wide XLA compile counter with entry attribution.

    Use as a context manager around a stage (bench does) or install the
    process-global one via :func:`enable`.  ``emit=True`` additionally
    publishes every event to the CURRENT recorder
    (``device.compiles{entry=}`` counter, ``device.compile_s{entry=}``
    histogram) — stage-local monitors keep ``emit=False`` so a bench
    stage nested inside the global observatory never double-counts.

    Counts are exact per attribution scope; thread-safe (compiles can
    happen on executor threads — the fleet service's solve path)."""

    def __init__(self, emit: bool = False) -> None:
        self.emit = emit
        self.by_entry: dict[str, int] = {}
        self.by_fn: dict[str, int] = {}
        self.compile_s_by_entry: dict[str, float] = {}
        self._lock = threading.Lock()
        self._tap: Optional[_Tap] = None
        self._prev_levels: dict[str, int] = {}
        self._prev_propagate: dict[str, bool] = {}

    # -- event fan-in (called from the logging tap) --------------------------

    def _on_compile(self, fn_name: str) -> None:
        ent = current_entry()
        with self._lock:
            self.by_entry[ent] = self.by_entry.get(ent, 0) + 1
            self.by_fn[fn_name] = self.by_fn.get(fn_name, 0) + 1
        if self.emit:
            get_recorder().count(
                f'device.compiles{{entry="{_lbl(ent)}"}}')

    def _on_compile_done(self, fn_name: str, secs: float) -> None:
        ent = current_entry()
        with self._lock:
            self.compile_s_by_entry[ent] = \
                self.compile_s_by_entry.get(ent, 0.0) + secs
        if self.emit:
            get_recorder().observe(
                f'device.compile_s{{entry="{_lbl(ent)}"}}', secs)

    # -- lifecycle ------------------------------------------------------------

    def install(self) -> "CompileMonitor":
        """Attach the tap.  Deliberately does NOT flip
        ``jax_log_compiles``: jax logs the same records at DEBUG when
        the flag is off, so dropping the two loggers to DEBUG level
        feeds the tap while the root handler (WARNING by default) keeps
        stderr quiet — no spam for the observatory's whole lifetime."""
        if self._tap is not None:
            return self
        self._tap = _Tap(self)
        for name in (_PXLA_LOGGER, _DISPATCH_LOGGER):
            logger = logging.getLogger(name)
            self._prev_levels[name] = logger.level
            self._prev_propagate[name] = logger.propagate
            logger.setLevel(logging.DEBUG)
            # The tap is the only intended consumer of the DEBUG-level
            # stream; without this, jax's own console handler (attached
            # to the parent "jax" logger) would echo every record.
            logger.propagate = False
            logger.addHandler(self._tap)
        return self

    def uninstall(self) -> None:
        if self._tap is None:
            return
        for name in (_PXLA_LOGGER, _DISPATCH_LOGGER):
            logger = logging.getLogger(name)
            logger.removeHandler(self._tap)
            logger.setLevel(self._prev_levels.get(name, logging.NOTSET))
            logger.propagate = self._prev_propagate.get(name, True)
        self._prev_levels.clear()
        self._prev_propagate.clear()
        self._tap = None

    def __enter__(self) -> "CompileMonitor":
        return self.install()

    def __exit__(self, *exc: object) -> None:
        self.uninstall()

    # -- summaries ------------------------------------------------------------

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.by_entry.values())

    def summary(self) -> dict:
        """JSON-ready stage summary (bench embeds this per stage)."""
        with self._lock:
            return {
                "total": sum(self.by_entry.values()),
                "by_entry": dict(sorted(self.by_entry.items())),
                "compile_s_by_entry": {
                    k: round(v, 4)
                    for k, v in sorted(self.compile_s_by_entry.items())},
            }


# -- the process-global observatory ------------------------------------------

_state: dict[str, Any] = {
    "monitor": None,  # the emit=True process monitor, when enabled
    "cost": False,
    "sweep_trace": False,
}
_state_lock = threading.Lock()


def enable(cost_analysis: bool = True, sweep_trace: bool = True) -> None:
    """Switch the observatory ON process-wide: install the emitting
    compile monitor and (optionally) arm AOT cost analysis + in-graph
    sweep tracing.  Idempotent."""
    with _state_lock:
        if _state["monitor"] is None:
            _state["monitor"] = CompileMonitor(emit=True).install()
        _state["cost"] = bool(cost_analysis)
        _state["sweep_trace"] = bool(sweep_trace)


def disable() -> None:
    """Switch the observatory OFF and restore jax_log_compiles."""
    with _state_lock:
        mon = _state["monitor"]
        if mon is not None:
            mon.uninstall()
        _state["monitor"] = None
        _state["cost"] = False
        _state["sweep_trace"] = False


def enabled() -> bool:
    return _state["monitor"] is not None


def cost_enabled() -> bool:
    return bool(_state["cost"])


def sweep_trace_enabled() -> bool:
    return bool(_state["sweep_trace"])


def monitor() -> Optional[CompileMonitor]:
    """The process-global monitor (None while disabled)."""
    mon: Optional[CompileMonitor] = _state["monitor"]
    return mon


# -- static cost & memory gauges ----------------------------------------------

# (entry, klass) -> summary dict (or None when analysis failed): the
# first-dispatch memo.  Bounded by the entry x bucket-class product,
# which bucketing keeps small by design.
_COST_CACHE: dict[tuple[str, str], Optional[dict]] = {}
_COST_LOCK = threading.Lock()


def reset_cost_cache() -> None:
    with _COST_LOCK:
        _COST_CACHE.clear()


def cost_summaries() -> dict:
    """{entry: {klass: summary}} for everything published so far."""
    out: dict[str, dict[str, dict]] = {}
    with _COST_LOCK:
        items = list(_COST_CACHE.items())
    for (ent, klass), summary in sorted(items):
        if summary is not None:
            out.setdefault(ent, {})[klass] = summary
    return out


def _extract_cost(compiled: Any) -> dict:
    """Pull flops / traffic / peak-alloc numbers off an AOT-compiled
    executable, tolerant of per-backend shape differences
    (cost_analysis returns a list of dicts on CPU, a dict on some
    backends; memory_analysis can be absent)."""
    flops = hbm = 0.0
    try:
        ca = compiled.cost_analysis()
    except Exception as e:  # per-backend API gaps (absent/NotImplemented)
        logging.getLogger(__name__).debug(
            "device-obs: cost_analysis unavailable: %s: %s",
            type(e).__name__, e)
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = float(ca.get("flops", 0.0) or 0.0)
        hbm = float(ca.get("bytes accessed", 0.0) or 0.0)
    peak = 0.0
    mem: dict[str, float] = {}
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # same per-backend API gap class as above
        logging.getLogger(__name__).debug(
            "device-obs: memory_analysis unavailable: %s: %s",
            type(e).__name__, e)
        ma = None
    if ma is not None:
        for fieldname in ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = float(getattr(ma, fieldname, 0) or 0)
            mem[fieldname] = v
            if fieldname != "generated_code_size_in_bytes":
                peak += v
    return {"flops": flops, "hbm_bytes": hbm,
            "peak_alloc_bytes": peak, "memory": mem}


def maybe_publish_cost(ent: str, klass: str, fn: Any,
                       *args: Any, **kwargs: Any) -> Optional[dict]:
    """AOT cost/memory analysis for one (entry, bucket-shape), once.

    ``fn`` must be a jitted callable (``.lower`` supported); ``args`` /
    ``kwargs`` are exactly what the live dispatch passes.  No-op unless
    :func:`enable` armed cost analysis — the extra AOT compile this
    costs (one per memo key) is an explicit opt-in, so the tier-1
    recompile budgets never see it.  Publishes ``device.flops`` /
    ``device.hbm_bytes`` / ``device.peak_alloc_bytes`` gauges labeled
    ``{entry=,klass=}`` and bumps ``device.cost_analyses``; returns the
    summary dict (None on analysis failure, which is recorded so the
    failure isn't retried per dispatch)."""
    if not cost_enabled():
        return None
    key = (ent, klass)
    with _COST_LOCK:
        if key in _COST_CACHE:
            return _COST_CACHE[key]
    try:
        # The AOT lower+compile is real work owned by the entry point,
        # but it is observation overhead, not a retrace: label it
        # "<entry>+aot" so operators can see it while the retrace-budget
        # check (analysis/retrace.py) excludes it from the live counts.
        # Escape any ambient scope first — entry() is first-wins.
        tok = _entry_var.set(None)
        try:
            with entry(f"{ent}+aot"):
                compiled = fn.lower(*args, **kwargs).compile()
        finally:
            _entry_var.reset(tok)
        summary = _extract_cost(compiled)
    except Exception as e:  # analysis is best-effort observability:
        # an unlowerable shape must never fail the solve it observes.
        summary = None
        logging.getLogger(__name__).warning(
            "device-obs: cost analysis failed for %s/%s: %s: %s",
            ent, klass, type(e).__name__, e)
    with _COST_LOCK:
        _COST_CACHE[key] = summary
    if summary is not None:
        rec = get_recorder()
        labels = f'{{entry="{_lbl(ent)}",klass="{_lbl(klass)}"}}'
        rec.set_gauge(f"device.flops{labels}", summary["flops"])
        rec.set_gauge(f"device.hbm_bytes{labels}", summary["hbm_bytes"])
        rec.set_gauge(f"device.peak_alloc_bytes{labels}",
                      summary["peak_alloc_bytes"])
        rec.count("device.cost_analyses")
    return summary


# -- sweep-level convergence traces -------------------------------------------


def record_sweep_trace(rec: Recorder, t0: float, t1: float,
                       sweeps: int, fracs: Any) -> None:
    """Emit one solve's per-sweep accepted-bid fractions as a Chrome
    counter track (``device.sweep_accept_frac``).

    The fixpoint loop is one fused device program, so per-sweep host
    timestamps do not exist; samples are INTERPOLATED evenly across the
    solve's host interval [t0, t1] — the track then sits under the
    solve's span (and its device_profile slices) with the right number
    of steps, which is the alignment that matters for reading
    convergence shape in Perfetto."""
    n = int(sweeps)
    if n <= 0:
        return
    span = max(t1 - t0, 0.0)
    for i in range(n):
        t = t0 + span * (i + 1) / n
        rec.sample("device.sweep_accept_frac", float(fracs[i]), t=t)


# -- CLI: the CI device-obs gate ----------------------------------------------


def main(argv: Optional[list[str]] = None) -> int:
    """``python -m blance_tpu.obs.device --check``: the retrace-budget
    table check + a cost-analysis smoke, on CPU, with an optional Chrome
    trace artifact for upload on failure."""
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m blance_tpu.obs.device",
        description="device-side observatory checks "
                    "(docs/OBSERVABILITY.md)")
    ap.add_argument("--check", action="store_true",
                    help="run the retrace-budget workload + a smoke "
                         "cost-analysis pass; exit nonzero on failure")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write the run's spans + counter tracks as a "
                         "Chrome trace (the CI failure artifact)")
    args = ap.parse_args(argv)
    if not args.check:
        ap.print_help()
        return 2

    # CPU + virtual devices BEFORE jax initializes, like every other
    # host-side gate (tests/conftest.py, analysis --ci).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    from ..analysis.retrace import run_retrace_check
    from .chrome import trace
    from .recorder import use_recorder

    rec = Recorder()
    failures: list[str] = []
    with use_recorder(rec):
        enable(cost_analysis=True, sweep_trace=True)
        ctx = trace(args.trace_out, recorder=rec) if args.trace_out \
            else contextlib.nullcontext()
        try:
            with ctx:
                findings, entries = run_retrace_check()
                for f in findings:
                    failures.append(f.render())
                    print(f.render(), file=sys.stderr)
                # Cost-analysis smoke: the workload above dispatched the
                # solver entry points with cost analysis armed, so the
                # gauges and compile counters must be live.
                flops = [v for k, v in rec.gauges.items()
                         if k.startswith("device.flops{")]
                if not flops or not any(v > 0 for v in flops):
                    failures.append(
                        "cost-analysis smoke: no nonzero device.flops "
                        "gauge published")
                compiles = [v for k, v in rec.counters.items()
                            if k.startswith("device.compiles{")]
                if not compiles:
                    failures.append(
                        "compile accounting: no device.compiles counter "
                        "moved during the workload")
        finally:
            disable()
    print(f"device-obs: {entries} budget entries, "
          f"{len(failures)} failure(s)"
          + (" — FAIL" if failures else " — OK"), file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    import sys

    # Under ``python -m`` runpy executes this file as a SECOND module
    # instance ("__main__") distinct from the already-imported
    # ``blance_tpu.obs.device`` the solver entry points call into —
    # enabling the observatory on the copy would arm the wrong _state.
    # Delegate to the canonical instance.
    from blance_tpu.obs.device import main as _canonical_main

    sys.exit(_canonical_main())
