"""Calibrated per-(node, op-kind) move-cost model.

ROADMAP item 2's critical-path move scheduler needs a per-move cost
estimate "calibrated online from the obs ``orchestrate.move_latency_s``
histograms".  This module is that artifact: :class:`CostModel` is a span
SINK — attach it to the Recorder and it learns from the exact same
``orchestrate.move.exec`` lifecycle spans the histograms are built from,
with no extra instrumentation in the orchestrator:

- each exec span carries its node and the batch's op kinds; the batch's
  wall-clock (retries included — that IS the cost of moving onto a flaky
  node) is amortized evenly across its moves, and each move's share
  updates an EWMA per ``(node, op)``:
  ``ewma' = alpha * observed + (1 - alpha) * ewma``;
- :meth:`predict` answers in fallback order — exact ``(node, op)``
  estimate, then the op-kind aggregate (a new node costs like the op
  does elsewhere), then the global aggregate, then ``default_s`` —
  so the scheduler always gets a number;
- prediction error is scored ONLINE: at each update where an estimate
  already existed, the relative error ``|predicted - observed| /
  observed`` lands in the ``costmodel.rel_err`` histogram and the
  calibration report (bench's costmodel stage publishes its p50);
- the whole model round-trips through JSON (:meth:`save` /
  :meth:`load`), so a scheduler can warm-start from the previous run's
  calibration instead of re-learning a fleet from scratch.

The sink methods are plain sync code (the Recorder calls them inline as
spans finish), so updates are atomic on the event loop; the race lint's
``SHARED_STATE`` table declares the mutable attributes.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, TextIO, Union

from ..utils.atomicio import atomic_write_json
from .recorder import Recorder, Span, get_recorder, percentile

__all__ = ["CostModel", "EXEC_SPAN", "DEFAULT_PRIORS_PATH",
           "default_op_priors"]

# The move-lifecycle span the model learns from: the app-callback
# execution child, which carries node= and ops= attributes.
EXEC_SPAN = "orchestrate.move.exec"

_FORMAT_VERSION = 1
_PRIORS_VERSION = 1

# The committed bench calibration: per-op EWMA aggregates measured by
# bench.py's costmodel stage (regenerate from its ``op_priors_s``
# output).  Seeding these as op-level priors means a scheduler on a
# NEVER-OBSERVED cluster already prices a del cheaper than an add
# instead of running uniform-cost (ISSUE 12 satellite).
DEFAULT_PRIORS_PATH = os.path.join(os.path.dirname(__file__),
                                   "costmodel_priors.json")


def default_op_priors(path: Optional[str] = None) -> dict[str, float]:
    """Load the committed per-op prior table: op kind -> seconds.
    Raises on a version mismatch (regenerate the file from the bench
    costmodel stage) so a stale format can never silently mis-seed."""
    with open(path if path is not None else DEFAULT_PRIORS_PATH) as f:
        data = json.load(f)
    version = data.get("version")
    if version != _PRIORS_VERSION:
        raise ValueError(
            f"cost-model priors version {version!r} != {_PRIORS_VERSION}"
            f" (regenerate the file from the bench costmodel stage)")
    return {str(op): float(s)
            for op, s in data["op_priors_s"].items()}


class CostModel:
    """EWMA move-cost estimates per (node, op kind), learned from spans.

    alpha: EWMA smoothing factor in (0, 1] — higher adapts faster.
    default_s: the cold-start prediction before any observation.
    recorder: where ``costmodel.updates`` / ``costmodel.rel_err`` land;
        defaults to the process recorder at update time.
    """

    def __init__(self, alpha: float = 0.3, default_s: float = 0.05,
                 recorder: Optional[Recorder] = None) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self._alpha = alpha
        self._default_s = default_s
        self._rec = recorder
        # (node, op) -> [ewma_seconds, n_observations]
        self._est: dict[tuple[str, str], list] = {}
        # op -> [ewma_seconds, n] (fallback for unseen nodes)
        self._op_est: dict[str, list] = {}
        # [ewma_seconds, n] (fallback for unseen ops)
        self._global: list = [0.0, 0]
        # Online relative errors, bounded exactly like the Recorder's
        # percentile sample: a systematic 1-in-stride subsample whose
        # stride doubles on each 2:1 decimation at the cap — the sample
        # stays spread over the WHOLE scoring history, not just the
        # most recent window.
        self._errors: list[float] = []
        self._err_stride = 1
        self._n_scored = 0

    # -- sink protocol --------------------------------------------------------

    def span(self, sp: Span) -> None:
        if sp.name != EXEC_SPAN or sp.t_end is None:
            return
        node = sp.attrs.get("node")
        ops_attr = sp.attrs.get("ops")
        if not isinstance(node, str) or not isinstance(ops_attr, str) \
                or not ops_attr:
            return
        ops = ops_attr.split(",")
        per_move_s = max(sp.duration_s, 0.0) / len(ops)
        rec = self._rec if self._rec is not None else get_recorder()
        for op in ops:
            self._update(node, op, per_move_s, rec)

    # NOTE: no ``counter`` hook — the Recorder feature-detects it, and
    # declaring one would put this sink on the hot path of every count().

    def close(self) -> None:
        pass

    def _update(self, node: str, op: str, observed_s: float,
                rec: Recorder) -> None:
        key = (node, op)
        est = self._est.get(key)
        if est is not None:
            # Score the prediction this observation falsifies, BEFORE
            # folding the observation in.
            err = abs(est[0] - observed_s) / max(observed_s, 1e-9)
            if self._n_scored % self._err_stride == 0:
                self._errors.append(err)
                if len(self._errors) >= 4096:
                    del self._errors[::2]
                    self._err_stride *= 2
            self._n_scored += 1
            rec.observe("costmodel.rel_err", err)
            est[0] = self._alpha * observed_s + (1 - self._alpha) * est[0]
            est[1] += 1
        else:
            self._est[key] = [observed_s, 1]
        for agg in (self._op_est.setdefault(op, [0.0, 0]), self._global):
            agg[0] = observed_s if agg[1] == 0 else \
                self._alpha * observed_s + (1 - self._alpha) * agg[0]
            agg[1] += 1
        rec.count("costmodel.updates")

    # -- cold-start priors ----------------------------------------------------

    def seed_priors(self, op_priors_s: "dict[str, float]",
                    n: int = 1) -> None:
        """Seed op-level fallback estimates (op kind -> seconds) for
        ops with NO observations yet — the committed bench calibration
        (``default_op_priors``) is the canonical source.  Live
        observations take over through the normal EWMA fold; aggregates
        that already learned from real spans are never overwritten."""
        for op, s in op_priors_s.items():
            agg = self._op_est.get(op)
            if agg is None or agg[1] == 0:
                self._op_est[op] = [float(s), max(int(n), 1)]

    @classmethod
    def with_priors(cls, path: Optional[str] = None,
                    **kwargs: Any) -> "CostModel":
        """A fresh model seeded from the committed bench calibration
        file — the scheduler's cold-start spelling."""
        model = cls(**kwargs)
        model.seed_priors(default_op_priors(path))
        return model

    # -- the scheduler-facing API ---------------------------------------------

    def predict(self, node: str, op: str) -> float:
        """Estimated seconds for one (node, op) move — exact estimate,
        else op aggregate, else global aggregate, else default.  Every
        answer below the exact level counts ``costmodel.cold_predictions``
        so dashboards can see how much of a schedule ran on priors."""
        est = self._est.get((node, op))
        if est is not None:
            return float(est[0])
        rec = self._rec if self._rec is not None else get_recorder()
        rec.count("costmodel.cold_predictions")
        agg = self._op_est.get(op)
        if agg is not None and agg[1] > 0:
            return float(agg[0])
        if self._global[1] > 0:
            return float(self._global[0])
        return self._default_s

    def predict_move(self, move: Any) -> float:
        """``predict`` over anything with ``node``/``op`` attributes
        (``PartitionMove``, a move cursor entry)."""
        return self.predict(move.node, move.op)

    def observations(self) -> int:
        return int(self._global[1])

    def estimates(self) -> dict[tuple[str, str], float]:
        """A copy of the exact (node, op) estimate table."""
        return {k: float(v[0]) for k, v in self._est.items()}

    def calibration(self) -> dict:
        """Online predicted-vs-actual scoring: relative-error p50/p95
        over the updates that had a prior estimate to falsify (exact up
        to ~4k scored updates, a systematic whole-history subsample
        beyond — same bounding as the Recorder's percentile sample)."""
        out = {
            "observations": self.observations(),
            "scored": self._n_scored,
            "estimates": len(self._est),
        }
        if self._errors:
            out["p50_rel_err"] = percentile(self._errors, 50)
            out["p95_rel_err"] = percentile(self._errors, 95)
        return out

    # -- persistence ----------------------------------------------------------

    def to_json(self) -> dict:
        """The on-disk format (docs/OBSERVABILITY.md documents it)."""
        return {
            "version": _FORMAT_VERSION,
            "alpha": self._alpha,
            "default_s": self._default_s,
            "estimates": [
                {"node": node, "op": op, "ewma_s": est[0], "n": est[1]}
                for (node, op), est in sorted(self._est.items())
            ],
            "op_estimates": {
                op: {"ewma_s": agg[0], "n": agg[1]}
                for op, agg in sorted(self._op_est.items())
            },
            "global": {"ewma_s": self._global[0], "n": self._global[1]},
        }

    @classmethod
    def from_json(cls, data: dict,
                  recorder: Optional[Recorder] = None) -> "CostModel":
        version = data.get("version")
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"cost-model format version {version!r} != "
                f"{_FORMAT_VERSION} (regenerate the file)")
        model = cls(alpha=float(data["alpha"]),
                    default_s=float(data["default_s"]), recorder=recorder)
        for entry in data.get("estimates", ()):
            model._est[(str(entry["node"]), str(entry["op"]))] = [
                float(entry["ewma_s"]), int(entry["n"])]
        for op, agg in data.get("op_estimates", {}).items():
            model._op_est[str(op)] = [float(agg["ewma_s"]), int(agg["n"])]
        g = data.get("global", {"ewma_s": 0.0, "n": 0})
        model._global = [float(g["ewma_s"]), int(g["n"])]
        return model

    def save(self, path_or_file: Union[str, TextIO]) -> None:
        """Persist as JSON; a path write goes through the shared
        crash-atomic recipe (:mod:`blance_tpu.utils.atomicio` — same-dir
        temp + fsync + rename + directory fsync) so a scheduler never
        loads a torn model and a completed save survives power loss."""
        if not isinstance(path_or_file, str):
            json.dump(self.to_json(), path_or_file, indent=1, sort_keys=True)
            return
        atomic_write_json(path_or_file, self.to_json(),
                          indent=1, sort_keys=True)

    @classmethod
    def load(cls, path_or_file: Union[str, TextIO],
             recorder: Optional[Recorder] = None) -> "CostModel":
        if isinstance(path_or_file, str):
            with open(path_or_file) as f:
                return cls.from_json(json.load(f), recorder=recorder)
        return cls.from_json(json.load(path_or_file), recorder=recorder)
