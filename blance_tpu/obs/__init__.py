"""blance_tpu.obs — unified tracing & metrics for the whole pipeline.

One process-local :class:`Recorder` (``get_recorder()``) receives spans,
counters, and histograms from every layer:

=====================  ======================================================
layer                  signals
=====================  ======================================================
plan (api/tensor)      ``plan.encode`` / ``plan.solve`` / ``plan.decode``
                       spans (engine + fallback attributes),
                       ``plan.solve.sweeps`` convergence counter/histogram
plan (greedy)          ``plan.greedy`` span,
                       ``plan.greedy.candidates`` scoring histogram
moves (batch)          ``moves.calc_all_moves`` / ``moves.encode`` /
                       ``moves.device_diff`` / ``moves.materialize`` spans
orchestrate            ``orchestrate.move`` lifecycle span per fed batch,
                       split into ``.wait`` (queue/concurrency wait) and
                       ``.exec`` (mover callback) children;
                       ``orchestrate.move_latency_s`` histogram; every
                       OrchestratorProgress counter mirrored as
                       ``orchestrate.tot_*``
=====================  ======================================================

Sinks decide retention (``sinks.InMemorySink``, ``sinks.JsonlSink``,
``chrome.ChromeTraceSink``); the recorder alone keeps only aggregates.
``chrome.trace(path)`` captures a region into a chrome://tracing /
Perfetto-loadable file; ``utils.trace.PhaseTimer`` remains as a thin
compatibility shim whose phases are recorded as spans here.

The LIVE telemetry plane (PR 6) layers on the same Recorder:
``expo.MetricsServer`` serves Prometheus text format from periodic
Recorder snapshots (``expo.default_registry()`` is the one declarative
table of every metric); ``slo.SloTracker`` computes online SLO gauges
(availability, churn, convergence lag, quarantine exposure) during a
rebalance; ``costmodel.CostModel`` learns per-(node, op) EWMA move
costs from the move-lifecycle spans and persists them as JSON for the
critical-path scheduler.

The DEVICE side has its own observatory (``device``, opt-in via
``device.enable()``): XLA compile accounting attributed per owning
entry point, AOT cost/memory gauges per (entry, bucket-shape), and
in-graph sweep-level convergence traces; ``tracectx`` adds
end-to-end request tracing (deterministic ``TraceContext`` ids +
``RequestTimeline`` latency decomposition, used by
``plan.service.PlanService``).

See docs/OBSERVABILITY.md for the architecture tour.
"""

from . import device
from .chrome import ChromeTraceSink, trace, write_chrome_trace
from .costmodel import CostModel
from .expo import (
    Metric,
    MetricsRegistry,
    MetricsServer,
    default_registry,
    parse_prometheus,
    render_prometheus,
    scrape,
)
from .recorder import (
    DEFAULT_BUCKETS,
    Recorder,
    Span,
    get_recorder,
    percentile,
    phase_span,
    set_recorder,
    use_recorder,
)
from .sinks import InMemorySink, JsonlSink, span_to_dict
from .slo import MoveObserver, SloSummary, SloTracker
from .tracectx import (
    SEGMENTS,
    RequestTimeline,
    TraceContext,
    TraceIdSource,
    current_trace,
    use_trace,
)

__all__ = [
    "device",
    "TraceContext",
    "TraceIdSource",
    "RequestTimeline",
    "SEGMENTS",
    "current_trace",
    "use_trace",
    "Recorder",
    "Span",
    "DEFAULT_BUCKETS",
    "get_recorder",
    "set_recorder",
    "use_recorder",
    "phase_span",
    "percentile",
    "InMemorySink",
    "JsonlSink",
    "span_to_dict",
    "ChromeTraceSink",
    "write_chrome_trace",
    "trace",
    "Metric",
    "MetricsRegistry",
    "MetricsServer",
    "default_registry",
    "render_prometheus",
    "parse_prometheus",
    "scrape",
    "MoveObserver",
    "SloSummary",
    "SloTracker",
    "CostModel",
]
