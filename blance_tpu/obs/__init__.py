"""blance_tpu.obs — unified tracing & metrics for the whole pipeline.

One process-local :class:`Recorder` (``get_recorder()``) receives spans,
counters, and histograms from every layer:

=====================  ======================================================
layer                  signals
=====================  ======================================================
plan (api/tensor)      ``plan.encode`` / ``plan.solve`` / ``plan.decode``
                       spans (engine + fallback attributes),
                       ``plan.solve.sweeps`` convergence counter/histogram
plan (greedy)          ``plan.greedy`` span,
                       ``plan.greedy.candidates`` scoring histogram
moves (batch)          ``moves.calc_all_moves`` / ``moves.encode`` /
                       ``moves.device_diff`` / ``moves.materialize`` spans
orchestrate            ``orchestrate.move`` lifecycle span per fed batch,
                       split into ``.wait`` (queue/concurrency wait) and
                       ``.exec`` (mover callback) children;
                       ``orchestrate.move_latency_s`` histogram; every
                       OrchestratorProgress counter mirrored as
                       ``orchestrate.tot_*``
=====================  ======================================================

Sinks decide retention (``sinks.InMemorySink``, ``sinks.JsonlSink``,
``chrome.ChromeTraceSink``); the recorder alone keeps only aggregates.
``chrome.trace(path)`` captures a region into a chrome://tracing /
Perfetto-loadable file; ``utils.trace.PhaseTimer`` remains as a thin
compatibility shim whose phases are recorded as spans here.

See docs/OBSERVABILITY.md for the architecture tour.
"""

from .chrome import ChromeTraceSink, trace, write_chrome_trace
from .recorder import (
    Recorder,
    Span,
    get_recorder,
    percentile,
    phase_span,
    set_recorder,
    use_recorder,
)
from .sinks import InMemorySink, JsonlSink, span_to_dict

__all__ = [
    "Recorder",
    "Span",
    "get_recorder",
    "set_recorder",
    "use_recorder",
    "phase_span",
    "percentile",
    "InMemorySink",
    "JsonlSink",
    "span_to_dict",
    "ChromeTraceSink",
    "write_chrome_trace",
    "trace",
]
