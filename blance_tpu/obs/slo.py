"""Online SLO accounting for a live rebalance.

The continuous-rebalance story (ROADMAP item 4) needs service-level
numbers DURING the transition, not after: how much of the keyspace is
serving right now, how much movement the convergence is costing, and
whether progress has stalled.  :class:`SloTracker` computes them online:

- **partition availability** — the fraction of partitions with at least
  one node in a serving-primary state.  Maintained INCREMENTALLY: the
  tracker holds a per-partition ``node -> state`` view seeded from the
  begin map and applies each successfully executed move as the
  orchestrator reports it (the achieved-map delta), so an update is
  O(moves in the batch), never a full-map recompute.
- **cumulative churn** — successfully executed moves divided by the
  minimum necessary (the primary plan's move count).  1.0 is a perfect
  run; retries burned on abandoned partitions and recovery-round
  re-placements push it above 1.
- **convergence lag** — seconds (on the tracker's clock, so virtual
  seconds under ``DeterministicLoop``) since the last successfully
  executed move: the "is it stuck" gauge.
- **per-node quarantine exposure** — cumulative seconds each node has
  spent quarantined/half-open, read from the orchestrator's
  ``HealthTracker``.

With ``track_timeline=True`` the tracker additionally keeps *horizon*
accounting for the continuous-rebalance control loop (the
``testing/simulate`` tier, docs/SIMULATOR.md): every availability
change is appended to a ``(t, availability)`` step timeline, from which
it derives

- **time-weighted availability** — the integral of the availability
  step function over the run divided by its duration: the fraction of
  (partition x seconds) that was actually serving, the honest headline
  for a run with transient dips;
- **SLO-violation intervals** — with ``availability_floor`` set, the
  maximal ``[start, end)`` intervals during which availability sat
  below the floor, plus their cumulative seconds.

Both are pure functions of the timeline, so under a virtual clock the
whole horizon account replays bit-identically.

The tracker is an orchestrator *move observer* (``on_batch``): the
mover calls it after every batch with the outcome.  Updates are plain
sync methods with no awaits — on the event loop they are atomic, so
concurrent movers cannot tear the placement view (the race lint's
``SHARED_STATE`` table declares the attributes; the schedule explorer's
``slo_gauges_under_chaos`` scenario checks the bounds dynamically).

Gauges are published to a Recorder (``slo.*`` — see the
``MetricsRegistry`` table in ``obs/expo.py``) on every update;
``publish`` is also the collector hook a ``MetricsServer`` calls before
each snapshot so time-derived gauges stay fresh between events.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping, Optional, Protocol, \
    Sequence

from .recorder import Recorder, escape_label_value, get_recorder

__all__ = ["FleetSloRollup", "FleetSloSummary", "MoveObserver",
           "SloSummary", "SloTracker", "SLO_FORMAT_VERSION"]

# On-disk schema version for SloTracker.to_dict/from_dict (durability
# snapshots); from_dict refuses other versions.
SLO_FORMAT_VERSION = 1

# Kept as the module-local spelling; the one implementation lives in
# obs/recorder.py so it cannot drift from obs/device.py's labels.
_escape_label = escape_label_value


class MoveObserver(Protocol):
    """What the orchestrator notifies after every batch outcome.  A
    'move' is duck-typed (``partition``/``node``/``state``/``op``
    attributes) so observers need no import of the orchestrate layer."""

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None: ...


@dataclass
class SloSummary:
    """The end-of-run SLO snapshot (``RebalanceResult.slo``, the bench
    artifact's ``slo`` block).  Formulas in docs/OBSERVABILITY.md."""

    availability: float
    churn_ratio: float
    convergence_lag_s: float
    moves_executed: int
    moves_failed: int
    min_moves: int
    partitions: int
    available_partitions: int
    quarantine_exposure_s: dict[str, float] = field(default_factory=dict)
    # Per-incident makespan accounting (ISSUE 12 satellite): seconds
    # from incident open to the LAST required move executed, one entry
    # per closed incident.  ``convergence_lag_s`` ("seconds since the
    # last executed move") under-reports during a long scheduled tail —
    # moves keep landing, so the gauge hugs zero while the rebalance is
    # still hours from done; this is the honest time-to-converged the
    # critical-path scheduler minimizes.  None until an incident closed.
    first_converged_lag_s: Optional[float] = None
    first_converged_lags: list[float] = field(default_factory=list)
    # -- horizon accounting (None/empty unless track_timeline was on) --
    time_weighted_availability: Optional[float] = None
    availability_floor: Optional[float] = None
    violation_s: float = 0.0
    # Maximal [start, end) intervals with availability < floor, in
    # tracker-clock seconds.
    violation_intervals: list[tuple[float, float]] = \
        field(default_factory=list)


class SloTracker:
    """Incremental SLO gauges over one (possibly multi-round) rebalance.

    ``beg_map`` seeds the placement view; ``primary_states`` names the
    states that count as "serving" (the priority-0 states of the model;
    ``rebalance_async`` computes this automatically).  ``clock`` is the
    time source for convergence lag — pass ``recorder.now`` so SLO time
    and span time agree (and both follow a virtual clock in tests)."""

    def __init__(self, beg_map: Mapping[str, Any],
                 primary_states: Iterable[str] = ("primary",),
                 clock: Optional[Callable[[], float]] = None,
                 recorder: Optional[Recorder] = None,
                 track_timeline: bool = False,
                 availability_floor: Optional[float] = None,
                 publish_gauges: bool = True) -> None:
        self._rec = recorder
        # publish_gauges=False keeps the whole account (summaries,
        # timelines, incidents) but silences the slo.* gauge writes: a
        # fleet of per-tenant trackers must not fight last-writer-wins
        # over one process-wide gauge set — the FleetSloRollup publishes
        # the aggregate instead (docs/FLEET.md).
        self._publish_gauges = publish_gauges
        self._clock: Callable[[], float] = (
            clock if clock is not None
            else (recorder.now if recorder is not None else time.perf_counter))
        self._primary_states = frozenset(primary_states)
        # partition -> {node -> state}: the live placement view.
        self._placements: dict[str, dict[str, str]] = {}
        # partition -> number of serving-primary holders.
        self._primaries: dict[str, int] = {}
        self._available = 0
        for name, part in beg_map.items():
            d: dict[str, str] = {}
            for state, ns in part.nodes_by_state.items():
                for n in ns:
                    d[n] = state
            self._placements[name] = d
            prim = sum(1 for s in d.values() if s in self._primary_states)
            self._primaries[name] = prim
            if prim > 0:
                self._available += 1
        self._total = len(self._placements)
        self._min_moves = 0
        self.moves_executed = 0
        self.moves_failed = 0
        self._t_last_progress = self._clock()
        self._health: Optional[Any] = None
        # Incident accounting: open at the event that starts a
        # rebalance episode (delta submission / rebalance entry), close
        # at its quiesce; the lag is measured to the LAST executed move
        # inside the incident, so debounce/planning idle after the
        # final move never inflates it.
        self._incident_t0: Optional[float] = None
        self._incident_moves0 = 0
        self._incident_fails0 = 0
        self._t_last_fail: Optional[float] = None
        self._first_converged_lags: list[float] = []
        # Horizon accounting: a step timeline of (t, availability),
        # appended only on CHANGE (plus the seed point), so the
        # integral below is a plain fold over it.
        self._floor = availability_floor
        self._t0 = self._t_last_progress
        self._timeline: Optional[list[tuple[float, float]]] = (
            [(self._t0, self.availability())] if track_timeline else None)

    # -- wiring ---------------------------------------------------------------

    def set_min_moves(self, n: int) -> None:
        """Pin the churn denominator to the PRIMARY plan's move count.
        First call wins: recovery rounds re-plan, but the minimum
        necessary is what the original transition needed."""
        if self._min_moves == 0:
            self._min_moves = max(int(n), 0)

    def attach_health(self, health: Optional[Any]) -> None:
        """Adopt the orchestrator's HealthTracker (it carries across
        recovery rounds) as the quarantine-exposure source."""
        if health is not None:
            self._health = health

    # -- incident (makespan) accounting ---------------------------------------

    def open_incident(self, t: Optional[float] = None) -> None:
        """Mark the start of a rebalance incident (a cluster delta, a
        rebalance call).  First open wins until the incident closes, so
        a burst of coalesced deltas reads as ONE incident measured from
        its first event."""
        if self._incident_t0 is None:
            self._incident_t0 = self._clock() if t is None else t
            self._incident_moves0 = self.moves_executed
            self._incident_fails0 = self.moves_failed

    def close_incident(self, t: Optional[float] = None) -> Optional[float]:
        """Close the open incident (the control loop quiesced / the
        rebalance returned) and record its time-to-converged: incident
        open to the last executed move — 0.0 when the incident needed
        no moves.  An incident whose execution TAIL is failures (fails
        after the last execute, or no execute at all) never converged,
        so its lag is the whole open-to-close window (a lower bound),
        never a deflated time-to-last-execute; a failure that a retry
        or recovery round then executed past still reads as converged.
        Publishes ``slo.first_converged_lag_s``; returns the lag (None
        when no incident was open)."""
        if self._incident_t0 is None:
            return None
        executed = self.moves_executed > self._incident_moves0
        failed = self.moves_failed > self._incident_fails0
        fail_tail = failed and self._t_last_fail is not None and (
            not executed or self._t_last_fail > self._t_last_progress)
        if executed and not fail_tail:
            lag = max(self._t_last_progress - self._incident_t0, 0.0)
        elif fail_tail:
            t_close = self._clock() if t is None else t
            lag = max(t_close - self._incident_t0, 0.0)
        else:
            lag = 0.0
        self._first_converged_lags.append(lag)
        self._incident_t0 = None
        self.publish(t)
        return lag

    def discard_incident(self) -> None:
        """Drop the open incident WITHOUT recording a lag — the caller
        raised out of the episode (validation error, planner crash), so
        there is no makespan to account and the next episode's
        ``open_incident`` must not read a stale start.  No-op when
        nothing is open."""
        self._incident_t0 = None

    def first_converged_lags(self) -> list[float]:
        """Per-incident time-to-converged samples, in close order."""
        return list(self._first_converged_lags)

    # -- the orchestrator hook ------------------------------------------------

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        """One batch outcome from a mover.  ``ok`` means the assign
        callback succeeded and the moves are applied cluster-side; a
        failed batch is assumed NOT applied (the orchestrator's
        achieved-map presumption) and only counts against churn
        bookkeeping as failures."""
        if ok:
            for mv in moves:
                self._apply(mv)
            self.moves_executed += len(moves)
            self._t_last_progress = now
            self._note_availability(now)
        else:
            self.moves_failed += len(moves)
            self._t_last_fail = now
        self.publish(now)

    def _apply(self, mv: Any) -> None:
        """One executed move against the placement view: remove the node
        from wherever it was, then (unless the move is a removal) place
        it in the move's state — mirroring ``Orchestrator.achieved_map``
        one move at a time."""
        d = self._placements.get(mv.partition)
        if d is None:  # a partition outside the begin map: ignore
            return
        was_available = self._primaries[mv.partition] > 0
        old = d.pop(mv.node, None)
        if old in self._primary_states:
            self._primaries[mv.partition] -= 1
        if mv.state:
            d[mv.node] = mv.state
            if mv.state in self._primary_states:
                self._primaries[mv.partition] += 1
        now_available = self._primaries[mv.partition] > 0
        if was_available != now_available:
            self._available += 1 if now_available else -1

    def strip_nodes(self, nodes: Iterable[str],
                    now: Optional[float] = None) -> None:
        """Drop every placement on ``nodes`` — the recovery-round
        presumption that a quarantined node's data is lost.  Mirrors
        ``rebalance._strip_nodes`` on the incremental view."""
        dead = set(nodes)
        if not dead:
            return
        for name, d in self._placements.items():
            was_available = self._primaries[name] > 0
            for n in list(d):
                if n in dead:
                    if d.pop(n) in self._primary_states:
                        self._primaries[name] -= 1
            now_available = self._primaries[name] > 0
            if was_available != now_available:
                self._available += 1 if now_available else -1
        self._note_availability(now)
        self.publish(now)

    def _note_availability(self, now: Optional[float] = None) -> None:
        """Append to the horizon timeline when availability changed
        (no-op unless ``track_timeline``).  The timeline is a step
        function: each entry holds from its ``t`` until the next."""
        if self._timeline is None:
            return
        a = self.availability()
        if a != self._timeline[-1][1]:
            t = self._clock() if now is None else now
            self._timeline.append((t, a))

    # -- gauges ---------------------------------------------------------------

    def availability(self) -> float:
        """available partitions / total partitions, in [0, 1]."""
        return self._available / self._total if self._total else 1.0

    def churn_ratio(self) -> float:
        """moves executed / minimum necessary (>= 0; 0 until a plan is
        pinned, 1.0 for a perfect single-pass run)."""
        return self.moves_executed / self._min_moves if self._min_moves \
            else 0.0

    def convergence_lag_s(self, now: Optional[float] = None) -> float:
        """Seconds since the last forward progress (executed move)."""
        t = self._clock() if now is None else now
        return max(t - self._t_last_progress, 0.0)

    def timeline(self) -> list[tuple[float, float]]:
        """The (t, availability) step timeline (empty unless
        ``track_timeline``); entry i holds from t_i until t_{i+1}."""
        return list(self._timeline) if self._timeline is not None else []

    def time_weighted_availability(
            self, now: Optional[float] = None) -> float:
        """Integral of the availability step function over [t0, now]
        divided by the duration — the fraction of partition-seconds
        that was serving.  Falls back to the instantaneous availability
        with no timeline or a zero-length horizon."""
        if self._timeline is None:
            return self.availability()
        t = self._clock() if now is None else now
        if t <= self._t0:
            return self.availability()
        total = 0.0
        for (t_i, a_i), (t_j, _a_j) in zip(self._timeline,
                                           self._timeline[1:]):
            total += (t_j - t_i) * a_i
        t_last, a_last = self._timeline[-1]
        total += (t - t_last) * a_last
        return total / (t - self._t0)

    def violation_intervals(
            self, now: Optional[float] = None) -> list[tuple[float, float]]:
        """Maximal [start, end) intervals with availability strictly
        below ``availability_floor`` (empty without a floor or
        timeline; an interval still open at ``now`` closes at it)."""
        if self._timeline is None or self._floor is None:
            return []
        t = self._clock() if now is None else now
        out: list[tuple[float, float]] = []
        open_at: Optional[float] = None
        for t_i, a_i in self._timeline:
            if a_i < self._floor and open_at is None:
                open_at = t_i
            elif a_i >= self._floor and open_at is not None:
                out.append((open_at, t_i))
                open_at = None
        if open_at is not None:
            out.append((open_at, max(t, open_at)))
        return out

    def violation_s(self, now: Optional[float] = None) -> float:
        """Cumulative seconds spent below the availability floor."""
        return sum(e - s for s, e in self.violation_intervals(now))

    def quarantine_exposure_s(self) -> dict[str, float]:
        """node -> cumulative quarantined seconds, from the attached
        HealthTracker (empty when no breaker is wired).  The tracker
        reads its OWN clock for the open interval — its ``tripped_at``
        stamps came from that clock, and mixing another clock's 'now'
        into the subtraction would corrupt the arithmetic (perf_counter
        and monotonic have unrelated epochs)."""
        if self._health is None:
            return {}
        out: dict[str, float] = self._health.exposures()
        return out

    # -- exposition -----------------------------------------------------------

    def publish(self, now: Optional[float] = None) -> None:
        """Write every gauge into the recorder (``slo.*``).  Collector-
        compatible: a MetricsServer calls this before each snapshot.
        No-op when the tracker was built with ``publish_gauges=False``
        (fleet mode: the rollup owns the process-wide gauges)."""
        if not self._publish_gauges:
            return
        rec = self._rec if self._rec is not None else get_recorder()
        t = self._clock() if now is None else now
        rec.set_gauge("slo.partition_availability", self.availability())
        rec.set_gauge("slo.churn_ratio", self.churn_ratio())
        rec.set_gauge("slo.convergence_lag_s", self.convergence_lag_s(t))
        rec.set_gauge("slo.moves_executed", self.moves_executed)
        rec.set_gauge("slo.moves_failed", self.moves_failed)
        rec.set_gauge("slo.min_moves", self._min_moves)
        if self._first_converged_lags:
            rec.set_gauge("slo.first_converged_lag_s",
                          self._first_converged_lags[-1])
        if self._timeline is not None:
            rec.set_gauge("slo.time_weighted_availability",
                          self.time_weighted_availability(t))
            if self._floor is not None:
                rec.set_gauge("slo.violation_seconds", self.violation_s(t))
        exposures = self.quarantine_exposure_s()
        rec.set_gauge("slo.quarantined_nodes", float(len(
            self._health.quarantined_nodes()) if self._health is not None
            else 0))
        for node, exposure in exposures.items():
            rec.set_gauge(
                f'slo.quarantine_exposure_s{{node="{_escape_label(node)}"}}',
                exposure)

    # -- serialization (durability snapshots) ---------------------------------

    def to_dict(self, now: Optional[float] = None) -> dict[str, Any]:
        """Versioned JSON-safe snapshot of the whole account — placement
        view, churn counters, incident state, and the horizon timeline.

        Every instant is stored as an AGE relative to ``now`` (the same
        epoch-free convention as ``HealthTracker.to_dict``): the clock
        that stamped the timeline dies with the process, so absolute
        instants would be meaningless to a restored tracker.  Ages keep
        every duration — integrals, dwell, lag — exact; only the
        absolute origin shifts to the new clock's epoch.
        """
        t = self._clock() if now is None else now
        return {
            "version": SLO_FORMAT_VERSION,
            "primary_states": sorted(self._primary_states),
            "placements": {name: dict(d)
                           for name, d in sorted(self._placements.items())},
            "min_moves": self._min_moves,
            "moves_executed": self.moves_executed,
            "moves_failed": self.moves_failed,
            "floor": self._floor,
            "last_progress_age_s": t - self._t_last_progress,
            "last_fail_age_s": (t - self._t_last_fail
                                if self._t_last_fail is not None else None),
            "incident_age_s": (t - self._incident_t0
                               if self._incident_t0 is not None else None),
            "incident_moves0": self._incident_moves0,
            "incident_fails0": self._incident_fails0,
            "first_converged_lags": list(self._first_converged_lags),
            "t0_age_s": t - self._t0,
            "timeline": ([[t - t_i, a] for t_i, a in self._timeline]
                         if self._timeline is not None else None),
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any], *,
                  clock: Optional[Callable[[], float]] = None,
                  recorder: Optional[Recorder] = None,
                  now: Optional[float] = None,
                  publish_gauges: bool = True) -> "SloTracker":
        """Rebuild a tracker on a NEW clock from :meth:`to_dict` output.
        Ages re-base onto the new clock (``instant = now - age``); the
        placement-derived counts (primaries, availability) are
        recomputed from the serialized view rather than trusted."""
        version = data.get("version")
        if version != SLO_FORMAT_VERSION:
            raise ValueError(
                f"slo snapshot version {version!r} != {SLO_FORMAT_VERSION} "
                f"(incompatible snapshot)")
        tracker = cls({}, primary_states=tuple(data["primary_states"]),
                      clock=clock, recorder=recorder,
                      availability_floor=data.get("floor"),
                      publish_gauges=publish_gauges)
        t = tracker._clock() if now is None else now
        tracker._placements = {
            str(name): {str(n): str(s) for n, s in d.items()}
            for name, d in data["placements"].items()}
        tracker._primaries = {
            name: sum(1 for s in d.values() if s in tracker._primary_states)
            for name, d in tracker._placements.items()}
        tracker._available = sum(
            1 for prim in tracker._primaries.values() if prim > 0)
        tracker._total = len(tracker._placements)
        tracker._min_moves = int(data["min_moves"])
        tracker.moves_executed = int(data["moves_executed"])
        tracker.moves_failed = int(data["moves_failed"])
        tracker._t_last_progress = t - float(data["last_progress_age_s"])
        last_fail = data.get("last_fail_age_s")
        tracker._t_last_fail = (t - float(last_fail)
                                if last_fail is not None else None)
        incident = data.get("incident_age_s")
        tracker._incident_t0 = (t - float(incident)
                                if incident is not None else None)
        tracker._incident_moves0 = int(data["incident_moves0"])
        tracker._incident_fails0 = int(data["incident_fails0"])
        tracker._first_converged_lags = [
            float(x) for x in data["first_converged_lags"]]
        tracker._t0 = t - float(data["t0_age_s"])
        timeline = data.get("timeline")
        tracker._timeline = (
            [(t - float(age), float(a)) for age, a in timeline]
            if timeline is not None else None)
        return tracker

    def summary(self, now: Optional[float] = None) -> SloSummary:
        t = self._clock() if now is None else now
        return SloSummary(
            availability=self.availability(),
            churn_ratio=self.churn_ratio(),
            convergence_lag_s=self.convergence_lag_s(t),
            moves_executed=self.moves_executed,
            moves_failed=self.moves_failed,
            min_moves=self._min_moves,
            partitions=self._total,
            available_partitions=self._available,
            quarantine_exposure_s=self.quarantine_exposure_s(),
            first_converged_lag_s=(self._first_converged_lags[-1]
                                   if self._first_converged_lags
                                   else None),
            first_converged_lags=list(self._first_converged_lags),
            time_weighted_availability=(
                self.time_weighted_availability(t)
                if self._timeline is not None else None),
            availability_floor=self._floor,
            violation_s=self.violation_s(t),
            violation_intervals=self.violation_intervals(t),
        )


@dataclass
class FleetSloSummary:
    """One fleet-wide SLO reading rolled up over every tenant loop
    (``FleetSloRollup.summary``; the fleet simulator's scorecard and
    the ``slo.fleet_*`` gauges' source of truth)."""

    tenants: int
    availability_min: float
    availability_mean: float
    tenants_below_floor: int
    availability_floor: Optional[float]
    moves_executed: int
    moves_failed: int
    violation_s: float
    # The tenant at availability_min (ties: first registration order) —
    # the "who is hurting" pointer the scorecard renders.
    worst_tenant: Optional[str] = None
    per_tenant: dict[str, SloSummary] = field(default_factory=dict)


class FleetSloRollup:
    """Fleet-wide rollup over per-tenant :class:`SloTracker`\\ s.

    The fleet-of-loops tier (``blance_tpu/fleetloop.py``) runs one
    tracker per tenant; this class aggregates them into one scorecard —
    minimum / mean availability across tenants, how many sit below the
    SLO floor, total executed/failed moves, cumulative violation
    seconds — published as ``slo.fleet_*`` / ``fleet.tenants`` gauges
    so the EXISTING exposition plane (``obs/expo.py``
    ``MetricsServer``) renders the whole fleet without any new
    endpoint.  ``publish`` is collector-compatible: pass it in a
    ``MetricsServer(collectors=...)`` so every scrape snapshots a fresh
    rollup.

    Single-task discipline (analysis/race_lint.py SHARED_STATE): every
    method is sync with no await — registration happens on the fleet
    controller's task, reads on the exposition snapshot path — so the
    registry cannot tear mid-rollup."""

    def __init__(self, availability_floor: Optional[float] = None,
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._rec = recorder
        self._floor = availability_floor
        self._clock: Callable[[], float] = (
            clock if clock is not None
            else (recorder.now if recorder is not None
                  else time.perf_counter))
        self._trackers: dict[str, SloTracker] = {}

    def register(self, key: str, tracker: SloTracker) -> None:
        """Adopt one tenant loop's tracker (re-registering a key
        replaces it — a re-onboarded tenant starts a fresh account)."""
        self._trackers[key] = tracker

    def forget(self, key: str) -> None:
        self._trackers.pop(key, None)

    def keys(self) -> list[str]:
        return list(self._trackers)

    def summary(self, now: Optional[float] = None,
                per_tenant: bool = True) -> FleetSloSummary:
        t = self._clock() if now is None else now
        avail: list[tuple[str, float]] = [
            (k, tr.availability()) for k, tr in self._trackers.items()]
        below = sum(1 for _k, a in avail
                    if self._floor is not None and a < self._floor)
        worst: Optional[str] = None
        amin = 1.0
        for k, a in avail:
            if a < amin:
                amin, worst = a, k
        return FleetSloSummary(
            tenants=len(avail),
            availability_min=amin if avail else 1.0,
            availability_mean=(sum(a for _k, a in avail) / len(avail)
                               if avail else 1.0),
            tenants_below_floor=below,
            availability_floor=self._floor,
            moves_executed=sum(tr.moves_executed
                               for tr in self._trackers.values()),
            moves_failed=sum(tr.moves_failed
                             for tr in self._trackers.values()),
            violation_s=sum(tr.violation_s(t)
                            for tr in self._trackers.values()),
            worst_tenant=worst,
            per_tenant=({k: tr.summary(t)
                         for k, tr in self._trackers.items()}
                        if per_tenant else {}),
        )

    def publish(self, now: Optional[float] = None) -> None:
        """Write the fleet gauges (collector-compatible, like
        :meth:`SloTracker.publish`)."""
        rec = self._rec if self._rec is not None else get_recorder()
        s = self.summary(now, per_tenant=False)
        rec.set_gauge("fleet.tenants", float(s.tenants))
        rec.set_gauge("slo.fleet_availability_min", s.availability_min)
        rec.set_gauge("slo.fleet_availability_mean", s.availability_mean)
        rec.set_gauge("slo.fleet_tenants_below_floor",
                      float(s.tenants_below_floor))
        rec.set_gauge("slo.fleet_violation_seconds", s.violation_s)
