"""Chrome trace-event export: open the pipeline in chrome://tracing / Perfetto.

``ChromeTraceSink`` collects finished spans and writes the trace-event JSON
object format (the stable subset both viewers load):

- one ``"X"`` (complete) event per live span — ``ts``/``dur`` in
  microseconds on the recorder's monotonic clock, ``pid`` the OS process,
  ``tid`` a dense integer per logical lane (asyncio task / thread / mover
  node), ``args`` the span attributes (plus span/parent ids for tooling);
- nestable async ``"b"``/``"e"`` pairs for overlappable spans (backdated
  lifecycles, queue waits recorded after the fact): they may partially
  overlap live slices on their lane, which ``"X"`` slices cannot express;
- ``"M"`` metadata events naming each lane, so Perfetto shows
  "mover:n0001" instead of a bare number;
- ``"C"`` counter events: one time-stamped sample per counter UPDATE
  (the sink implements the Recorder's live ``counter`` hook), so
  Perfetto renders counter tracks evolving on the same timeline as the
  spans — retries ramping during a flaky stretch, move totals climbing
  batch by batch — plus one final sample per counter at the trace end
  so the track closes at its end-of-run value.

``trace(...)`` is the one-call wrapper (bench.py ``--trace-out`` uses it):
it attaches the sink, runs the body under ``device_profile`` when a TPU log
dir is given — both captures cover the same wall-clock window, so host
spans and the TPU trace (opened side-by-side in Perfetto) line up — and
writes the JSON on exit.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Iterator, Optional

from .recorder import Recorder, Span, get_recorder

__all__ = ["ChromeTraceSink", "write_chrome_trace", "trace"]


class ChromeTraceSink:
    """Collects spans and serializes them as trace-event JSON."""

    def __init__(self, recorder: Optional[Recorder] = None) -> None:
        self._t0 = (recorder or get_recorder()).t0
        self._spans: list[Span] = []
        self._counter_samples: list[tuple[float, str, float]] = []
        self._lock = threading.Lock()

    def span(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp)

    def counter(self, name: str, value: float, t: float) -> None:
        """Live counter sample (the Recorder calls this on every
        ``count``): becomes one time-stamped "C" event, so the counter
        renders as a track over time, not just a final value."""
        with self._lock:
            self._counter_samples.append((t, name, value))

    def close(self) -> None:
        pass

    def events(self, counters: Optional[dict] = None) -> list[dict]:
        """The traceEvents list (see module docstring for the shapes)."""
        with self._lock:
            spans = list(self._spans)
            samples = list(self._counter_samples)
        pid = os.getpid()
        tids: dict[str, int] = {}
        events: list[dict] = []
        for lane in sorted({sp.task for sp in spans}):
            tids[lane] = len(tids) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tids[lane], "args": {"name": lane},
            })
        t_last = 0.0
        for sp in spans:
            ts = max(sp.t_start - self._t0, 0.0) * 1e6
            dur = max(sp.duration_s, 0.0) * 1e6
            t_last = max(t_last, ts + dur)
            args = {str(k): v for k, v in sp.attrs.items()}
            args["span_id"] = sp.span_id
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            if sp.overlappable:
                # Backdated spans (queue waits, move lifecycles) may
                # partially overlap live slices on their lane, which the
                # "X" format forbids (slices on one track must nest) —
                # emit them as nestable async begin/end pairs instead,
                # which both viewers render on overlap-tolerant tracks.
                ident = f"0x{sp.span_id:x}"
                common = {"name": sp.name, "cat": "obs", "pid": pid,
                          "tid": tids[sp.task], "id": ident}
                events.append({**common, "ph": "b", "ts": ts,
                               "args": args})
                events.append({**common, "ph": "e", "ts": ts + dur})
            else:
                events.append({
                    "name": sp.name, "ph": "X", "ts": ts, "dur": dur,
                    "pid": pid, "tid": tids[sp.task], "args": args,
                })
        # Live counter samples, time-ordered: the evolving track.
        for t, name, value in sorted(samples):
            ts = max(t - self._t0, 0.0) * 1e6
            t_last = max(t_last, ts)
            events.append({
                "name": name, "ph": "C", "ts": ts, "pid": pid,
                "args": {"value": value},
            })
        # Final values close every track at the trace end (and cover
        # counters bumped before the sink was attached).
        for name, value in sorted((counters or {}).items()):
            events.append({
                "name": name, "ph": "C", "ts": t_last, "pid": pid,
                "args": {"value": value},
            })
        return events

    def write(self, path: str, counters: Optional[dict] = None) -> None:
        payload = {
            "traceEvents": self.events(counters),
            "displayTimeUnit": "ms",
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)


def write_chrome_trace(path: str, sink: ChromeTraceSink,
                       recorder: Optional[Recorder] = None) -> None:
    """Write ``sink``'s collected spans (plus ``recorder``'s final counter
    values) as a Chrome trace file.  The sink is required because the
    Recorder retains no spans itself — only sinks do."""
    rec = recorder or get_recorder()
    sink.write(path, counters=dict(rec.counters))


@contextlib.contextmanager
def trace(path: str, recorder: Optional[Recorder] = None,
          device_log_dir: Optional[str] = None) -> Iterator[ChromeTraceSink]:
    """Capture every span finished inside the body into a Chrome trace at
    ``path``.  With ``device_log_dir``, the body also runs under
    ``utils.trace.device_profile`` so the XLA/TPU profile covers the same
    interval as the host spans (open both in Perfetto to correlate).
    The file is written even when the body raises — a crashed run's trace
    is exactly the one worth reading."""
    from ..utils.trace import device_profile

    rec = recorder or get_recorder()
    sink = ChromeTraceSink(rec)
    # Write an empty-but-valid trace up front: a bad path fails HERE,
    # before hours of instrumented work, never in the finally below
    # (where it would also mask the body's own exception).
    sink.write(path)
    rec.add_sink(sink)
    try:
        with device_profile(device_log_dir):
            yield sink
    finally:
        rec.remove_sink(sink)
        sink.write(path, counters=dict(rec.counters))
