"""Span sinks: where finished spans go.

A sink is any object with a ``span(span)`` method (and an optional
``close()``).  The Recorder itself keeps only aggregates; retention is the
sink's job, so attaching no sink costs no memory growth.  A sink may also
define ``counter(name, value, t)`` to receive live counter updates (the
Chrome exporter builds time-series counter tracks from them).

- ``InMemorySink``: keeps Span objects — the test/debug sink.
- ``JsonlSink``: one JSON object per finished span, streamed to a file —
  the production log-shipping shape (grep-able, tail-able, no buffering
  of the whole trace in memory).  With ``max_bytes`` set the file is
  size-capped and rotated (``path`` -> ``path.1`` -> ... -> ``path.N``),
  so an un-rotated sink can't grow unboundedly in a long-running
  service.
"""

from __future__ import annotations

import json
import os
import threading
from typing import IO, Optional, Protocol, Union, runtime_checkable

from .recorder import Span

__all__ = ["Sink", "InMemorySink", "JsonlSink", "span_to_dict"]


@runtime_checkable
class Sink(Protocol):
    """The structural contract a sink implements (duck-typed; this
    Protocol names it for annotations and the static tier).  The
    optional ``counter(name, value, t)`` hook is deliberately absent:
    the Recorder feature-detects it with ``hasattr``, so span-only
    sinks stay two methods."""

    def span(self, sp: Span) -> None: ...

    def close(self) -> None: ...


def span_to_dict(sp: Span, t0: float = 0.0) -> dict:
    """JSON-serializable view of a span; times shifted by ``t0`` so
    exported timestamps start near zero."""
    return {
        "name": sp.name,
        "t_start_s": sp.t_start - t0,
        "duration_s": sp.duration_s,
        "span_id": sp.span_id,
        "parent_id": sp.parent_id,
        "task": sp.task,
        "attrs": sp.attrs,
    }


class InMemorySink:
    """Retains every finished span (tests, small traces)."""

    def __init__(self) -> None:
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def span(self, sp: Span) -> None:
        with self._lock:
            self.spans.append(sp)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [sp for sp in self.spans if sp.name == name]

    def close(self) -> None:  # symmetry with file-backed sinks
        pass


class JsonlSink:
    """Streams spans as JSON lines to ``path`` (or an open file object).

    Lines are written and flushed per span under a lock, so concurrent
    asyncio tasks / threads interleave whole records, never bytes.

    Rotation (path-owned sinks only): with ``max_bytes`` set, a write
    that carries the file to or past the cap closes it, shifts
    ``path.{i}`` -> ``path.{i+1}`` keeping the newest ``keep`` rotated
    files, renames ``path`` -> ``path.1``, and reopens ``path`` fresh.
    Rotation happens AFTER the triggering line is written whole, so a
    record is never split across files and every rotated file is valid
    JSONL; the cap is therefore a high-water mark, overshot by at most
    one record."""

    def __init__(self, path_or_file: Union[str, IO], t0: float = 0.0,
                 max_bytes: Optional[int] = None, keep: int = 3) -> None:
        self._own = isinstance(path_or_file, str)
        self._path: Optional[str] = path_or_file if self._own else None
        if max_bytes is not None and not self._own:
            raise ValueError("rotation (max_bytes) requires a path-owned "
                             "sink, not an open file object")
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self._max_bytes = max_bytes
        self._keep = keep
        self._f: Optional[IO] = (
            open(path_or_file, "w") if self._own else path_or_file)
        self._t0 = t0
        self._lock = threading.Lock()

    def span(self, sp: Span) -> None:
        with self._lock:
            if self._f is None:
                return
            json.dump(span_to_dict(sp, self._t0), self._f,
                      default=str, separators=(",", ":"))
            self._f.write("\n")
            self._f.flush()
            if self._max_bytes is not None and \
                    self._f.tell() >= self._max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        """Shift the rotation chain and reopen; caller holds the lock.
        ``os.replace`` onto ``path.keep`` drops the oldest file."""
        assert self._f is not None and self._path is not None
        self._f.close()
        for i in range(self._keep - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._f = open(self._path, "w")

    def close(self) -> None:
        with self._lock:
            if self._f is not None and self._own:
                self._f.close()
            self._f = None
