"""Span sinks: where finished spans go.

A sink is any object with a ``span(span)`` method (and an optional
``close()``).  The Recorder itself keeps only aggregates; retention is the
sink's job, so attaching no sink costs no memory growth.

- ``InMemorySink``: keeps Span objects — the test/debug sink.
- ``JsonlSink``: one JSON object per finished span, streamed to a file —
  the production log-shipping shape (grep-able, tail-able, no buffering
  of the whole trace in memory).
"""

from __future__ import annotations

import json
import threading
from typing import IO, Optional, Protocol, Union, runtime_checkable

from .recorder import Span

__all__ = ["Sink", "InMemorySink", "JsonlSink", "span_to_dict"]


@runtime_checkable
class Sink(Protocol):
    """The structural contract a sink implements (duck-typed; this
    Protocol names it for annotations and the static tier)."""

    def span(self, sp: Span) -> None: ...

    def close(self) -> None: ...


def span_to_dict(sp: Span, t0: float = 0.0) -> dict:
    """JSON-serializable view of a span; times shifted by ``t0`` so
    exported timestamps start near zero."""
    return {
        "name": sp.name,
        "t_start_s": sp.t_start - t0,
        "duration_s": sp.duration_s,
        "span_id": sp.span_id,
        "parent_id": sp.parent_id,
        "task": sp.task,
        "attrs": sp.attrs,
    }


class InMemorySink:
    """Retains every finished span (tests, small traces)."""

    def __init__(self) -> None:
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def span(self, sp: Span) -> None:
        with self._lock:
            self.spans.append(sp)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [sp for sp in self.spans if sp.name == name]

    def close(self) -> None:  # symmetry with file-backed sinks
        pass


class JsonlSink:
    """Streams spans as JSON lines to ``path`` (or an open file object).

    Lines are written and flushed per span under a lock, so concurrent
    asyncio tasks / threads interleave whole records, never bytes."""

    def __init__(self, path_or_file: Union[str, IO], t0: float = 0.0) -> None:
        self._own = isinstance(path_or_file, str)
        self._f: Optional[IO] = (
            open(path_or_file, "w") if self._own else path_or_file)
        self._t0 = t0
        self._lock = threading.Lock()

    def span(self, sp: Span) -> None:
        with self._lock:
            if self._f is None:
                return
            json.dump(span_to_dict(sp, self._t0), self._f,
                      default=str, separators=(",", ":"))
            self._f.write("\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None and self._own:
                self._f.close()
            self._f = None
