"""Streaming metrics exposition: a Prometheus text-format endpoint.

PR 1's obs core was post-hoc — spans and histograms readable only after
the run.  This module makes telemetry a live subsystem: an asyncio HTTP
endpoint serves the Recorder's aggregates in the Prometheus text format
(version 0.0.4, the stable subset every scraper parses), so a
long-running rebalance serving real traffic is observable WHILE it
executes.  Three pieces:

- :class:`MetricsRegistry` — the single declarative table of every
  metric the pipeline emits: internal dotted name, type (counter /
  gauge / histogram), and help string.  ``default_registry()`` builds
  the blance_tpu table (plan, moves, orchestrate, rebalance, slo,
  costmodel groups; the ``orchestrate.tot_*`` progress mirror is
  generated from ``OrchestratorProgress``'s own fields so the mirror
  can never drift from the dataclass).  The drift-guard test pins this
  table against both the names actually emitted during a pipeline run
  and the metric table in docs/OBSERVABILITY.md.
- :func:`render_prometheus` — one Recorder snapshot rendered as
  exposition text.  Counters get a ``_total`` suffix; histograms render
  cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count`` series straight
  off the Recorder's EXACT bucket counts; gauges render last-value
  samples, including labeled families (a gauge key of the form
  ``name{label="x"}`` carries its label set through verbatim).  Every
  DECLARED metric is rendered (zero-valued when never emitted), so a
  scrape is a complete, stable schema from the first request.
- :class:`MetricsServer` — a minimal asyncio HTTP/1.1 server for
  ``GET /metrics``.  Renders are throttled to one Recorder snapshot per
  ``min_interval_s`` (scrapes between snapshots serve the cached text),
  and ``collectors`` callables run before each snapshot — the SLO
  tracker's ``publish`` hook plugs in there so time-derived gauges
  (convergence lag) are fresh per snapshot.

Pure asyncio + stdlib; no sockets are touched until ``start()``, and
``render_prometheus`` needs no event loop at all — the virtual-time
tests drive it directly under ``DeterministicLoop``.

CLI (the CI ``obs-smoke`` step)::

    python -m blance_tpu.obs.expo --smoke

runs a seeded chaos rebalance (30% flaky + a dead node) with the
endpoint live, scrapes it mid-run and again later, and asserts the
output parses, counters are monotone between scrapes, every registry
metric is present, and availability stays in [0, 1].
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Sequence

from .recorder import Recorder, get_recorder

__all__ = [
    "Metric",
    "MetricsRegistry",
    "default_registry",
    "render_prometheus",
    "parse_prometheus",
    "MetricsServer",
    "scrape",
    "main",
]

_KINDS = ("counter", "gauge", "histogram")


@dataclass(frozen=True)
class Metric:
    """One declared metric: internal dotted name, type, help string."""

    name: str  # e.g. "orchestrate.move_latency_s"
    kind: str  # "counter" | "gauge" | "histogram"
    help: str

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"metric {self.name!r}: unknown kind "
                             f"{self.kind!r} (want one of {_KINDS})")


def _prom_base(name: str) -> str:
    """Dotted internal name -> Prometheus-legal base name."""
    return "blance_" + name.replace(".", "_").replace("-", "_")


class MetricsRegistry:
    """The declarative metric table the exposition renders from.

    One entry per (name, kind) — ``plan.solve.sweeps`` is legitimately
    both a counter (total passes) and a histogram (passes per solve),
    and the two render under distinct Prometheus names (``_total`` vs
    ``_bucket``/``_sum``/``_count``)."""

    def __init__(self, metrics: Iterable[Metric]) -> None:
        self._by_key: dict[tuple[str, str], Metric] = {}
        seen_prom: dict[str, tuple[str, str]] = {}
        for m in metrics:
            key = (m.name, m.kind)
            if key in self._by_key:
                raise ValueError(f"duplicate metric declaration {key}")
            pname = self.prom_name(m)
            if pname in seen_prom:
                raise ValueError(
                    f"metric {key} renders to Prometheus name {pname!r} "
                    f"already taken by {seen_prom[pname]}")
            seen_prom[pname] = key
            self._by_key[key] = m

    def metrics(self) -> list[Metric]:
        return sorted(self._by_key.values(), key=lambda m: (m.name, m.kind))

    def declared(self, name: str, kind: str) -> bool:
        return (name, kind) in self._by_key

    @staticmethod
    def prom_name(metric: Metric) -> str:
        base = _prom_base(metric.name)
        return base + "_total" if metric.kind == "counter" else base

    def names(self, kind: Optional[str] = None) -> set[str]:
        return {n for (n, k) in self._by_key if kind is None or k == kind}

    def undeclared(self, recorder: Recorder) -> list[str]:
        """Every (kind, name) the recorder holds that this registry does
        not declare — the drift-guard's 'no undeclared emissions' check.
        Labeled gauge keys are matched on their base name."""
        out: list[str] = []
        with recorder._lock:  # consistent snapshot vs concurrent emits
            counters = list(recorder.counters)
            gauges = list(recorder.gauges)
            hists = list(recorder._hist_stats)
        for kind, keys in (("counter", counters), ("gauge", gauges),
                           ("histogram", hists)):
            for key in keys:
                base = key.split("{", 1)[0]
                if not self.declared(base, kind):
                    out.append(f"{kind}:{base}")
        return sorted(set(out))


_REGISTRY: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The blance_tpu metric table, built lazily (the ``orchestrate.tot_*``
    mirror enumerates ``OrchestratorProgress``'s fields, and importing
    orchestrate at module-import time would be circular: orchestrate
    itself imports obs)."""
    global _REGISTRY
    if _REGISTRY is not None:
        return _REGISTRY
    from ..orchestrate.orchestrator import OrchestratorProgress

    metrics: list[Metric] = [
        # -- plan ------------------------------------------------------------
        Metric("plan.solve.calls", "counter",
               "solver invocations (cold solves + warm repair attempts)"),
        Metric("plan.solve.sweeps", "counter",
               "converged-loop passes executed, summed over all solves"),
        Metric("plan.solve.sweeps", "histogram",
               "converged-loop passes per solve"),
        Metric("plan.solve.carry_hit", "counter",
               "warm replans whose carry-seeded repair was accepted"),
        Metric("plan.solve.carry_miss", "counter",
               "replans with no usable solver carry"),
        Metric("plan.solve.warm_fallback", "counter",
               "warm repairs declined or failed, falling back to cold"),
        Metric("plan.solve.dirty_fraction", "histogram",
               "fraction of partitions each delta replan marked dirty"),
        Metric("plan.engine_fallback", "counter",
               "score-engine fallbacks (fused -> matrix)"),
        # -- fused plan pipeline (plan/tensor.plan_pipeline +
        # PlannerSession.replan_with_moves) ---------------------------------
        Metric("plan.pipeline.calls", "counter",
               "fused plan-pipeline invocations (solve->diff->pack in "
               "one device dispatch)"),
        Metric("plan.pipeline.warm", "counter",
               "pipeline dispatches resolved by the one-sweep warm "
               "repair (accepted through every gate)"),
        Metric("plan.pipeline.fallback", "counter",
               "pipeline dispatch failures degraded to the staged "
               "encode/solve/decode path"),
        Metric("plan.pipeline.dispatch_s", "histogram",
               "wall-clock seconds per fused pipeline device dispatch "
               "(solve + diff + pack, one program)"),
        # -- sparse shortlist solver (plan/tensor.solve_sparse +
        # core/shortlist.py + parallel/sharded.solve_sparse_sharded) ----------
        Metric("plan.sparse.shortlist_build_s", "histogram",
               "seconds to derive the per-partition top-K candidate "
               "shortlist (host entries; the fused sparse pipeline "
               "builds it in-dispatch instead)"),
        Metric("plan.sparse.k_effective", "gauge",
               "candidate columns per partition (K) of the most recent "
               "sparse solve"),
        Metric("plan.sparse.shortlist_exhausted", "counter",
               "partitions flagged by the sparse solve with no "
               "acceptable shortlist candidate for some slot"),
        Metric("plan.sparse.dense_fallback_rows", "counter",
               "exhausted partitions re-placed by the per-row dense "
               "fallback"),
        Metric("plan.greedy.candidates", "histogram",
               "candidates scored per greedy (partition, state) pick"),
        # -- moves -----------------------------------------------------------
        Metric("moves.diff_partitions", "counter",
               "partitions diffed by the batched device move calculus"),
        Metric("moves.irregular_partitions", "counter",
               "partitions routed to the host loop by the batched diff"),
        Metric("moves.total_ops", "counter",
               "move operations produced by the batched diff"),
        # -- orchestrate (beyond the tot_* mirror) ---------------------------
        Metric("orchestrate.retries", "counter",
               "backoff-scheduled retry attempts"),
        Metric("orchestrate.retry_backoff_s", "histogram",
               "seconds each scheduled retry backed off"),
        Metric("orchestrate.timeouts", "counter",
               "async assign callbacks cancelled at move_timeout_s"),
        Metric("orchestrate.quarantine_trips", "counter",
               "circuit-breaker entries into quarantine"),
        Metric("orchestrate.move_failures", "counter",
               "structured MoveFailures recorded (abandoned moves)"),
        Metric("orchestrate.missing_mover", "counter",
               "moves targeting a node with no mover (outside nodes_all)"),
        Metric("orchestrate.errors", "counter",
               "errors folded into the progress stream (legacy aborts, "
               "mover exits)"),
        Metric("orchestrate.task_exceptions", "counter",
               "orchestration tasks that died with an escaped exception"),
        Metric("orchestrate.move_latency_s", "histogram",
               "per-partition-move callback latency (batch exec amortized "
               "across its moves)"),
        # -- rebalance -------------------------------------------------------
        Metric("rebalance.recovery_rounds", "counter",
               "failure-aware recovery replan rounds entered"),
        Metric("rebalance.unconverged", "counter",
               "rebalances/controller cycles that exhausted their "
               "recovery budget with failures still outstanding"),
        Metric("rebalance.degraded", "counter",
               "recovery replans degraded structurally (e.g. empty "
               "candidate node set) instead of raising"),
        # -- slo (obs/slo.py; formulas in docs/OBSERVABILITY.md) -------------
        Metric("slo.partition_availability", "gauge",
               "fraction of partitions with at least one serving primary"),
        Metric("slo.churn_ratio", "gauge",
               "moves executed / minimum necessary (the primary plan)"),
        Metric("slo.convergence_lag_s", "gauge",
               "seconds since the last successfully executed move"),
        Metric("slo.moves_executed", "gauge",
               "partition moves successfully executed so far (monotone)"),
        Metric("slo.moves_failed", "gauge",
               "partition moves that failed or were rejected (monotone)"),
        Metric("slo.min_moves", "gauge",
               "the primary plan's move count (the churn denominator)"),
        Metric("slo.quarantined_nodes", "gauge",
               "nodes currently quarantined or half-open"),
        Metric("slo.quarantine_exposure_s", "gauge",
               "cumulative seconds each node has spent quarantined "
               "(labeled per node)"),
        Metric("slo.time_weighted_availability", "gauge",
               "integral of availability over the run / duration "
               "(horizon accounting; emitted when timeline tracking "
               "is on)"),
        Metric("slo.violation_seconds", "gauge",
               "cumulative seconds availability sat below the "
               "configured SLO floor"),
        Metric("slo.first_converged_lag_s", "gauge",
               "per-incident seconds from incident open to the last "
               "required move executed (the rebalance makespan the "
               "scheduler minimizes; last closed incident)"),
        # -- sched (orchestrate/sched; docs/SCHEDULER.md) ---------------------
        Metric("sched.makespan_predicted_s", "gauge",
               "list-scheduled makespan of the current move DAG on the "
               "node lanes, priced by the calibrated cost model"),
        Metric("sched.makespan_actual_s", "gauge",
               "achieved makespan of the finished orchestration (bind "
               "to last executed move)"),
        Metric("sched.critical_path_s", "gauge",
               "longest scheduled dependency chain by predicted cost "
               "(the makespan lower bound; stalled tails excluded)"),
        Metric("sched.lane_utilization", "gauge",
               "predicted busy fraction of the active nodes' lanes "
               "across the scheduled makespan"),
        Metric("sched.makespan_rel_err", "histogram",
               "relative error of the predicted vs achieved makespan, "
               "scored as each orchestration winds down"),
        Metric("sched.reschedules", "counter",
               "online schedule rebuilds (health-breaker quarantine "
               "or heal mid-schedule)"),
        Metric("sched.host_ranks", "counter",
               "upward-rank sweeps computed on host (move set below "
               "the device threshold)"),
        Metric("sched.device_ranks", "counter",
               "upward-rank sweeps dispatched on device (jitted "
               "leveled-DAG scan)"),
        # -- sim (rebalance.RebalanceController + testing/simulate.py) -------
        Metric("sim.events", "counter",
               "scenario trace events applied by the simulator driver"),
        Metric("sim.deltas", "counter",
               "cluster deltas submitted to the rebalance controller"),
        Metric("sim.rebalances", "counter",
               "orchestration passes the control loop started"),
        Metric("sim.superseded", "counter",
               "in-flight rebalances cancelled because a newer delta "
               "invalidated them (resumed from the achieved map)"),
        Metric("sim.degraded_plans", "counter",
               "planning steps that applied a graceful-degradation "
               "policy (replica shed / empty candidate set)"),
        Metric("sim.convergence_lag_s", "histogram",
               "per-incident seconds from cluster-delta submission to "
               "the control loop's next quiesce"),
        # -- costmodel (obs/costmodel.py) ------------------------------------
        Metric("costmodel.updates", "counter",
               "EWMA cost-model updates from move-lifecycle spans"),
        Metric("costmodel.rel_err", "histogram",
               "relative error of the cost prediction vs the observed "
               "per-move cost, at update time"),
        Metric("costmodel.cold_predictions", "counter",
               "predictions served without an exact (node, op) "
               "estimate (op-prior / global / default fallback)"),
        # -- fleet (plan/fleet.py + plan/service.py) -------------------------
        Metric("fleet.requests", "counter",
               "tenant plan requests submitted to the plan service"),
        Metric("fleet.batches", "counter",
               "fleet batch device dispatches (one per bucket class x "
               "warm/cold)"),
        Metric("fleet.dispatcher_crashes", "counter",
               "plan-service dispatcher tasks that died with an escaped "
               "exception"),
        Metric("fleet.queue_depth", "gauge",
               "plan requests waiting in the service's bounded queue"),
        Metric("fleet.batch_tenants", "histogram",
               "real tenants per fleet batch dispatch"),
        Metric("fleet.batch_occupancy", "histogram",
               "real tenants / padded batch size per dispatch (mesh "
               "divisibility padding included)"),
        Metric("fleet.admission_latency_s", "histogram",
               "seconds from plan-service submit to resolved result"),
        Metric("fleet.dispatch_s", "histogram",
               "wall-clock seconds per fleet batch device dispatch"),
        Metric("fleet.request_segment_s", "histogram",
               "per-request latency decomposition (labeled by segment: "
               "admission/coalesce/executor_queue/device/resolve; the "
               "segments tile submit-to-resolve exactly)"),
        # -- fleet of control loops (blance_tpu/fleetloop.py +
        # plan/service.py fairness + plan/carry.py evictions) ----------------
        Metric("fleet.starved_admissions", "counter",
               "plan requests rolled out of a coalescing window by the "
               "per-tenant fair-share quota (one count per deferral "
               "event; the cross-tenant starvation observable)"),
        Metric("fleet.carry_evictions", "counter",
               "warm-carry cache evictions, labeled by reason (bytes = "
               "byte-budget LRU, entries = key-count LRU drop, shape = "
               "re-shaped problem reset) — every one costs the key one "
               "cold solve"),
        # -- encode residency (plan/resident.py + fleetloop.py
        # ServicePlanner; docs/DESIGN.md "Encode residency") ------------------
        Metric("fleet.encode_cold", "counter",
               "full encode_problem runs that (re)established resident "
               "state: a tenant's first cycle, or one after a counted "
               "demotion/eviction (tenants <= cold <= tenants + "
               "demotions + evictions; out-of-protocol tenants' "
               "every-cycle full encodes show as fleet.decode_full "
               "instead)"),
        Metric("fleet.encode_warm", "counter",
               "converge cycles served by delta-patching the resident "
               "encode state (O(delta) host work, no re-encode)"),
        Metric("fleet.encode_demotions", "counter",
               "resident encode states dropped by the conservative "
               "protocol, labeled by reason (divergence = pass/strip "
               "did not land the held map, statics = model/options "
               "swap, nodes = node-list drift, shape = slot-depth "
               "drift) — each costs the key one cold re-encode"),
        Metric("fleet.encode_evictions", "counter",
               "resident encode states dropped by the EncodeCache "
               "budgets, labeled by reason (bytes / entries) — each "
               "costs the key one cold re-encode"),
        Metric("fleet.encode_patch_rows", "histogram",
               "prev/weight rows written per resident delta patch "
               "(strip scatters, weight-drift rows, adopted-pass "
               "scatters, dark-set flips)"),
        Metric("fleet.encode_patch_bytes", "counter",
               "array bytes written by resident encode delta patches — "
               "the warm cycle's whole fresh-data footprint (bounded "
               "by dirty rows + scalars; the perf-smoke gate pins it)"),
        Metric("fleet.decode_full", "counter",
               "full decode_assignment runs on the planner path (cold "
               "cycles, first decode after a cold encode, pass-through "
               "tenants)"),
        Metric("fleet.decode_patch", "counter",
               "incremental decodes: held map patched at the changed "
               "rows, bit-identical to the full decode"),
        Metric("fleet.decode_dirty_rows", "histogram",
               "rows rebuilt per incremental decode (the rows the "
               "solve actually changed)"),
        Metric("fleet.h2d_bytes", "counter",
               "host->device bytes shipped as stacked fleet batch "
               "tensors, summed per dispatch"),
        Metric("fleet.tenants", "gauge",
               "tenant control loops registered with the fleet rollup"),
        Metric("fleet.converge_cycles", "gauge",
               "converge cycles completed across every tenant loop "
               "(fleet-controller rollup)"),
        Metric("slo.fleet_availability_min", "gauge",
               "minimum partition availability across all tenant loops "
               "(the fleet's worst tenant)"),
        Metric("slo.fleet_availability_mean", "gauge",
               "mean partition availability across all tenant loops"),
        Metric("slo.fleet_tenants_below_floor", "gauge",
               "tenant loops currently below their availability floor"),
        Metric("slo.fleet_violation_seconds", "gauge",
               "cumulative SLO-violation seconds summed across all "
               "tenant loops"),
        # -- durability (blance_tpu/durability; docs/DURABILITY.md) ----------
        Metric("durability.journal_records", "counter",
               "records appended to the write-ahead journal (all kinds, "
               "all tenants)"),
        Metric("durability.journal_bytes", "counter",
               "bytes appended to the write-ahead journal (framing "
               "included)"),
        Metric("durability.segments_rotated", "counter",
               "journal segment rotations (a fresh crash-atomically "
               "birthed segment file every rotate_records appends)"),
        Metric("durability.snapshots", "counter",
               "state snapshots written (controller map + membership + "
               "breaker/SLO/cost state; the pointer record is the "
               "commit point)"),
        Metric("durability.torn_tail", "counter",
               "journal segments whose final record was torn (partial "
               "write / CRC or framing failure), truncated to the last "
               "valid prefix at replay"),
        Metric("durability.recoveries", "counter",
               "recover() invocations: journal replays that rebuilt "
               "controller state and fenced a new epoch"),
        Metric("durability.replayed_records", "counter",
               "journal records folded into recovered state across all "
               "recoveries"),
        Metric("durability.stale_epoch_rejections", "counter",
               "writes or move completions rejected because their "
               "captured epoch lost the fence (zombie pre-crash writer "
               "or stale process) — counted, never applied"),
        Metric("durability.recovery_cold_solves", "counter",
               "resumed controllers whose first plan is a cold solve "
               "(carry/encode caches are deliberately not persisted; "
               "bounded by the fleet demotion attribution identity)"),
        # -- device (obs/device.py; all emitted only while the device
        # observatory is enabled) ---------------------------------------------
        Metric("device.compiles", "counter",
               "XLA compilations, labeled by owning entry point "
               "(solve_dense cold/carry/warm/bucketed, fleet batch "
               "classes, sharded dispatch, other)"),
        Metric("device.compile_s", "histogram",
               "seconds per XLA backend compilation (labeled by entry)"),
        Metric("device.cost_analyses", "counter",
               "AOT cost/memory analyses published (one per entry x "
               "bucket-shape, memoized)"),
        Metric("device.flops", "gauge",
               "XLA cost-analysis FLOPs per dispatch of the compiled "
               "program (labeled entry + bucket-shape klass)"),
        Metric("device.hbm_bytes", "gauge",
               "XLA cost-analysis bytes accessed per dispatch (labeled "
               "entry + klass)"),
        Metric("device.peak_alloc_bytes", "gauge",
               "XLA memory-analysis argument+output+temp bytes for the "
               "compiled program (labeled entry + klass)"),
        Metric("device.sweep_accept_frac", "histogram",
               "per-sweep accepted-bid fraction of the converged solve "
               "(also a Chrome counter track under the solve span)"),
    ]
    metrics.extend(
        Metric("orchestrate." + name, "counter",
               f"progress counter mirror of OrchestratorProgress.{name}")
        for name in OrchestratorProgress().__dict__
        if name != "errors")
    _REGISTRY = MetricsRegistry(metrics)
    return _REGISTRY


# -- rendering ---------------------------------------------------------------


def _fmt(v: float) -> str:
    """Deterministic sample formatting: integral floats render as ints
    (the common counter case), everything else as repr (full precision,
    stable across platforms)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2 ** 53:
        return str(int(f))
    return repr(f)


def render_prometheus(recorder: Optional[Recorder] = None,
                      registry: Optional[MetricsRegistry] = None) -> str:
    """One Recorder snapshot as Prometheus text format (0.0.4).

    Registry-driven: every declared metric appears (HELP + TYPE + at
    least one sample, zero-valued when never emitted), so the scrape
    schema is complete and stable from the first request.  Recorder
    names NOT in the registry are deliberately omitted — the drift
    guard makes that set empty for the shipped pipeline."""
    rec = recorder if recorder is not None else get_recorder()
    reg = registry if registry is not None else default_registry()
    with rec._lock:  # the Recorder is counted from threads too; copying
        counters = dict(rec.counters)  # an unlocked dict mid-insert can
        gauges = dict(rec.gauges)  # raise 'changed size during iteration'
        hist_keys = list(rec._hist_stats)
    lines: list[str] = []

    def _render_hist(key: str, pname: str, labels: str) -> None:
        """One histogram series (base or labeled).  ``labels`` is the
        inner label list ('' for the base series); the le label composes
        with it inside one brace set, per the exposition format."""
        hb = rec.histogram_buckets(key)
        sep = "," if labels else ""
        suffix = f"{{{labels}}}" if labels else ""
        if hb is None:
            lines.append(f'{pname}_bucket{{{labels}{sep}le="+Inf"}} 0')
            lines.append(f"{pname}_sum{suffix} 0")
            lines.append(f"{pname}_count{suffix} 0")
            return
        bounds, cum, count, total = hb
        for b, c in zip(bounds, cum):
            lines.append(
                f'{pname}_bucket{{{labels}{sep}le="{_fmt(b)}"}} {c}')
        lines.append(f'{pname}_bucket{{{labels}{sep}le="+Inf"}} {cum[-1]}')
        lines.append(f"{pname}_sum{suffix} {_fmt(total)}")
        lines.append(f"{pname}_count{suffix} {count}")

    for m in reg.metrics():
        pname = reg.prom_name(m)
        lines.append(f"# HELP {pname} {m.help}")
        lines.append(f"# TYPE {pname} {m.kind}")
        if m.kind == "counter":
            labeled = sorted(k for k in counters
                             if k.startswith(m.name + "{"))
            if m.name in counters or not labeled:
                lines.append(f"{pname} {_fmt(counters.get(m.name, 0))}")
            for key in labeled:
                lines.append(f"{pname}{key[len(m.name):]} "
                             f"{_fmt(counters[key])}")
        elif m.kind == "gauge":
            labeled = sorted(k for k in gauges
                             if k.startswith(m.name + "{"))
            if m.name in gauges:
                lines.append(f"{pname} {_fmt(gauges[m.name])}")
            for key in labeled:
                lines.append(f"{pname}{key[len(m.name):]} "
                             f"{_fmt(gauges[key])}")
            if m.name not in gauges and not labeled:
                lines.append(f"{pname} 0")
        else:  # histogram
            labeled = sorted(k for k in hist_keys
                             if k.startswith(m.name + "{"))
            if m.name in hist_keys or not labeled:
                _render_hist(m.name, pname, "")
            for key in labeled:
                _render_hist(key, pname, key[len(m.name) + 1:-1])
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> tuple[dict[str, float], dict[str, str]]:
    """Parse exposition text back into (samples, types).

    ``samples`` is keyed by the full sample name INCLUDING any label
    set (``blance_x_bucket{le="1"}``); ``types`` maps base metric name
    to its declared type.  Raises ValueError on any line that is
    neither a comment nor a well-formed sample — the CI smoke's
    'parseable' assertion."""
    samples: dict[str, float] = {}
    types: dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in _KINDS:
                raise ValueError(f"line {lineno}: malformed TYPE: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        name, sep, value = line.rpartition(" ")
        if not sep or not name:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        try:
            samples[name] = float(value)
        except ValueError as e:
            raise ValueError(
                f"line {lineno}: bad sample value {value!r}") from e
    return samples, types


# -- the asyncio endpoint ----------------------------------------------------


class MetricsServer:
    """Minimal asyncio HTTP/1.1 server for ``GET /metrics``.

    ``collectors`` run before each snapshot (e.g. ``SloTracker.publish``
    refreshing time-derived gauges); renders are throttled to one per
    ``min_interval_s`` with scrapes in between served from the cached
    text, so a tight scrape loop cannot turn the recorder lock into a
    hot path."""

    def __init__(self, recorder: Optional[Recorder] = None,
                 registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 min_interval_s: float = 0.25,
                 collectors: Sequence[Callable[[], None]] = ()) -> None:
        self._recorder = recorder
        self._registry = registry
        self._host = host
        self._requested_port = port
        self._min_interval_s = min_interval_s
        self._collectors = tuple(collectors)
        self._server: Optional[asyncio.Server] = None
        self._cached: Optional[str] = None
        self._cached_at: Optional[float] = None
        self._started_at: Optional[float] = None
        self._snapshots = 0

    # -- snapshotting --------------------------------------------------------

    def render(self) -> str:
        """A FRESH snapshot (collectors + render), bypassing the cache.
        Loop-free: usable directly under DeterministicLoop tests."""
        for collect in self._collectors:
            collect()
        rec = self._recorder if self._recorder is not None \
            else get_recorder()
        return render_prometheus(rec, self._registry)

    def _snapshot(self) -> str:
        rec = self._recorder if self._recorder is not None \
            else get_recorder()
        now = rec.now()
        if self._cached is None or self._cached_at is None or \
                now - self._cached_at >= self._min_interval_s:
            self._cached = self.render()
            self._cached_at = now
            self._snapshots += 1
        return self._cached

    def _healthz(self) -> tuple[str, bytes]:
        """Liveness + freshness: 200 with uptime/snapshot-age JSON once
        a snapshot exists, 503 before the first one — so a scraper (and
        the CI obs-smoke) can tell 'up and serving fresh aggregates'
        from 'up but you would get a stale or empty cache'."""
        import json

        rec = self._recorder if self._recorder is not None \
            else get_recorder()
        now = rec.now()
        if self._cached_at is None:
            payload = {"status": "no-snapshot",
                       "uptime_s": (now - self._started_at
                                    if self._started_at is not None
                                    else None)}
            return "503 Service Unavailable", \
                (json.dumps(payload, sort_keys=True) + "\n").encode()
        payload = {
            "status": "ok",
            "uptime_s": (now - self._started_at
                         if self._started_at is not None else None),
            "snapshot_age_s": now - self._cached_at,
            "snapshots": self._snapshots,
        }
        return "200 OK", \
            (json.dumps(payload, sort_keys=True) + "\n").encode()

    # -- server lifecycle ----------------------------------------------------

    async def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("MetricsServer already started")
        rec = self._recorder if self._recorder is not None \
            else get_recorder()
        self._started_at = rec.now()
        self._server = await asyncio.start_server(
            self._handle, self._host, self._requested_port)

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("MetricsServer not started")
        sock = self._server.sockets[0]
        return int(sock.getsockname()[1])

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 10.0)
            while True:  # drain headers to the blank line
                header = await asyncio.wait_for(reader.readline(), 10.0)
                if header in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 else ""
            ctype = "text/plain; version=0.0.4; charset=utf-8"
            if parts and parts[0] != b"GET":
                status, body = "405 Method Not Allowed", b"method not allowed\n"
            elif path in ("/metrics", "/"):
                status, body = "200 OK", self._snapshot().encode()
            elif path == "/healthz":
                status, body = self._healthz()
                ctype = "application/json; charset=utf-8"
            else:
                status, body = "404 Not Found", b"not found\n"
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body)
            await writer.drain()
        except (ConnectionError, asyncio.TimeoutError, OSError):
            pass  # a dropped/slow scraper is the scraper's problem
        finally:
            writer.close()


async def scrape(host: str, port: int, path: str = "/metrics",
                 timeout_s: float = 10.0) -> str:
    """Minimal asyncio scrape client (the CI smoke and tests use it;
    production scrapes come from a real Prometheus)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                     f"Connection: close\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout_s)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0]
    if b" 200 " not in status + b" ":
        raise RuntimeError(f"scrape failed: {status.decode('latin-1')}")
    return body.decode()


# -- CI smoke ----------------------------------------------------------------


async def _smoke_async(fail_rate: float = 0.3, seed: int = 7) -> int:
    """Chaos rebalance with the endpoint live: scrape twice mid-flight,
    once after, and assert the acceptance contract (parseable output,
    every registry metric present, monotone counters, availability in
    [0, 1]).  Returns a process exit code."""
    from ..core.types import Partition, PartitionModelState
    from ..orchestrate.faults import FaultPlan, NodeFaults
    from ..orchestrate.orchestrator import OrchestratorOptions
    from ..rebalance import rebalance_async
    from .recorder import use_recorder
    from .slo import SloTracker

    P, N = 64, 8
    nodes = [f"n{i:03d}" for i in range(N)]
    live, dead = nodes[:-1], nodes[-1]
    model = {"primary": PartitionModelState(priority=0, constraints=1),
             "replica": PartitionModelState(priority=1, constraints=1)}
    beg = {
        f"{i:04d}": Partition(f"{i:04d}", {
            "primary": [live[i % len(live)]],
            "replica": [live[(i + 1) % len(live)]]})
        for i in range(P)
    }
    plan = FaultPlan(seed=seed, nodes={
        dead: NodeFaults(dead=True),
        nodes[0]: NodeFaults(fail_rate=fail_rate),
        nodes[1]: NodeFaults(fail_rate=fail_rate),
    })

    async def assign(stop_ch: object, node: str, partitions: list[str],
                     states: list[str], ops: list[str]) -> None:
        await asyncio.sleep(0.001)  # keep the run in flight across scrapes

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if not cond:
            failures.append(what)
        print(f"  {'ok' if cond else 'FAIL'}: {what}", file=sys.stderr)

    rec = Recorder()
    with use_recorder(rec):
        slo = SloTracker(beg, primary_states=("primary",), clock=rec.now,
                         recorder=rec)
        server = MetricsServer(recorder=rec, collectors=(slo.publish,),
                               min_interval_s=0.01)
        await server.start()
        try:
            # /healthz before ANY metrics scrape: no snapshot exists yet,
            # so a healthy-but-stale server must answer 503, not 200 —
            # that is the distinction real scrapers key alerts on.
            try:
                await scrape("127.0.0.1", server.port, path="/healthz")
                health_pre = "200"
            except RuntimeError as e:
                health_pre = "503" if " 503 " in f" {e} " else str(e)
            loop = asyncio.get_running_loop()
            # Decommission one live node AND add the dead one: the
            # decommission forces real (retried-through-the-flakes)
            # migrations between live nodes, while every move onto the
            # dead node fails into quarantine + recovery — so the scrape
            # sees both executed moves and failures.
            run = loop.create_task(rebalance_async(
                model, beg, nodes, [live[2]], [dead], plan.wrap(assign),
                # Generous deadline/retry budget: on a loaded CI host
                # only the SCRIPTED faults may fail moves — an innocent
                # callback stalled by scheduling jitter must not trip
                # quarantine and sink the final-availability assertion.
                orchestrator_options=OrchestratorOptions(
                    move_timeout_s=5.0, max_retries=6,
                    backoff_base_s=0.002, quarantine_after=3,
                    probe_after_s=60.0),
                max_recovery_rounds=3, backend="greedy", slo=slo))
            await asyncio.sleep(0.05)
            text1 = await scrape("127.0.0.1", server.port)
            await asyncio.sleep(0.05)
            text2 = await scrape("127.0.0.1", server.port)
            result = await run
            text3 = await scrape("127.0.0.1", server.port)
            health = await scrape("127.0.0.1", server.port,
                                  path="/healthz")
        finally:
            await server.stop()

    s1, t1 = parse_prometheus(text1)
    s2, _t2 = parse_prometheus(text2)
    s3, _t3 = parse_prometheus(text3)
    print(f"obs-smoke: scraped {len(s1)} -> {len(s2)} -> {len(s3)} "
          f"samples; rebalance failures={len(result.failures)} "
          f"quarantined={result.quarantined_nodes}", file=sys.stderr)

    reg = default_registry()
    missing = [reg.prom_name(m) for m in reg.metrics()
               if reg.prom_name(m) not in t1]
    check(not missing, f"every registry metric exposed (missing: "
                       f"{missing[:5]})")
    counter_names = {reg.prom_name(m) for m in reg.metrics()
                     if m.kind == "counter"}
    regressed = [n for n in counter_names
                 if not (s1.get(n, 0) <= s2.get(n, 0) <= s3.get(n, 0))]
    check(not regressed, f"counters monotone across scrapes (regressed: "
                         f"{regressed[:5]})")
    avail = "blance_slo_partition_availability"
    check(all(0.0 <= s[avail] <= 1.0 for s in (s1, s2, s3)),
          "availability within [0, 1] on every scrape")
    check(s3[avail] == 1.0, "final availability is 1.0 (chaos run "
                            "completed on the survivors)")
    # Churn can land under 1.0 here: abandoned moves are never executed
    # and the recovery replan (dead placements presumed lost) owes fewer
    # moves than the primary plan did.  Positive just means the gauge is
    # wired.
    check(s3["blance_slo_churn_ratio"] > 0.0,
          "churn ratio positive and published")
    check(s3["blance_slo_moves_executed"] > 0,
          "executed-move gauge advanced")
    check(s3["blance_orchestrate_move_failures_total"] > 0,
          "chaos actually injected failures")
    check(health_pre == "503",
          f"/healthz is 503 before the first snapshot (got {health_pre})")
    import json as _json

    try:
        hz = _json.loads(health)
    except ValueError:
        hz = {}
    check(hz.get("status") == "ok" and hz.get("snapshot_age_s", -1) >= 0
          and hz.get("uptime_s", -1) >= 0,
          f"/healthz serves ok + uptime/snapshot-age JSON (got {health!r})")
    if failures:
        print(f"obs-smoke: FAIL ({len(failures)} checks)", file=sys.stderr)
        return 1
    print("obs-smoke: OK", file=sys.stderr)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m blance_tpu.obs.expo",
        description="Prometheus exposition endpoint for blance_tpu "
                    "telemetry (docs/OBSERVABILITY.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: chaos rebalance with the endpoint "
                         "live; scrape + assert, exit nonzero on failure")
    ap.add_argument("--render", action="store_true",
                    help="render one snapshot of the process recorder "
                         "to stdout and exit")
    args = ap.parse_args(argv)
    if args.smoke:
        return asyncio.run(_smoke_async())
    if args.render:
        print(render_prometheus(), end="")
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
