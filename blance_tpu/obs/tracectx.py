"""End-to-end request tracing: trace contexts + latency decomposition.

A :class:`TraceContext` is minted where a request enters the system
(``PlanService.submit``) and rides the request through coalescing,
executor hand-off and the fleet batch dispatch, so one tenant's latency
decomposes into named segments — in Perfetto (each request gets a
``req:<trace_id>`` lane with one span per segment) and as
``fleet.request_segment_s{segment=...}`` histograms on the exposition
endpoint.

Design constraints, in order:

- **Determinism.**  Trace ids come from a per-:class:`TraceIdSource`
  counter — never ``uuid``/``random`` — so a seeded run under the PR-5
  ``DeterministicLoop`` mints the same ids in the same order, and the
  whole telemetry plane (ids included) is a pure function of the
  schedule.
- **Exact decomposition.**  A :class:`RequestTimeline` is an ordered
  list of named timestamps on ONE clock (the owning Recorder's); each
  segment is the difference of two adjacent marks, so the segments
  tile the request's lifetime exactly — no gaps, no overlaps — and
  their sum telescopes to the end-to-end latency.
- **Zero cost off the request path.**  The context is a frozen
  dataclass, the timeline a list of (name, float) pairs; nothing here
  touches jax, sockets, or wall clocks.

The contextvar pair (:func:`current_trace` / :func:`use_trace`) lets
deeper layers (the fleet dispatch span) read the ambient context
without threading it through every signature.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
from dataclasses import dataclass
from typing import TYPE_CHECKING, Iterator, Optional

if TYPE_CHECKING:  # annotation-only
    from .recorder import Recorder

__all__ = [
    "TraceContext",
    "TraceIdSource",
    "RequestTimeline",
    "SEGMENTS",
    "current_trace",
    "use_trace",
]


# The canonical decomposition of one plan-service request, in lifecycle
# order.  Each name labels the segment that ENDS at the mark of the same
# name (docs/OBSERVABILITY.md "Request decomposition"):
#   admission       — queue wait: submit() until the dispatcher dequeues
#   coalesce        — the admission window: dequeue until the batch closes
#   executor_queue  — batch closed until the solver actually starts
#   device          — the fleet batch solve itself
#   resolve         — solve done until the request's future resolves
SEGMENTS = ("admission", "coalesce", "executor_queue", "device", "resolve")


@dataclass(frozen=True)
class TraceContext:
    """One request's identity: ``trace_id`` names the end-to-end trace,
    ``parent_id`` the minting hop (None at the root).  Frozen — a child
    hop gets a NEW context via :meth:`child`, never a mutation."""

    trace_id: str
    parent_id: Optional[str] = None

    def child(self, hop: str) -> "TraceContext":
        """A derived context for a sub-operation (``hop`` suffixes the
        id so children stay unique AND deterministic)."""
        return TraceContext(trace_id=f"{self.trace_id}/{hop}",
                            parent_id=self.trace_id)


class TraceIdSource:
    """Deterministic trace-id mint: ``prefix-000001``, ``prefix-000002``,
    ... per source instance.  Each PlanService owns one, so two seeded
    runs of the same scenario mint identical ids in identical order."""

    def __init__(self, prefix: str = "req") -> None:
        self._prefix = prefix
        self._n = itertools.count(1)

    def mint(self) -> TraceContext:
        return TraceContext(trace_id=f"{self._prefix}-{next(self._n):06d}")


_current: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("blance_trace_ctx", default=None)


def current_trace() -> Optional[TraceContext]:
    """The ambient trace context, if any hop set one."""
    return _current.get()


@contextlib.contextmanager
def use_trace(ctx: TraceContext) -> Iterator[TraceContext]:
    """Install ``ctx`` as the ambient trace context for the body."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


class RequestTimeline:
    """Ordered named timestamps decomposing one request's latency.

    ``mark(name, t)`` closes the segment called ``name`` at time ``t``
    (times come from the owning Recorder's clock — virtual under
    ``DeterministicLoop``).  ``record`` emits the whole decomposition:
    one ``fleet.request`` span covering the request end-to-end, one
    ``fleet.request.<segment>`` span per segment (all on the request's
    own ``req:<trace_id>`` lane, so Perfetto shows the tiling), and one
    ``fleet.request_segment_s{segment=...}`` histogram observation per
    segment.  Every span carries ``trace_id`` (and ``parent_id`` when
    set), which is what lands in JSONL sink lines.
    """

    __slots__ = ("ctx", "marks")

    def __init__(self, ctx: TraceContext, t_submit: float) -> None:
        self.ctx = ctx
        self.marks: list[tuple[str, float]] = [("submit", t_submit)]

    def mark(self, name: str, t: float) -> None:
        self.marks.append((name, t))

    @property
    def t_submit(self) -> float:
        return self.marks[0][1]

    def segments(self) -> list[tuple[str, float]]:
        """(segment name, duration) pairs — adjacent-mark differences,
        so they tile [t_submit, t_last] exactly."""
        out: list[tuple[str, float]] = []
        for (_, t_prev), (name, t) in zip(self.marks, self.marks[1:]):
            out.append((name, t - t_prev))
        return out

    def record(self, rec: "Recorder", **attrs: object) -> None:
        """Emit the decomposition (spans + histograms) to ``rec``."""
        if len(self.marks) < 2:
            return
        lane = f"req:{self.ctx.trace_id}"
        ids: dict[str, object] = {"trace_id": self.ctx.trace_id}
        if self.ctx.parent_id is not None:
            ids["trace_parent_id"] = self.ctx.parent_id
        t_prev = self.marks[0][1]
        seg_attrs: dict[str, object] = {}
        for name, t in self.marks[1:]:
            rec.record_span(f"fleet.request.{name}", t_prev, t,
                            task=lane, **ids)
            rec.observe(f'fleet.request_segment_s{{segment="{name}"}}',
                        t - t_prev)
            seg_attrs[f"{name}_s"] = t - t_prev
            t_prev = t
        rec.record_span("fleet.request", self.marks[0][1], t_prev,
                        task=lane, **ids, **seg_attrs, **attrs)
