"""Process-local tracing & metrics core: spans, counters, histograms.

The Recorder is the single funnel every layer reports into (plan encode/
solve/decode, the greedy scorer, the batched move diff, the orchestrator's
per-move lifecycle).  Three primitives:

- **Spans**: nestable timed regions with attributes.  Parent tracking uses
  a ``contextvars.ContextVar``, so nesting is correct both synchronously
  and across asyncio tasks (a task inherits the span that was current when
  it was created, and sibling tasks cannot become each other's parents).
  Spans can also be *manufactured* after the fact (``record_span``) for
  lifecycles whose start predates the code that observes them — e.g. a
  move request's queue-wait time, measured by the mover that dequeues it.
- **Counters**: monotonic named floats (``count``).
- **Histograms**: named value series (``observe``) summarized by
  nearest-rank percentiles (p50/p95) — per-move latency, solver sweep
  counts, greedy candidate-list sizes.

The Recorder itself keeps only O(#names) aggregate state: span totals,
counters, exact histogram stats (count/sum/min/max), and a BOUNDED
histogram sample — once a series reaches ``_HIST_CAP`` values it is
decimated 2:1 and subsequent observations are systematically subsampled
(deterministic, no RNG), so percentiles stay representative while memory
stays flat.  Finished spans are retained only by attached sinks
(``blance_tpu.obs.sinks``); an un-sinked recorder in a long-running
service never grows with traffic.

Timestamps are ``time.perf_counter()`` seconds, offset against the
recorder's construction time (``t0``) at export — one consistent
monotonic clock for every span in a process, which is what lets the
Chrome-trace exporter lay host spans on a single timeline next to
``device_profile`` TPU traces captured over the same interval.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import math
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterator, Optional

if TYPE_CHECKING:  # annotation-only
    from ..utils.trace import PhaseTimer
    from .sinks import Sink

__all__ = [
    "Span",
    "Recorder",
    "get_recorder",
    "set_recorder",
    "use_recorder",
    "phase_span",
    "percentile",
]


@dataclass
class Span:
    """One finished (or in-flight) timed region."""

    name: str
    t_start: float  # perf_counter seconds
    t_end: Optional[float]  # None while in flight
    attrs: dict
    span_id: int
    parent_id: Optional[int]
    task: str  # logical lane (thread/asyncio task/node) for trace viewers
    # Backdated / manufactured spans (explicit t_start, record_span) can
    # partially overlap live spans on their lane — e.g. a move's queue
    # wait starts while the mover is still executing the previous batch.
    # Exporters whose slice format requires strict nesting per lane
    # (Chrome "X" events) must emit these as async events instead.
    overlappable: bool = False

    @property
    def duration_s(self) -> float:
        return (self.t_end or self.t_start) - self.t_start


def percentile(values: list, q: float) -> float:
    """Nearest-rank percentile of an UNSORTED value list.

    rank = ceil(q/100 * n) clamped to [1, n]; q=0 returns the minimum,
    q=100 the maximum.  Deterministic (no interpolation), so summaries
    are stable across platforms and reproducible in tests."""
    if not values:
        raise ValueError("percentile of empty series")
    s = sorted(values)
    rank = max(1, min(len(s), math.ceil(q / 100.0 * len(s))))
    return s[rank - 1]


# Per-series percentile-sample bound: at the cap the sample is decimated
# 2:1 and the subsample stride doubles, so memory stays O(_HIST_CAP) while
# the sample stays spread evenly over the series' whole history.
_HIST_CAP = 4096


def _current_task_label() -> str:
    """Lane label: the asyncio task name when inside one, else the thread."""
    try:
        import asyncio

        task = asyncio.current_task()
        if task is not None:
            return task.get_name()
    except RuntimeError:
        pass
    return threading.current_thread().name


class Recorder:
    """Span/counter/histogram recorder with pluggable sinks.

    Thread-safe for aggregate updates (one lock); span parenthood is
    context-local, never locked.  ``sinks`` receive every finished span
    via their ``span(span)`` method."""

    def __init__(self, sinks: tuple = ()) -> None:
        self.t0 = time.perf_counter()
        self.sinks: list = list(sinks)
        self.span_totals: dict[str, float] = {}
        self.span_counts: dict[str, int] = {}
        self.counters: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}  # bounded sample
        self._hist_stats: dict[str, list] = {}  # [count, sum, min, max]
        self._hist_stride: dict[str, int] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # Per-instance ContextVar: two recorders never share nesting state
        # (tests swap recorders mid-process via use_recorder).
        self._current: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar(f"obs_span_{id(self)}", default=None)

    # -- spans ---------------------------------------------------------------

    def add_sink(self, sink: "Sink") -> None:
        with self._lock:
            self.sinks.append(sink)

    def remove_sink(self, sink: "Sink") -> None:
        with self._lock:
            if sink in self.sinks:
                self.sinks.remove(sink)

    def current_span(self) -> Optional[Span]:
        return self._current.get()

    @contextlib.contextmanager
    def span(self, name: str, *, t_start: Optional[float] = None,
             task: Optional[str] = None, **attrs) -> Iterator[Span]:
        """Open a nested span.  ``t_start`` backdates the span (e.g. to a
        request's enqueue time); ``task`` overrides the lane label."""
        parent = self._current.get()
        sp = Span(
            name=name,
            t_start=time.perf_counter() if t_start is None else t_start,
            t_end=None,
            attrs=dict(attrs),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            task=task if task is not None else _current_task_label(),
            overlappable=t_start is not None,
        )
        token = self._current.set(sp)
        try:
            yield sp
        finally:
            self._current.reset(token)
            sp.t_end = time.perf_counter()
            self._finish(sp)

    def record_span(self, name: str, t_start: float, t_end: float, *,
                    task: Optional[str] = None, **attrs) -> Span:
        """Record an already-elapsed span (both endpoints known).  Parents
        onto the caller's current span, like a live span would."""
        parent = self._current.get()
        sp = Span(
            name=name, t_start=t_start, t_end=t_end, attrs=dict(attrs),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            task=task if task is not None else _current_task_label(),
            overlappable=True,
        )
        self._finish(sp)
        return sp

    def set_attr(self, key: str, value: object) -> None:
        """Attach an attribute to the current span; no-op outside any."""
        sp = self._current.get()
        if sp is not None:
            sp.attrs[key] = value

    def _finish(self, sp: Span) -> None:
        with self._lock:
            self.span_totals[sp.name] = \
                self.span_totals.get(sp.name, 0.0) + sp.duration_s
            self.span_counts[sp.name] = self.span_counts.get(sp.name, 0) + 1
            sinks = list(self.sinks)
        for sink in sinks:
            sink.span(sp)

    # -- counters / histograms ----------------------------------------------

    def count(self, name: str, value: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + value

    def observe(self, name: str, value: float) -> None:
        v = float(value)
        with self._lock:
            st = self._hist_stats.get(name)
            if st is None:
                st = self._hist_stats[name] = [0, 0.0, v, v]
            st[0] += 1
            st[1] += v
            if v < st[2]:
                st[2] = v
            if v > st[3]:
                st[3] = v
            # Bounded percentile sample: systematic 1-in-stride subsample,
            # stride doubling on each 2:1 decimation at the cap.
            stride = self._hist_stride.get(name, 1)
            if (st[0] - 1) % stride == 0:
                series = self.histograms.setdefault(name, [])
                series.append(v)
                if len(series) >= _HIST_CAP:
                    del series[::2]
                    self._hist_stride[name] = stride * 2

    # -- summaries -----------------------------------------------------------

    def histogram_summary(self, name: str) -> Optional[dict]:
        with self._lock:
            st = self._hist_stats.get(name)
            values = list(self.histograms.get(name, ()))
        if st is None or not values:
            return None
        return {
            "count": st[0],
            "sum": st[1],
            "min": st[2],
            "max": st[3],
            "p50": percentile(values, 50),
            "p95": percentile(values, 95),
        }

    def summary(self) -> dict:
        """Everything aggregate, JSON-serializable: per-span-name totals,
        counters, and histogram percentile summaries — the block bench.py
        embeds into its artifact."""
        with self._lock:
            spans = {
                name: {"total_s": self.span_totals[name],
                       "count": self.span_counts[name]}
                for name in sorted(self.span_totals)
            }
            counters = {k: self.counters[k] for k in sorted(self.counters)}
            hist_names = sorted(self.histograms)
        return {
            "spans": spans,
            "counters": counters,
            "histograms": {
                name: self.histogram_summary(name) for name in hist_names
            },
        }


# -- process-global recorder --------------------------------------------------

_global_recorder = Recorder()


def get_recorder() -> Recorder:
    """The process-local recorder every instrumented layer reports to."""
    return _global_recorder


def set_recorder(recorder: Recorder) -> Recorder:
    """Swap the process recorder; returns the previous one."""
    global _global_recorder
    prev = _global_recorder
    _global_recorder = recorder
    return prev


@contextlib.contextmanager
def use_recorder(recorder: Recorder) -> Iterator[Recorder]:
    """Temporarily install ``recorder`` as the process recorder (tests)."""
    prev = set_recorder(recorder)
    try:
        yield recorder
    finally:
        set_recorder(prev)


@contextlib.contextmanager
def phase_span(name: str, timer: Optional["PhaseTimer"] = None,
               phase: Optional[str] = None,
               **attrs: object) -> Iterator[Span]:
    """Recorder span that ALSO accumulates into a PhaseTimer.

    The instrumented pipeline names spans hierarchically ("plan.encode")
    while PhaseTimer callers keep their short phase keys ("encode", the
    default: the last dot segment) — one timed region, two views, no
    double-recorded span."""
    rec = get_recorder()
    start = time.perf_counter()
    try:
        with rec.span(name, **attrs) as sp:
            yield sp
    finally:
        if timer is not None:
            timer._accumulate(phase or name.rsplit(".", 1)[-1],
                              time.perf_counter() - start)
