"""Process-local tracing & metrics core: spans, counters, histograms.

The Recorder is the single funnel every layer reports into (plan encode/
solve/decode, the greedy scorer, the batched move diff, the orchestrator's
per-move lifecycle).  Three primitives:

- **Spans**: nestable timed regions with attributes.  Parent tracking uses
  a ``contextvars.ContextVar``, so nesting is correct both synchronously
  and across asyncio tasks (a task inherits the span that was current when
  it was created, and sibling tasks cannot become each other's parents).
  Spans can also be *manufactured* after the fact (``record_span``) for
  lifecycles whose start predates the code that observes them — e.g. a
  move request's queue-wait time, measured by the mover that dequeues it.
- **Counters**: monotonic named floats (``count``).
- **Gauges**: last-value-wins named floats (``set_gauge``) — the online
  SLO accounting (``obs.slo``) publishes availability/churn/lag here and
  the exposition endpoint (``obs.expo``) serves them.
- **Histograms**: named value series (``observe``) summarized by
  nearest-rank percentiles (p50/p95) — per-move latency, solver sweep
  counts, greedy candidate-list sizes — plus EXACT cumulative bucket
  counts over fixed log-spaced bounds, which is what the Prometheus
  exposition's ``_bucket``/``_sum``/``_count`` series are built from.

The Recorder itself keeps only O(#names) aggregate state: span totals,
counters, gauges, exact histogram stats (count/sum/min/max) and bucket
counts, and a BOUNDED percentile sample — once a series reaches
``_HIST_CAP`` values it is decimated 2:1 and subsequent observations are
systematically subsampled (deterministic, no RNG), so percentiles stay
representative while memory stays flat.  Finished spans are retained
only by attached sinks (``blance_tpu.obs.sinks``); an un-sinked recorder
in a long-running service never grows with traffic.

Timestamps come from the recorder's injectable ``clock`` (default
``time.perf_counter``) in seconds, offset against the recorder's
construction time (``t0``) at export — one consistent monotonic clock
for every span in a process, which is what lets the Chrome-trace
exporter lay host spans on a single timeline next to ``device_profile``
TPU traces captured over the same interval.  Injecting the clock is
what makes telemetry DETERMINISTIC under the controlled virtual-time
loop (``testing.sched.DeterministicLoop``): ``Recorder(clock=loop.time)``
makes every span duration, SLO gauge, and exposition snapshot a pure
function of the (seeded) schedule.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import itertools
import math
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Iterator, Optional

if TYPE_CHECKING:  # annotation-only
    from ..utils.trace import PhaseTimer
    from .sinks import Sink

__all__ = [
    "Span",
    "Recorder",
    "DEFAULT_BUCKETS",
    "get_recorder",
    "set_recorder",
    "use_recorder",
    "phase_span",
    "percentile",
    "escape_label_value",
]


def escape_label_value(value: str) -> str:
    """Prometheus label-value escaping (backslash, double quote,
    newline) — THE one spelling every labeled-metric emitter uses
    (obs/slo.py node labels, obs/device.py entry/klass labels), so the
    escaping rules cannot drift between emitters.  Arbitrary caller
    strings must not invalidate the whole scrape."""
    return value.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


@dataclass
class Span:
    """One finished (or in-flight) timed region."""

    name: str
    t_start: float  # perf_counter seconds
    t_end: Optional[float]  # None while in flight
    attrs: dict
    span_id: int
    parent_id: Optional[int]
    task: str  # logical lane (thread/asyncio task/node) for trace viewers
    # Backdated / manufactured spans (explicit t_start, record_span) can
    # partially overlap live spans on their lane — e.g. a move's queue
    # wait starts while the mover is still executing the previous batch.
    # Exporters whose slice format requires strict nesting per lane
    # (Chrome "X" events) must emit these as async events instead.
    overlappable: bool = False

    @property
    def duration_s(self) -> float:
        return (self.t_end or self.t_start) - self.t_start


def percentile(values: list, q: float) -> float:
    """Nearest-rank percentile of an UNSORTED value list.

    rank = ceil(q/100 * n) clamped to [1, n]; q=0 returns the minimum,
    q=100 the maximum.  Deterministic (no interpolation), so summaries
    are stable across platforms and reproducible in tests."""
    if not values:
        raise ValueError("percentile of empty series")
    s = sorted(values)
    rank = max(1, min(len(s), math.ceil(q / 100.0 * len(s))))
    return s[rank - 1]


# Per-series percentile-sample bound: at the cap the sample is decimated
# 2:1 and the subsample stride doubles, so memory stays O(_HIST_CAP) while
# the sample stays spread evenly over the series' whole history.
_HIST_CAP = 4096

# Default histogram bucket upper bounds (``le`` semantics), log-spaced
# 1-2.5-5 per decade from 100 µs to 10k.  Wide on purpose: one fixed set
# covers sub-ms move latencies, solver sweep counts, and candidate-list
# sizes, so EVERY series has exact Prometheus-style bucket counts from
# its first observation without per-name registration (a +Inf bucket is
# implicit).  Override per series with ``Recorder.set_hist_bounds``
# BEFORE the first observation.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


def _current_task_label() -> str:
    """Lane label: the asyncio task name when inside one, else the thread."""
    try:
        import asyncio

        task = asyncio.current_task()
        if task is not None:
            return task.get_name()
    except RuntimeError:
        pass
    return threading.current_thread().name


class Recorder:
    """Span/counter/gauge/histogram recorder with pluggable sinks.

    Thread-safe for aggregate updates (one lock); span parenthood is
    context-local, never locked.  ``sinks`` receive every finished span
    via their ``span(span)`` method; a sink that also defines
    ``counter(name, value, t)`` additionally sees every counter update
    live (the Chrome exporter uses this for time-series counter tracks).

    ``clock`` is the recorder's one time source (monotonic seconds);
    inject ``DeterministicLoop.time`` to run all telemetry — span
    durations, SLO gauges, exposition snapshots — under virtual time."""

    def __init__(self, sinks: tuple = (),
                 clock: Callable[[], float] = time.perf_counter) -> None:
        self._clock = clock
        self.t0 = clock()
        self.sinks: list = list(sinks)
        self.span_totals: dict[str, float] = {}
        self.span_counts: dict[str, int] = {}
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}  # bounded sample
        self._hist_stats: dict[str, list] = {}  # [count, sum, min, max]
        self._hist_stride: dict[str, int] = {}
        self._hist_bounds: dict[str, tuple[float, ...]] = {}
        self._hist_buckets: dict[str, list[int]] = {}  # per-bound counts
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # Sinks that opted into live counter samples, cached at
        # add/remove time so count() — the orchestrator's hottest obs
        # call — never probes hasattr under the lock.
        self._counter_sinks: list = [
            s for s in self.sinks if hasattr(s, "counter")]
        # Per-instance ContextVar: two recorders never share nesting state
        # (tests swap recorders mid-process via use_recorder).
        self._current: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar(f"obs_span_{id(self)}", default=None)

    def now(self) -> float:
        """The recorder's clock — the one time source every instrumented
        layer should read instead of ``time.perf_counter`` directly, so
        a virtual-time clock injection covers the whole pipeline."""
        return self._clock()

    # -- spans ---------------------------------------------------------------

    def add_sink(self, sink: "Sink") -> None:
        with self._lock:
            self.sinks.append(sink)
            if hasattr(sink, "counter"):
                self._counter_sinks = self._counter_sinks + [sink]

    def remove_sink(self, sink: "Sink") -> None:
        with self._lock:
            if sink in self.sinks:
                self.sinks.remove(sink)
            if sink in self._counter_sinks:
                self._counter_sinks = [
                    s for s in self._counter_sinks if s is not sink]

    def current_span(self) -> Optional[Span]:
        return self._current.get()

    @contextlib.contextmanager
    def span(self, name: str, *, t_start: Optional[float] = None,
             task: Optional[str] = None, **attrs) -> Iterator[Span]:
        """Open a nested span.  ``t_start`` backdates the span (e.g. to a
        request's enqueue time); ``task`` overrides the lane label."""
        parent = self._current.get()
        sp = Span(
            name=name,
            t_start=self._clock() if t_start is None else t_start,
            t_end=None,
            attrs=dict(attrs),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            task=task if task is not None else _current_task_label(),
            overlappable=t_start is not None,
        )
        token = self._current.set(sp)
        try:
            yield sp
        finally:
            self._current.reset(token)
            sp.t_end = self._clock()
            self._finish(sp)

    def record_span(self, name: str, t_start: float, t_end: float, *,
                    task: Optional[str] = None, **attrs) -> Span:
        """Record an already-elapsed span (both endpoints known).  Parents
        onto the caller's current span, like a live span would."""
        parent = self._current.get()
        sp = Span(
            name=name, t_start=t_start, t_end=t_end, attrs=dict(attrs),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            task=task if task is not None else _current_task_label(),
            overlappable=True,
        )
        self._finish(sp)
        return sp

    def set_attr(self, key: str, value: object) -> None:
        """Attach an attribute to the current span; no-op outside any."""
        sp = self._current.get()
        if sp is not None:
            sp.attrs[key] = value

    def _finish(self, sp: Span) -> None:
        with self._lock:
            self.span_totals[sp.name] = \
                self.span_totals.get(sp.name, 0.0) + sp.duration_s
            self.span_counts[sp.name] = self.span_counts.get(sp.name, 0) + 1
            sinks = list(self.sinks)
        for sink in sinks:
            sink.span(sp)

    # -- counters / gauges / histograms --------------------------------------

    def count(self, name: str, value: float = 1) -> None:
        with self._lock:
            total = self.counters[name] = self.counters.get(name, 0) + value
            # Cached at add/remove-sink time; rebound wholesale there, so
            # grabbing the reference is safe and the common no-hook path
            # stays one dict update under the lock.
            notify = self._counter_sinks
        if notify:
            t = self._clock()
            for sink in notify:
                sink.counter(name, total, t)

    def set_gauge(self, name: str, value: float) -> None:
        """Set a last-value-wins gauge (SLO accounting publishes here;
        the exposition endpoint serves them)."""
        with self._lock:
            self.gauges[name] = float(value)

    def sample(self, name: str, value: float,
               t: Optional[float] = None) -> None:
        """One time-stamped series point: recorded as a histogram
        observation (aggregates) AND forwarded to counter-capable sinks
        as a Chrome counter-track sample at time ``t`` (default: now).
        This is how a value-over-time series that is neither monotone
        (counter) nor last-value (gauge) — e.g. the per-sweep
        accepted-bid fraction — gets a track on the span timeline."""
        self.observe(name, value)
        notify = self._counter_sinks
        if notify:
            tt = self._clock() if t is None else t
            for sink in notify:
                sink.counter(name, float(value), tt)

    def set_hist_bounds(self, name: str, bounds: tuple[float, ...]) -> None:
        """Override the bucket upper bounds for one series.  Must happen
        before the series' first observation — bucket counts are exact
        by construction and cannot be re-binned after the fact."""
        with self._lock:
            if name in self._hist_stats:
                raise ValueError(
                    f"histogram {name!r} already has observations; bucket "
                    f"bounds must be set before the first observe()")
            self._hist_bounds[name] = tuple(sorted(float(b) for b in bounds))

    def observe(self, name: str, value: float) -> None:
        v = float(value)
        with self._lock:
            st = self._hist_stats.get(name)
            if st is None:
                st = self._hist_stats[name] = [0, 0.0, v, v]
            st[0] += 1
            st[1] += v
            if v < st[2]:
                st[2] = v
            if v > st[3]:
                st[3] = v
            # Exact per-bound bucket counts (le semantics; the final slot
            # is the +Inf bucket).  Incremental here, cumulated at export.
            bounds = self._hist_bounds.get(name, DEFAULT_BUCKETS)
            buckets = self._hist_buckets.get(name)
            if buckets is None:
                buckets = self._hist_buckets[name] = [0] * (len(bounds) + 1)
            buckets[bisect.bisect_left(bounds, v)] += 1
            # Bounded percentile sample: systematic 1-in-stride subsample,
            # stride doubling on each 2:1 decimation at the cap.
            stride = self._hist_stride.get(name, 1)
            if (st[0] - 1) % stride == 0:
                series = self.histograms.setdefault(name, [])
                series.append(v)
                if len(series) >= _HIST_CAP:
                    del series[::2]
                    self._hist_stride[name] = stride * 2

    # -- summaries -----------------------------------------------------------

    def histogram_buckets(
            self, name: str) -> Optional[tuple[tuple[float, ...],
                                               list[int], int, float]]:
        """(bounds, cumulative counts incl. +Inf, count, sum) for one
        series, or None if never observed.  Counts are EXACT (every
        observation lands in exactly one bucket), so the exposition's
        ``_bucket``/``_count``/``_sum`` agree by construction."""
        with self._lock:
            buckets = self._hist_buckets.get(name)
            if buckets is None:
                return None
            st = self._hist_stats[name]
            bounds = self._hist_bounds.get(name, DEFAULT_BUCKETS)
            cum: list[int] = []
            running = 0
            for c in buckets:
                running += c
                cum.append(running)
            return bounds, cum, st[0], st[1]

    def histogram_summary(self, name: str) -> Optional[dict]:
        with self._lock:
            st = self._hist_stats.get(name)
            values = list(self.histograms.get(name, ()))
        if st is None or not values:
            return None
        return {
            "count": st[0],
            "sum": st[1],
            "min": st[2],
            "max": st[3],
            "p50": percentile(values, 50),
            "p95": percentile(values, 95),
        }

    def summary(self) -> dict:
        """Everything aggregate, JSON-serializable: per-span-name totals,
        counters, gauges, and histogram percentile summaries — the block
        bench.py embeds into its artifact."""
        with self._lock:
            spans = {
                name: {"total_s": self.span_totals[name],
                       "count": self.span_counts[name]}
                for name in sorted(self.span_totals)
            }
            counters = {k: self.counters[k] for k in sorted(self.counters)}
            gauges = {k: self.gauges[k] for k in sorted(self.gauges)}
            hist_names = sorted(self.histograms)
        return {
            "spans": spans,
            "counters": counters,
            "gauges": gauges,
            "histograms": {
                name: self.histogram_summary(name) for name in hist_names
            },
        }


# -- process-global recorder --------------------------------------------------

_global_recorder = Recorder()


def get_recorder() -> Recorder:
    """The process-local recorder every instrumented layer reports to."""
    return _global_recorder


def set_recorder(recorder: Recorder) -> Recorder:
    """Swap the process recorder; returns the previous one."""
    global _global_recorder
    prev = _global_recorder
    _global_recorder = recorder
    return prev


@contextlib.contextmanager
def use_recorder(recorder: Recorder) -> Iterator[Recorder]:
    """Temporarily install ``recorder`` as the process recorder (tests)."""
    prev = set_recorder(recorder)
    try:
        yield recorder
    finally:
        set_recorder(prev)


@contextlib.contextmanager
def phase_span(name: str, timer: Optional["PhaseTimer"] = None,
               phase: Optional[str] = None,
               **attrs: object) -> Iterator[Span]:
    """Recorder span that ALSO accumulates into a PhaseTimer.

    The instrumented pipeline names spans hierarchically ("plan.encode")
    while PhaseTimer callers keep their short phase keys ("encode", the
    default: the last dot segment) — one timed region, two views, no
    double-recorded span."""
    rec = get_recorder()
    start = rec.now()
    try:
        with rec.span(name, **attrs) as sp:
            yield sp
    finally:
        if timer is not None:
            timer._accumulate(phase or name.rsplit(".", 1)[-1],
                              rec.now() - start)
