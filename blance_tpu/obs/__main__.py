"""``python -m blance_tpu.obs`` — the exposition CLI (obs/expo.py).

A thin delegate so the CI obs-smoke step can invoke the package without
the 'found in sys.modules' RuntimeWarning that ``-m blance_tpu.obs.expo``
triggers (the package __init__ imports expo eagerly)."""

import sys

from .expo import main

if __name__ == "__main__":
    sys.exit(main())
