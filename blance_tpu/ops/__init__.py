"""blance_tpu.ops — Pallas TPU kernels for the planner's hot ops."""

from .reduce2 import (
    min2_argmin,
    min2_argmin_reference,
    pallas_available,
    priced_min2_argmin,
)

__all__ = ["min2_argmin", "min2_argmin_reference", "pallas_available",
           "priced_min2_argmin"]
