"""blance_tpu.ops subpackage."""
