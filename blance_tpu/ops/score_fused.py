"""Pallas TPU kernel: auction score computed IN-KERNEL + fused min2.

The auction round's hot op is a per-row (min, argmin, second-min) over
the priced score matrix ``score[P, N] + price[N]``.  With the score
materialized (ops/reduce2.py), every round pays a full HBM sweep of the
biggest tensor in the solver, plus one sweep to write it per slot.

But the score is a FUNCTION of tiny inputs: [N] vectors (fill factor,
weights, validity, price, candidate group ids) and [P, few] id columns
(previous holders, exclusivity list, rule anchors).  This kernel
evaluates the score formula per (TILE_P, TILE_N) block in VMEM —
identical term-by-term to the matrix build in plan/tensor.py
run_auction — and reduces it on the fly.  Per-round HBM traffic drops
from O(P*N) to O(P + N): the matrix never exists.

Outputs per row: (best = min of score+price, choice = argmin, second =
second-best, raw = unpriced score at choice) — the exact tuple
_assign_slot's rounds consume.  Tie-breaks match ops/reduce2.py: lowest
index wins within and across tiles.

Correctness is pinned by tests/test_score_fused.py: interpret-mode runs
of this kernel against the reference matrix formula, term order
preserved; bench.py additionally verifies compiled-vs-matrix on a real
device batch before enabling the fused path for timed runs.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._tiles import tile_env

# Tile shape for the score kernel, overridable for tuning sweeps
# (bench.py --tile-sweep).  Read once at import: the values are
# jit-static, so changing them mid-process would silently recompile
# rather than retune.
_TILE_P = tile_env("BLANCE_FUSED_TILE_P", 256, 8)
_TILE_N = tile_env("BLANCE_FUSED_TILE_N", 2048, 128)

try:  # ``vma`` on ShapeDtypeStruct arrived with JAX's varying-axes model
    jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
    _SDS_HAS_VMA = True
except TypeError:
    _SDS_HAS_VMA = False

__all__ = ["fused_score_min2", "ScoreInputs", "pack_score_inputs",
           "score_at_columns", "jitter_hash"]

_INF = 1.0e9
_RULE_MISS = 1.0e6
_RULE_TIER = 1.0e4
_J_MUL_P = 2654435761 - (1 << 32)  # int32 two's-complement bits of the
_J_MUL_N = 40503                   # unsigned Weyl multiplier 2654435761


def jitter_hash(pi: jnp.ndarray, ni: jnp.ndarray) -> jnp.ndarray:
    """THE deterministic tie-break hash, in [0, 1): Weyl-style over
    GLOBAL (partition, node) indices.  One spelling shared by the fused
    kernel, the point evaluator, the matrix engine in plan/tensor.py,
    and the test oracle — cross-engine decision equivalence depends on
    these being identical.  Inputs must be int32: XLA/Mosaic integer
    ops wrap two's-complement, so the masked low 16 bits equal the
    unsigned sequence bit-for-bit, and int32->float32 is a cast Mosaic
    can lower in-kernel (uint32->float32 is not)."""
    return ((pi * jnp.int32(_J_MUL_P) + ni * jnp.int32(_J_MUL_N))
            & jnp.int32(0xFFFF)).astype(jnp.float32) / 65536.0


class ScoreInputs(NamedTuple):
    """Packed per-slot score inputs (a pytree of arrays).

    [N_l]-shaped (this shard's columns):
      base       f32 — fill factor / node weight (the balance term)
      neg_boost  f32 — -min(node_weight, 0)
      validf     f32 — 1.0 valid / 0.0 removed
      cand_g     [2*nrules (or 1), N_l] i32 — per rule: candidates'
                 include-level gids, then exclude-level gids
    [P]-shaped:
      stick      f32 — stickiness[:, si]
      prev_slot  i32 — prev[:, si, ri] (-1 none): same-ordinal bonus
      prev_state [P, R] i32 — prev[:, si, :]: sticky-holder bonus
      taken      [P, T] i32 — exclusivity id columns (-1 padded)
      present    [P, A] f32 — 1.0 where the rule anchor exists
      a_inc_g / a_exc_g [P, A*nrules (or 1)] i32 — anchors' gids per
                 rule level, -3 where the anchor's gid is invalid
                 (matches nothing; candidate gids are >= 0)
      any_anchor f32 — 1.0 where any anchor present (penalty gate)
    Node ids in prev_slot / prev_state / taken are GLOBAL (compared
    against global column ids in-kernel)."""

    base: jnp.ndarray
    neg_boost: jnp.ndarray
    validf: jnp.ndarray
    cand_g: jnp.ndarray
    stick: jnp.ndarray
    prev_slot: jnp.ndarray
    prev_state: jnp.ndarray
    taken: jnp.ndarray
    present: jnp.ndarray
    a_inc_g: jnp.ndarray
    a_exc_g: jnp.ndarray
    any_anchor: jnp.ndarray


def pack_score_inputs(
    *,
    total_l, total_p, w_div_l, neg_boost_l, valid_l,
    stickiness_si, prev_slot, prev_state, taken_ids,
    anchors, gids_l, gid_valid, gids, rules,
) -> ScoreInputs:
    """Build ScoreInputs from run_auction's existing terms.

    ``gids_l`` holds this shard's candidate columns; anchor lookups use
    the full ``gids``/``gid_valid`` tables (global ids), exactly like
    _hier_penalty."""
    base = (0.001 * total_l / jnp.maximum(total_p, 1.0)) / w_div_l
    validf = valid_l.astype(jnp.float32)
    p = prev_slot.shape[0]
    nrules = len(rules)
    if nrules:
        cand_g = jnp.concatenate(
            [jnp.stack([gids_l[inc] for (inc, _exc) in rules]),
             jnp.stack([gids_l[exc] for (_inc, exc) in rules])], axis=0)
        a_width = anchors.shape[1]
        aa = jnp.maximum(anchors, 0)
        inc_cols = []
        exc_cols = []
        for ai in range(a_width):
            for (inc, exc) in rules:
                inc_cols.append(jnp.where(
                    gid_valid[inc][aa[:, ai]], gids[inc][aa[:, ai]], -3))
                exc_cols.append(jnp.where(
                    gid_valid[exc][aa[:, ai]], gids[exc][aa[:, ai]], -3))
        a_inc_g = jnp.stack(inc_cols, axis=1)
        a_exc_g = jnp.stack(exc_cols, axis=1)
        present = (anchors >= 0).astype(jnp.float32)
        any_anchor = jnp.any(anchors >= 0, axis=1).astype(jnp.float32)
    else:
        cand_g = jnp.zeros((1, base.shape[0]), jnp.int32)
        a_inc_g = jnp.full((p, 1), -3, jnp.int32)
        a_exc_g = jnp.full((p, 1), -3, jnp.int32)
        present = jnp.zeros((p, 1), jnp.float32)
        any_anchor = jnp.zeros(p, jnp.float32)
    if taken_ids:
        taken = jnp.stack(taken_ids, axis=1)
    else:
        taken = jnp.full((p, 1), -1, jnp.int32)
    return ScoreInputs(
        base=base, neg_boost=neg_boost_l, validf=validf, cand_g=cand_g,
        stick=stickiness_si, prev_slot=prev_slot, prev_state=prev_state,
        taken=taken, present=present, a_inc_g=a_inc_g, a_exc_g=a_exc_g,
        any_anchor=any_anchor)


def _kernel(price_ref, base_ref, nb_ref, validf_ref, cand_ref, stick_ref,
            pslot_ref, pstate_ref, taken_ref, present_ref, ainc_ref,
            aexc_ref, anyr_ref, pbase_ref, noff_ref,
            best_ref, idx_ref, second_ref, raw_ref, *,
            tile_p: int, tile_n: int, n: int, nrules: int, a_width: int,
            r_width: int, t_width: int, jitter_scale: float):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        best_ref[:] = jnp.full_like(best_ref, float("inf"))
        second_ref[:] = jnp.full_like(second_ref, float("inf"))
        idx_ref[:] = jnp.zeros_like(idx_ref)
        raw_ref[:] = jnp.zeros_like(raw_ref)

    tp = stick_ref.shape[0]
    tn = price_ref.shape[1]
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (tp, tn), 1) + \
        j * tile_n
    cols_g = cols_local + noff_ref[0, 0]  # GLOBAL ids for id compares

    # --- the score formula, term order mirroring run_auction ---
    base = base_ref[:]
    nb = nb_ref[:]
    stick = stick_ref[:]  # [tp, 1]
    score = base + jnp.where(nb > 0, jnp.maximum(nb, stick), 0.0)
    score = score - 0.01 * (pslot_ref[:] == cols_g).astype(jnp.float32)
    pstate = pstate_ref[:]
    sticky = pstate[:, 0:1] == cols_g
    for r in range(1, r_width):
        sticky = sticky | (pstate[:, r:r + 1] == cols_g)
    score = score - stick * sticky.astype(jnp.float32)
    if nrules:
        cand = cand_ref[:]
        ainc = ainc_ref[:]
        aexc = aexc_ref[:]
        present = present_ref[:]
        pen = jnp.full(score.shape, _RULE_MISS, jnp.float32)
        for idx in range(nrules):
            sat = jnp.ones(score.shape, jnp.bool_)
            for ai in range(a_width):
                col = ai * nrules + idx
                inc_same = ainc[:, col:col + 1] == cand[idx:idx + 1, :]
                exc_same = aexc[:, col:col + 1] == \
                    cand[nrules + idx:nrules + idx + 1, :]
                # (absent anchor passes) OR (rule gate) — spelled as
                # boolean algebra, not jnp.where: a select over i1
                # vectors lowers to an i8->i1 truncation Mosaic rejects.
                sat = sat & ((present[:, ai:ai + 1] <= 0.0)
                             | (inc_same & ~exc_same))
            pen = jnp.where(sat, jnp.minimum(pen, idx * _RULE_TIER), pen)
        score = score + jnp.where(anyr_ref[:] > 0, pen, 0.0)
    taken = taken_ref[:]
    tk = taken[:, 0:1] == cols_g
    for t in range(1, t_width):
        tk = tk | (taken[:, t:t + 1] == cols_g)
    score = score + _INF * (tk | (validf_ref[:] == 0.0)).astype(jnp.float32)
    # Deterministic tie-break jitter — identical hash to _assign_slot's.
    pi = (pbase_ref[0, 0] + i * tile_p
          + jax.lax.broadcasted_iota(jnp.int32, score.shape, 0))
    score = score + jitter_scale * jitter_hash(pi, cols_g)
    # --- fused min2/argmin over score + price ---
    price = price_ref[:]
    x = score + price
    if n % tn:
        x = jnp.where(cols_local < n, x, float("inf"))

    tile_best = jnp.min(x, axis=1, keepdims=True)
    is_min = x == tile_best
    tile_idx = jnp.min(jnp.where(is_min, cols_local, n), axis=1,
                       keepdims=True)
    x_wo = jnp.where(cols_local == tile_idx, float("inf"), x)
    tile_second = jnp.min(x_wo, axis=1, keepdims=True)
    # Unpriced score at the tile argmin: best minus the price there.
    price_at = jnp.sum(
        jnp.where(cols_local == tile_idx, jnp.broadcast_to(price, x.shape),
                  0.0), axis=1, keepdims=True)
    tile_raw = tile_best - price_at

    run_best = best_ref[:]
    run_second = second_ref[:]
    new_second = jnp.minimum(jnp.maximum(run_best, tile_best),
                             jnp.minimum(run_second, tile_second))
    win = tile_best < run_best
    best_ref[:] = jnp.minimum(run_best, tile_best)
    second_ref[:] = new_second
    idx_ref[:] = jnp.where(win, tile_idx, idx_ref[:])
    raw_ref[:] = jnp.where(win, tile_raw, raw_ref[:])


@functools.partial(
    jax.jit, static_argnames=("nrules", "jitter_scale", "tile_p", "tile_n",
                              "interpret", "vma"))
def fused_score_min2(
    price: jnp.ndarray,  # [N_l] f32, +INF where closed
    si: ScoreInputs,
    pbase,  # [1, 1] i32: global partition index of local row 0 (jitter)
    noff,  # [1, 1] i32: global column offset of this shard
    *,
    nrules: int,
    jitter_scale: float,
    tile_p: int = _TILE_P,
    tile_n: int = _TILE_N,
    interpret: bool = False,
    vma: tuple = (),
):
    """(best, choice_LOCAL, second, raw) per row; score built in-VMEM.

    The caller adds ``noff`` to the returned choice for global ids.
    ``vma`` names the mesh axes the outputs vary over when called under
    shard_map (the partition axis always; the node axis too on a 2-D
    mesh) — shard_map's varying-axes checker requires the annotation on
    pallas_call outputs."""
    p = si.stick.shape[0]
    n = price.shape[0]
    if n == 0:
        raise ValueError("fused_score_min2 requires N >= 1")
    tp = min(tile_p, max(p, 1))
    tn = min(tile_n, n)
    grid = (pl.cdiv(p, tp), pl.cdiv(n, tn))

    r_width = si.prev_state.shape[1]
    t_width = si.taken.shape[1]
    a_width = si.present.shape[1]

    # Pre-vma JAX has no varying-axes checker (and no ``vma`` kwarg on
    # ShapeDtypeStruct); those runtimes use check_rep=False instead, so
    # the annotation is simply not needed there.
    sds_kw = {"vma": frozenset(vma)} if vma and _SDS_HAS_VMA else {}
    out_shape = [
        jax.ShapeDtypeStruct((p, 1), jnp.float32, **sds_kw),  # best
        jax.ShapeDtypeStruct((p, 1), jnp.int32, **sds_kw),    # idx (local)
        jax.ShapeDtypeStruct((p, 1), jnp.float32, **sds_kw),  # second
        jax.ShapeDtypeStruct((p, 1), jnp.float32, **sds_kw),  # raw at idx
    ]
    out_spec = pl.BlockSpec((tp, 1), lambda i, j: (i, 0))
    row1 = pl.BlockSpec((1, tn), lambda i, j: (0, j))
    colp = lambda cols_: pl.BlockSpec((tp, cols_), lambda i, j: (i, 0))
    scalar = pl.BlockSpec((1, 1), lambda i, j: (0, 0))

    best, idx, second, raw = pl.pallas_call(
        functools.partial(
            _kernel, tile_p=tp, tile_n=tn, n=n, nrules=nrules,
            a_width=a_width, r_width=r_width, t_width=t_width,
            jitter_scale=jitter_scale),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            row1,               # price
            row1,               # base
            row1,               # neg_boost
            row1,               # validf
            pl.BlockSpec((si.cand_g.shape[0], tn),
                         lambda i, j: (0, j)),  # cand_g
            colp(1),            # stick
            colp(1),            # prev_slot
            colp(r_width),      # prev_state
            colp(t_width),      # taken
            colp(a_width),      # present
            colp(si.a_inc_g.shape[1]),  # a_inc_g
            colp(si.a_exc_g.shape[1]),  # a_exc_g
            colp(1),            # any_anchor
            scalar,             # pbase
            scalar,             # noff
        ],
        out_specs=[out_spec, out_spec, out_spec, out_spec],
        interpret=interpret,
    )(
        price.reshape(1, n),
        si.base.reshape(1, n),
        si.neg_boost.reshape(1, n),
        si.validf.reshape(1, n),
        si.cand_g,
        si.stick.reshape(p, 1),
        si.prev_slot.reshape(p, 1),
        si.prev_state,
        si.taken,
        si.present,
        si.a_inc_g,
        si.a_exc_g,
        si.any_anchor.reshape(p, 1),
        jnp.asarray(pbase, jnp.int32).reshape(1, 1),
        jnp.asarray(noff, jnp.int32).reshape(1, 1),
    )
    return best[:, 0], idx[:, 0], second[:, 0], raw[:, 0]


def score_at_columns(
    rows: jnp.ndarray,  # [K] local row ids
    cols_global: jnp.ndarray,  # [K] GLOBAL column ids (>= 0)
    *,
    base_full: jnp.ndarray,  # [N] FULL node-replicated base
    neg_boost_full: jnp.ndarray,
    valid_full: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    anchors: Optional[jnp.ndarray],
    rules: tuple,
    prev_slot: jnp.ndarray,  # [P] global ids
    prev_state: jnp.ndarray,  # [P, R]
    taken_ids: tuple,
    stick: jnp.ndarray,  # [P]
    jitter_scale: float,
    pbase,  # [1, 1]
) -> jnp.ndarray:
    """The same score formula evaluated at single (row, col) pairs with
    [K] ops — phase B's waterfall probe when no matrix exists.  Inputs
    are the FULL node-replicated tables, so no node-axis collective is
    needed (every shard computes identically)."""
    from ..plan.tensor import _hier_tier_at  # shared rule semantics

    c = cols_global
    s = base_full[c]
    nb = neg_boost_full[c]
    stick_r = stick[rows]
    s = s + jnp.where(nb > 0, jnp.maximum(nb, stick_r), 0.0)
    s = s - 0.01 * (prev_slot[rows] == c).astype(jnp.float32)
    sticky = jnp.zeros(rows.shape[0], jnp.bool_)
    for r in range(prev_state.shape[1]):
        sticky = sticky | (prev_state[rows, r] == c)
    s = s - stick_r * sticky.astype(jnp.float32)
    if rules:
        s = s + _hier_tier_at(anchors[rows], c, gids, gid_valid, rules)
    tk = jnp.zeros(rows.shape[0], jnp.bool_)
    for tid in taken_ids:
        tk = tk | (tid[rows] == c)
    s = s + _INF * (tk | ~valid_full[c]).astype(jnp.float32)
    pi = (jnp.asarray(pbase).reshape(()) + rows).astype(jnp.int32)
    return s + jitter_scale * jitter_hash(pi, c.astype(jnp.int32))
