"""Pallas TPU kernel: fused priced (min, argmin, second, raw) over a
gathered [P, K] candidate score block.

The sparse auction round (plan/tensor.py, shortlist path) needs, per
partition row of the gathered score block ``score[P, K]`` (K candidate
columns per row, K << N) and its gathered per-candidate price
``price[P, K]``:

    eff    = score + price
    best   = min(eff, axis=1)
    kidx   = argmin(eff, axis=1)              (first occurrence)
    second = min(eff with the argmin POSITION masked out, axis=1)
    raw    = score[row, kidx]                 (UNPRICED score at the pick)

The stock-XLA spelling costs four [P, K] HBM passes (min, argmin, a full
masked copy for the second, a take for raw).  This kernel fuses all four
into one: each grid step loads a (TILE_P, TILE_K) block pair into VMEM,
reduces on the VPU, and merges into running accumulators resident in
VMEM across the K-axis grid dimension — the [P, K] shape of the sparse
solve is exactly what makes the whole sweep O(P*K) instead of the dense
engine's O(P*N), so its reduction must not re-read the block.

Unlike ops/reduce2.py the price is a per-(row, candidate) MATRIX, not a
broadcast [N] row: the candidate ids differ per row, so the caller
gathers ``price_full[cand]`` once per round (that gather IS the sparse
memory budget) and this kernel fuses everything downstream of it.

Correctness notes:
- Ties break toward the LOWEST candidate index (strict ``<`` across
  tiles, ``jnp.argmin`` first-occurrence within a tile) — matching
  :func:`sparse_min2_reference` exactly, which the planner's saturating
  K = N bit-identity contract relies on (candidate column k IS node k
  there, so tie order matches the dense engine's lowest-node-id rule).
- ``second`` masks the argmin position, not its value: duplicate minima
  at different candidates yield ``second == best``.
- Ragged K tails are masked in-kernel with +inf; padded rows reduce
  garbage into garbage and are sliced off by pallas itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._tiles import tile_env
from .reduce2 import pallas_available

__all__ = ["sparse_min2_reference", "sparse_priced_min2",
           "pallas_available"]

_INF = float("inf")

# Tile shape for the sparse reduction, overridable for tuning sweeps.
# K is small by design (tens), so the default K tile covers the whole
# candidate axis in one block for every realistic shortlist; the P tile
# matches the other kernels' sublane-aligned default.  Read once at
# import (jit-static; see ops/_tiles.py).
_TILE_P = tile_env("BLANCE_SPARSE2_TILE_P", 512, 8)
_TILE_K = tile_env("BLANCE_SPARSE2_TILE_K", 512, 128)


def sparse_min2_reference(score: jnp.ndarray, price: jnp.ndarray):
    """Stock-XLA spelling (fallback path and test oracle).

    Returns ``(best[P] f32, kidx[P] i32, second[P] f32, raw[P] f32)``
    over ``eff = score + price`` with raw = the UNPRICED score at the
    argmin — the exact tuple the sparse auction consumes.
    """
    p = score.shape[0]
    eff = score + price
    best = jnp.min(eff, axis=1)
    kidx = jnp.argmin(eff, axis=1).astype(jnp.int32)
    masked = eff.at[jnp.arange(p), kidx].set(jnp.inf)
    second = jnp.min(masked, axis=1)
    raw = jnp.take_along_axis(score, kidx[:, None], axis=1)[:, 0]
    return best, kidx, second, raw


def _kernel(score_ref, price_ref, best_ref, idx_ref, second_ref, raw_ref,
            *, tile_k: int, k: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        best_ref[:] = jnp.full_like(best_ref, _INF)
        second_ref[:] = jnp.full_like(second_ref, _INF)
        idx_ref[:] = jnp.zeros_like(idx_ref)
        raw_ref[:] = jnp.zeros_like(raw_ref)

    score = score_ref[:]
    x = score + price_ref[:]  # [TP, TK]
    tp, tk = x.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (tp, tk), 1)
    # Mask the ragged K tail (pallas zero-fills partial blocks; a stray 0
    # would beat real scores) so no host-side padding copy is needed.
    if k % tk:
        x = jnp.where(j * tile_k + cols < k, x, _INF)

    tile_best = jnp.min(x, axis=1, keepdims=True)  # [TP, 1]
    is_min = x == tile_best
    # First-occurrence argmin within the tile.
    tile_idx = jnp.min(jnp.where(is_min, cols, tk), axis=1, keepdims=True)
    # Second-min masks the argmin POSITION only.
    x_wo = jnp.where(cols == tile_idx, _INF, x)
    tile_second = jnp.min(x_wo, axis=1, keepdims=True)
    # Unpriced score at the tile argmin (a masked sum: exactly one hit).
    tile_raw = jnp.sum(jnp.where(cols == tile_idx, score, 0.0), axis=1,
                       keepdims=True)
    tile_idx = tile_idx + j * tile_k

    run_best = best_ref[:]
    run_second = second_ref[:]

    # The loser of the best-vs-best match is a second-min candidate.
    new_second = jnp.minimum(jnp.maximum(run_best, tile_best),
                             jnp.minimum(run_second, tile_second))
    # Strict <: on equal values the earlier (lower-index) tile keeps argmin.
    win = tile_best < run_best
    best_ref[:] = jnp.minimum(run_best, tile_best)
    second_ref[:] = new_second
    idx_ref[:] = jnp.where(win, tile_idx, idx_ref[:])
    raw_ref[:] = jnp.where(win, tile_raw, raw_ref[:])


@functools.partial(jax.jit,
                   static_argnames=("tile_p", "tile_k", "interpret"))
def sparse_priced_min2(
    score: jnp.ndarray,  # [P, K] gathered candidate scores
    price: jnp.ndarray,  # [P, K] gathered per-candidate prices
    *,
    tile_p: int = _TILE_P,
    tile_k: int = _TILE_K,
    interpret: bool = False,
):
    """Fused (best, argmin, second, raw) over ``score + price``.

    Bit-identical to :func:`sparse_min2_reference` (pinned by
    tests/test_sparse.py in interpret mode; bench.py verifies the
    compiled kernel on device before timing the sparse stage).
    """
    p, k = score.shape
    if k == 0:
        # A zero-size row reduction has no defined argmin; fail loudly
        # like the XLA oracle instead of returning never-written buffers.
        raise ValueError("sparse_priced_min2 requires K >= 1 (got shape "
                         "%r)" % ((p, k),))
    if price.shape != score.shape:
        raise ValueError(f"price shape {price.shape} != score shape "
                         f"{score.shape}")
    tp = min(tile_p, max(p, 1))
    tk = min(tile_k, k)

    grid = (pl.cdiv(p, tp), pl.cdiv(k, tk))
    out_shape = [
        jax.ShapeDtypeStruct((p, 1), jnp.float32),  # best
        jax.ShapeDtypeStruct((p, 1), jnp.int32),    # idx
        jax.ShapeDtypeStruct((p, 1), jnp.float32),  # second
        jax.ShapeDtypeStruct((p, 1), jnp.float32),  # raw
    ]
    # Output blocks ignore the K grid index, so the accumulators stay
    # resident in VMEM across the whole K sweep of a P tile.
    out_spec = pl.BlockSpec((tp, 1), lambda i, j: (i, 0))
    block = pl.BlockSpec((tp, tk), lambda i, j: (i, j))
    best, idx, second, raw = pl.pallas_call(
        functools.partial(_kernel, tile_k=tk, k=k),
        out_shape=out_shape,
        grid=grid,
        in_specs=[block, block],
        out_specs=[out_spec, out_spec, out_spec, out_spec],
        interpret=interpret,
    )(score, price.astype(jnp.float32))

    return best[:, 0], idx[:, 0], second[:, 0], raw[:, 0]
