"""Pallas TPU kernel: fused row-wise (min, argmin, second-min).

The auction round of the tensor planner (blance_tpu/plan/tensor.py) needs,
per partition row of the effective score matrix ``eff[P, N]``:

    best   = min(eff, axis=1)
    choice = argmin(eff, axis=1)              (first occurrence)
    second = min(eff with the argmin POSITION masked out, axis=1)

The stock-XLA spelling materializes a full [P, N] copy for the position
mask (``eff.at[arange, choice].set(inf)``) and runs three separate
reductions — four HBM round-trips over the biggest tensor in the solver.
This kernel fuses all three into ONE pass: each grid step loads a
(TILE_P, TILE_N) block into VMEM, reduces it on the VPU, and merges into
running (best, second, idx) accumulators that stay resident in VMEM
across the N-axis grid dimension.  HBM traffic drops to a single read of
``eff`` plus three [P]-sized writes.

This replaces the hottest memory-bound op of the planner's while-loop; the
reference's analogous work is the per-partition ``sort.Sort(nodeSorter)``
inside its sequential loop (reference plan.go:172, plan.go:617-628).

Correctness notes:
- Ties break toward the LOWEST index (strict ``<`` when merging tiles, and
  ``jnp.argmin``'s first-occurrence rule within a tile) — matching
  ``jnp.argmin`` exactly, which the planner relies on for determinism.
- ``second`` masks the argmin position, not its value: duplicate minima at
  different indices yield ``second == best``, as the planner expects for
  its urgency margin.
- Rows are padded with +inf when P or N is not a multiple of the tile; a
  padded N-tail can never win a min, and padded rows are sliced off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._tiles import tile_env

__all__ = ["min2_argmin", "min2_argmin_reference", "priced_min2_argmin",
           "pallas_available"]

_INF = float("inf")

# Default tile shape for the priced reduction, overridable for tuning
# sweeps (bench.py --tile-sweep).  This kernel is the matrix engine's hot
# op in BOTH the cold fixpoint and the warm one-sweep repair, so the
# sweep's tile choice feeds the delta-replan path too.  Read once at
# import (jit-static; see ops/_tiles.py).
_TILE_P = tile_env("BLANCE_REDUCE2_TILE_P", 256, 8)
_TILE_N = tile_env("BLANCE_REDUCE2_TILE_N", 2048, 128)


def min2_argmin_reference(eff: jnp.ndarray):
    """Stock-XLA spelling (the fallback path and the test oracle)."""
    p = eff.shape[0]
    best = jnp.min(eff, axis=1)
    choice = jnp.argmin(eff, axis=1).astype(jnp.int32)
    masked = eff.at[jnp.arange(p), choice].set(jnp.inf)
    second = jnp.min(masked, axis=1)
    return best, choice, second


def _kernel(x_ref, price_ref, best_ref, idx_ref, second_ref, *,
            tile_n: int, n: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        best_ref[:] = jnp.full_like(best_ref, _INF)
        second_ref[:] = jnp.full_like(second_ref, _INF)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    # Fold the per-node price row in VMEM instead of materializing the
    # priced matrix in HBM (the auction re-prices every round; without the
    # fusion each round costs a full [P, N] write + read of `eff`).
    x = x_ref[:] + price_ref[:]  # [TP, TN] + [1, TN]
    tp, tn = x.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (tp, tn), 1)
    # Mask the ragged N tail (pallas zero-fills partial blocks; a stray 0
    # would beat real scores) so no host-side padding copy is ever needed.
    if n % tn:
        x = jnp.where(j * tile_n + cols < n, x, _INF)

    tile_best = jnp.min(x, axis=1, keepdims=True)  # [TP, 1]
    is_min = x == tile_best
    # First-occurrence argmin within the tile.
    tile_idx = jnp.min(jnp.where(is_min, cols, tn), axis=1, keepdims=True)
    # Second-min masks the argmin POSITION only.
    x_wo = jnp.where(cols == tile_idx, _INF, x)
    tile_second = jnp.min(x_wo, axis=1, keepdims=True)
    tile_idx = tile_idx + j * tile_n

    run_best = best_ref[:]
    run_second = second_ref[:]
    run_idx = idx_ref[:]

    new_best = jnp.minimum(run_best, tile_best)
    # The loser of the best-vs-best match is a second-min candidate.
    new_second = jnp.minimum(jnp.maximum(run_best, tile_best),
                             jnp.minimum(run_second, tile_second))
    # Strict <: on equal values the earlier (lower-index) tile keeps argmin.
    new_idx = jnp.where(tile_best < run_best, tile_idx, run_idx)

    best_ref[:] = new_best
    second_ref[:] = new_second
    idx_ref[:] = new_idx


@functools.partial(jax.jit, static_argnames=("tile_p", "tile_n", "interpret"))
def priced_min2_argmin(
    score: jnp.ndarray,
    price: jnp.ndarray,
    *,
    tile_p: int = _TILE_P,
    tile_n: int = _TILE_N,
    interpret: bool = False,
):
    """Fused (best, argmin, second-min) over axis 1 of ``score + price``.

    ``price[N]`` is the auction's per-node additive term (in-slot price +
    closed-node penalty); it is broadcast-added inside the kernel so the
    priced matrix never exists in HBM.  Returns ``(best[P] f32,
    choice[P] i32, second[P] f32)`` — bit-identical to
    ``min2_argmin_reference(score + price[None, :])``.
    """
    p, n = score.shape
    if n == 0:
        # A zero-size row reduction has no defined argmin; fail loudly like
        # the XLA oracle instead of returning never-written buffers.
        raise ValueError("min2_argmin requires N >= 1 (got shape %r)"
                         % ((p, n),))
    tp = min(tile_p, max(p, 1))
    tn = min(tile_n, n)

    grid = (pl.cdiv(p, tp), pl.cdiv(n, tn))
    out_shape = [
        jax.ShapeDtypeStruct((p, 1), jnp.float32),  # best
        jax.ShapeDtypeStruct((p, 1), jnp.int32),    # idx
        jax.ShapeDtypeStruct((p, 1), jnp.float32),  # second
    ]
    # Output blocks ignore the N grid index, so the accumulators stay
    # resident in VMEM across the whole N sweep of a P tile.  Ragged tails
    # need no padding: partial P blocks reduce row-wise (garbage rows never
    # touch real rows) and the ragged N tail is masked in-kernel.
    out_spec = pl.BlockSpec((tp, 1), lambda i, j: (i, 0))
    best, idx, second = pl.pallas_call(
        functools.partial(_kernel, tile_n=tn, n=n),
        out_shape=out_shape,
        grid=grid,
        in_specs=[pl.BlockSpec((tp, tn), lambda i, j: (i, j)),
                  pl.BlockSpec((1, tn), lambda i, j: (0, j))],
        out_specs=[out_spec, out_spec, out_spec],
        interpret=interpret,
    )(score, price.reshape(1, n).astype(jnp.float32))

    return best[:, 0], idx[:, 0], second[:, 0]


@functools.partial(jax.jit, static_argnames=("tile_p", "tile_n", "interpret"))
def min2_argmin(
    eff: jnp.ndarray,
    *,
    tile_p: int = _TILE_P,
    tile_n: int = _TILE_N,
    interpret: bool = False,
):
    """Fused (best, argmin, second-min) over axis 1 of ``eff[P, N]``."""
    return priced_min2_argmin(
        eff, jnp.zeros(eff.shape[1], jnp.float32),
        tile_p=tile_p, tile_n=tile_n, interpret=interpret)


def pallas_available() -> bool:
    """True when the Pallas path should be used (a real TPU backend)."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover — backend init failed: no
        return False      # usable device at all, so no Pallas either
