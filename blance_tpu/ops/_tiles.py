"""Shared tile-size plumbing for the Pallas kernels.

Both kernels (the priced min2 reduction in reduce2.py and the in-kernel
score in score_fused.py) tile [P, N] work into (TILE_P, TILE_N) VMEM
blocks.  The tile shape is a pure throughput knob — results are
bit-identical across tiles — so it is tunable per deployment via
environment variables, read ONCE at import: the values are jit-static,
and changing them mid-process would silently recompile rather than
retune.  ``bench.py --tile-sweep`` measures the candidates and emits the
choice as a JSON artifact.
"""

from __future__ import annotations

import os

__all__ = ["tile_env"]


def tile_env(name: str, default: int, multiple: int) -> int:
    """Read a tile size from the environment, validated for TPU
    sublane/lane alignment (an unaligned tile dies deep inside Mosaic
    with an opaque lowering error; reject it here with the env var's
    name instead)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if v < 1:
        raise ValueError(f"{name}={v} must be >= 1")
    if v % multiple:
        raise ValueError(
            f"{name}={v} must be a multiple of {multiple} (TPU "
            f"sublane/lane alignment)")
    return v
