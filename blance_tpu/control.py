"""The reusable converge-cycle engine behind every control loop.

``rebalance.RebalanceController`` (PR 10) owned the whole
debounce/coalesce/converge state machine inline.  The fleet tier
(``blance_tpu/fleetloop.py``) needs that exact machine *per tenant* —
hundreds of independent control loops multiplexed on ONE event loop, no
thread per tenant — so the generic half lives here as
:class:`CycleEngine`: the pending-delta intake, the wake/idle events,
the debounce window, the take-pending/converge cycle, and the
stop/quiesce rendezvous.  ``RebalanceController`` subclasses it and
keeps everything cluster-specific (planning, orchestration, supersede,
SLO accounting) in the hook methods.

Single-task discipline (analysis/race_lint.py ``SHARED_STATE``): the
engine's control state is touched by the app-facing sync surface
(``submit``/``stop_soon``) and the engine task; every mutation sits in
one no-await window, and the bounded rendezvous between them is the
wake event plus the pending list, taken atomically
(:meth:`_take_pending` clears the event in the same sync window that
takes the list, so a set can never be lost between a take and its
pending snapshot).

Time comes exclusively from the injected ``clock`` (pass
``recorder.now``), so a fleet of engines — debounce windows included —
runs deterministically under ``testing.sched.DeterministicLoop``.

:class:`CyclePlanner` is the seam that makes converge cycles
*coalescible*: a controller constructed with one plans ASYNCHRONOUSLY,
so N tenants' overlapping debounce windows can land their plan requests
in one shared ``plan.service.PlanService`` admission window — one
bucketed ``[B, ...]`` fleet dispatch instead of N device dispatches
(docs/FLEET.md "Fleet of control loops").
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional, Protocol

__all__ = ["CycleEngine", "CyclePlanner"]


class CyclePlanner(Protocol):
    """Async planning seam for a converge cycle.

    ``plan_cycle`` receives the loop's folded view — the current map,
    the full node list, the nodes to drain (graceful removals, abrupt
    failures and quarantined nodes alike), the model and the live
    options — and returns ``(next_map, warnings)`` exactly like
    ``plan.api.plan_next_map``.  Because it is awaited, N controllers
    sharing one :class:`~blance_tpu.plan.service.PlanService`-backed
    planner coalesce their cycles into shared fleet dispatches.

    **Optional residency hooks** (duck-typed — the controller calls
    them via ``getattr`` so plain planners need not define them): a
    planner that keeps *resident encoded state* between cycles
    (``fleetloop.ServicePlanner`` with encode residency,
    docs/DESIGN.md "Encode residency") can implement

    - ``notify_strip(nodes, before, after)`` — called in the same sync
      window an abrupt-fail delta replaced the controller's current
      map (``before`` → ``after``, dark placements stripped), so the
      planner can patch its resident encoding in O(delta) instead of
      re-encoding the whole map next cycle;
    - ``notify_pass(achieved, end_map, clean)`` — called when an
      orchestration pass adopted ``achieved`` as current; ``clean`` is
      the controller's hint that the pass fully landed ``end_map``
      (no supersede/cancel/failures/quarantine).  The planner owns the
      final verification and MUST demote to a full re-encode on
      anything it cannot prove — the conservative-protocol contract is
      that a missed hook or failed check only ever costs a cold
      encode, never a stale map."""

    async def plan_cycle(
        self,
        current: Any,
        nodes: list[str],
        removes: list[str],
        model: Any,
        opts: Any,
    ) -> tuple[Any, dict[str, list[str]]]: ...


class CycleEngine:
    """Debounced, coalescing converge-cycle loop (the generic half of
    ``rebalance.RebalanceController``; see the module doc).

    Subclasses implement :meth:`_apply_deltas` (fold a burst of deltas
    into their view, one sync window) and :meth:`_converge` (drive the
    view to a fixpoint), plus the optional hooks ``_on_submit``,
    ``_on_stop_soon``, ``_on_idle`` and ``_on_exit``."""

    #: asyncio task name for the engine task (subclasses override).
    TASK_NAME = "cycle-engine"

    def __init__(self, *, debounce_s: float,
                 clock: Callable[[], float]) -> None:
        self.debounce_s = debounce_s
        self._clock = clock
        self._pending: list[Any] = []
        self._wake = asyncio.Event()
        self._idle = asyncio.Event()
        self._idle.set()
        self._stopping = False
        self._task: "Optional[asyncio.Task[object]]" = None
        self.cycles = 0
        # Called with the clock time whenever the engine returns to idle
        # (no pending deltas, nothing in flight) — the simulator's
        # per-incident convergence-lag hook.
        self.on_quiesce: list[Callable[[float], None]] = []

    # -- app-facing control surface (sync: single atomic windows) ---------

    def submit(self, delta: Any) -> None:
        """Enqueue a delta; coalesces with everything else that arrives
        within the debounce window.  Sync and re-entrant from progress
        callbacks."""
        self._pending.append(delta)
        self._on_submit(delta)
        self._idle.clear()
        self._wake.set()

    def stop_soon(self) -> None:
        """Request wind-down: lets the engine task exit (subclass hooks
        cancel anything in flight).  Sync; pair with ``await stop()``
        (or await the start() task) for the rendezvous."""
        self._stopping = True
        self._wake.set()
        self._on_stop_soon()

    def start(self) -> "asyncio.Task[object]":
        """Spawn the engine task (requires a running loop)."""
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
            self._task.set_name(self.TASK_NAME)
        return self._task

    async def stop(self) -> None:
        """stop_soon + await the engine task's exit."""
        self.stop_soon()
        if self._task is not None:
            await self._task

    async def quiesce(self) -> Any:
        """Wait until the engine is idle (every submitted delta
        converged or structurally degraded).  Subclasses narrow the
        return to their converged view (the controller returns its
        current map)."""
        await self._idle.wait()
        return None

    def pending_tasks(self) -> "list[asyncio.Task[object]]":
        """Unfinished engine tasks — the no-orphan probe for explorer
        scenarios (subclasses extend with in-flight work)."""
        out: "list[asyncio.Task[object]]" = []
        if self._task is not None and not self._task.done():
            out.append(self._task)
        return out

    # -- the loop ----------------------------------------------------------

    async def _run(self) -> None:
        try:
            while not self._stopping:
                if not self._pending:
                    self._set_idle()
                    await self._wake.wait()
                    continue
                if self.debounce_s > 0:
                    # Coalesce the burst: everything that lands during
                    # this (virtual-time) window joins the cycle.
                    await asyncio.sleep(self.debounce_s)
                deltas = self._take_pending()
                if deltas:
                    self._apply_deltas(deltas)
                    self.cycles += 1
                    self._on_cycle(self.cycles, len(deltas))
                    await self._converge()
        finally:
            self._on_exit()
            self._set_idle()

    def _take_pending(self) -> list[Any]:
        taken, self._pending = self._pending, []
        self._wake.clear()
        return taken

    def _set_idle(self) -> None:
        if not self._idle.is_set():
            self._idle.set()
            t = self._clock()
            self._on_idle(t)
            for hook in self.on_quiesce:
                hook(t)

    async def _wake_wait(self) -> None:
        await self._wake.wait()

    # -- subclass surface --------------------------------------------------

    def _apply_deltas(self, deltas: list[Any]) -> None:
        """Fold a burst of deltas into the subclass view, IN ORDER, in
        one sync window."""
        raise NotImplementedError

    async def _converge(self) -> None:
        """Drive the view to a fixpoint (or a structural degradation /
        a supersede / the pass budget)."""
        raise NotImplementedError

    def _on_submit(self, delta: Any) -> None:
        """Sync hook inside :meth:`submit`'s atomic window (counters,
        SLO incident opening, WAL delta-intake records)."""

    def _on_cycle(self, n: int, deltas: int) -> None:
        """Sync hook at cycle begin — after the delta burst folded into
        the view, before convergence starts.  The explicit cycle-begin
        seam the durability journal records through."""

    def _on_stop_soon(self) -> None:
        """Sync hook inside :meth:`stop_soon` (cancel in-flight work)."""

    def _on_idle(self, t: float) -> None:
        """Sync hook inside :meth:`_set_idle`, before the quiesce
        callbacks run (SLO incident closing)."""

    def _on_exit(self) -> None:
        """Sync hook on engine-task exit, BEFORE the final idle edge (a
        crash / mid-episode stop is not a quiesce)."""
