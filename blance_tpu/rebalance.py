"""App-level rebalance facade: plan -> diff -> orchestrate in one call.

The reference leaves this composition to the application (SURVEY.md §3.4:
plan or hand-build the end map, call OrchestrateMoves, drain ProgressCh,
Stop).  This module packages the canonical wiring, with the checkpoint
story built in: the PartitionMap IS the checkpoint (JSON-serializable by
design, reference api.go:30-35), so a crashed rebalance resumes by
re-planning from the current map and orchestrating the remaining diff —
the planner is pure and idempotent at fixpoint (plan_test.go:1888-1908).
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from .core.types import (
    PartitionMap,
    PartitionModel,
    PlanOptions,
    partition_map_from_json,
    partition_map_to_json,
)
from .orchestrate.orchestrator import (
    FindMoveFunc,
    OrchestratorOptions,
    OrchestratorProgress,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)
from .plan.api import plan_next_map
from .utils.trace import PhaseTimer

__all__ = [
    "RebalanceResult",
    "rebalance",
    "rebalance_async",
    "save_partition_map",
    "load_partition_map",
]


@dataclass
class RebalanceResult:
    """Everything a caller needs after a full rebalance."""

    next_map: PartitionMap
    warnings: dict[str, list[str]]
    progress: OrchestratorProgress
    progress_events: int
    timer: PhaseTimer = field(default_factory=PhaseTimer)


def save_partition_map(pmap: PartitionMap, path: str) -> None:
    """Checkpoint a map as JSON (atomic rename)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(partition_map_to_json(pmap), f)
    os.replace(tmp, path)


def load_partition_map(path: str) -> PartitionMap:
    with open(path) as f:
        return partition_map_from_json(json.load(f))


async def rebalance_async(
    model: PartitionModel,
    current_map: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    assign_partitions,
    *,
    plan_options: Optional[PlanOptions] = None,
    orchestrator_options: Optional[OrchestratorOptions] = None,
    find_move: Optional[FindMoveFunc] = None,
    backend: str = "auto",
    on_progress: Optional[Callable[[OrchestratorProgress], None]] = None,
    checkpoint_path: Optional[str] = None,
) -> RebalanceResult:
    """Plan the next map and execute the transition against the callback.

    assign_partitions(stop_ch, node, partitions, states, ops) is the app's
    data plane (sync or async).  on_progress sees every progress snapshot.
    checkpoint_path, if set, saves the planned target map before
    orchestration begins; on a mid-orchestration crash, resume by re-running
    rebalance from the app's current map (the planner is idempotent at
    fixpoint, so the redo converges) or diff current vs the checkpointed
    target directly.
    """
    timer = PhaseTimer()
    with timer.phase("plan"):
        next_map, warnings = plan_next_map(
            current_map, current_map, nodes_all,
            nodes_to_remove, nodes_to_add, model,
            plan_options, backend=backend)

    if checkpoint_path:
        with timer.phase("checkpoint"):
            save_partition_map(next_map, checkpoint_path)

    events = 0
    with timer.phase("orchestrate"):
        o = orchestrate_moves(
            model,
            orchestrator_options or OrchestratorOptions(),
            nodes_all,
            current_map,
            next_map,
            assign_partitions,
            find_move or lowest_weight_partition_move_for_node,
        )
        final = OrchestratorProgress()
        async for progress in o.progress_ch():
            events += 1
            final = progress
            if on_progress is not None:
                on_progress(progress)
        o.stop()

    return RebalanceResult(
        next_map=next_map,
        warnings=warnings,
        progress=final,
        progress_events=events,
        timer=timer,
    )


def rebalance(*args, **kwargs) -> RebalanceResult:
    """Synchronous wrapper around rebalance_async (runs its own loop)."""
    return asyncio.run(rebalance_async(*args, **kwargs))
