"""App-level rebalance facade: plan -> diff -> orchestrate in one call.

The reference leaves this composition to the application (SURVEY.md §3.4:
plan or hand-build the end map, call OrchestrateMoves, drain ProgressCh,
Stop).  This module packages the canonical wiring, with the checkpoint
story built in: the PartitionMap IS the checkpoint (JSON-serializable by
design, reference api.go:30-35), so a crashed rebalance resumes by
re-planning from the current map and orchestrating the remaining diff —
the planner is pure and idempotent at fixpoint (plan_test.go:1888-1908).

Failure-aware recovery (docs/DESIGN.md "Failure semantics & recovery"):
when the orchestrator options enable fault tolerance (deadlines /
retries / quarantine) and ``max_recovery_rounds > 0``, an orchestration
pass that left failed moves or quarantined nodes re-enters the planner —
quarantined nodes become ``nodes_to_remove``, the reconstructed achieved
map (with dead-node placements presumed lost) becomes the current map —
and runs another bounded pass.  Each round's outcome lands in
``RebalanceResult.rounds``; the node health tracker carries across
rounds so a dead node stays dead.  With a ``PlannerSession`` supplied,
recovery replans warm-start off the session's solver carry whenever the
failures were confined to the dead nodes (the only rows that differ from
the adopted proposal are exactly the rows the removal marks dirty).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional

from .core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
    partition_map_from_json,
    partition_map_to_json,
)
from .obs import get_recorder
from .obs.slo import SloSummary, SloTracker
from .orchestrate.orchestrator import (
    FindMoveFunc,
    MoveFailure,
    OrchestratorOptions,
    OrchestratorProgress,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)
from .plan.api import plan_next_map
from .utils.trace import PhaseTimer

if TYPE_CHECKING:  # annotation-only
    from .plan.session import PlannerSession

__all__ = [
    "RebalanceResult",
    "RecoveryRound",
    "rebalance",
    "rebalance_async",
    "save_partition_map",
    "load_partition_map",
]


@dataclass
class RecoveryRound:
    """Outcome of one orchestration pass (round 0 = the primary pass)."""

    round: int
    dead_nodes: list[str]  # quarantined when the pass ENDED
    failures: int  # MoveFailures recorded during this pass
    progress_events: int
    progress: OrchestratorProgress


@dataclass
class RebalanceResult:
    """Everything a caller needs after a full rebalance."""

    next_map: PartitionMap
    warnings: dict[str, list[str]]
    progress: OrchestratorProgress
    progress_events: int
    timer: PhaseTimer = field(default_factory=PhaseTimer)
    # -- fault-tolerant mode extras (empty/None in legacy mode) --
    failures: list[MoveFailure] = field(default_factory=list)
    rounds: list[RecoveryRound] = field(default_factory=list)
    # The reconstructed map the cluster actually reached (== next_map on
    # a clean run); populated only when fault tolerance is on.
    achieved_map: Optional[PartitionMap] = None
    quarantined_nodes: list[str] = field(default_factory=list)
    # End-of-run SLO snapshot (obs/slo.py): availability, churn,
    # convergence lag, per-node quarantine exposure.  The live gauges
    # stream on the exposition endpoint during the run; this is the
    # final reading.
    slo: Optional[SloSummary] = None


def save_partition_map(pmap: PartitionMap, path: str) -> None:
    """Checkpoint a map as JSON, atomically.

    A crash mid-write must never leave a torn checkpoint: the JSON goes
    to a uniquely-named temp file IN THE SAME DIRECTORY (os.replace is
    only atomic within a filesystem), is fsync'd so the rename cannot be
    reordered before the data blocks, then os.replace'd into place.  A
    failure on any step removes the temp file and re-raises — the
    previous checkpoint survives untouched.
    """
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory)
    try:
        # mkstemp creates 0600; os.replace would carry that restrictive
        # mode onto the checkpoint and break unprivileged readers
        # (monitoring, backups).  Preserve the existing checkpoint's
        # mode, or umask-default for a fresh one.
        try:
            mode = os.stat(path).st_mode & 0o777
        except FileNotFoundError:
            umask = os.umask(0)
            os.umask(umask)
            mode = 0o666 & ~umask
        os.fchmod(fd, mode)
        with os.fdopen(fd, "w") as f:
            json.dump(partition_map_to_json(pmap), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_partition_map(path: str) -> PartitionMap:
    with open(path) as f:
        return partition_map_from_json(json.load(f))


def _session_matches(session: "PlannerSession", cur: PartitionMap) -> bool:
    """True when the session's adopted current state already IS ``cur``
    — then load_map (which invalidates the warm carry) can be skipped
    and a repeat rebalance through the same session warm-starts its
    primary plan off the carry the previous call promoted."""
    try:
        current, _warns = session.to_map("current")
    except ValueError:
        # to_map's documented failure (nothing adopted yet / unknown
        # which): no adopted state means no match.  Anything else is a
        # real bug and must surface, not silently force a cold replan.
        return False
    return current == cur


def _strip_nodes(pmap: PartitionMap, nodes: set[str]) -> PartitionMap:
    """Drop every placement on ``nodes`` — the recovery presumption that
    a quarantined node's data is lost, so no 'del' move is owed to it."""
    if not nodes:
        return pmap
    return {
        name: Partition(name, {
            s: [n for n in ns if n not in nodes]
            for s, ns in p.nodes_by_state.items()})
        for name, p in pmap.items()
    }


async def rebalance_async(
    model: PartitionModel,
    current_map: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    assign_partitions: Callable[..., object],
    *,
    plan_options: Optional[PlanOptions] = None,
    orchestrator_options: Optional[OrchestratorOptions] = None,
    find_move: Optional[FindMoveFunc] = None,
    backend: str = "auto",
    on_progress: Optional[Callable[[OrchestratorProgress], None]] = None,
    checkpoint_path: Optional[str] = None,
    max_recovery_rounds: int = 0,
    session=None,
    slo: Optional[SloTracker] = None,
) -> RebalanceResult:
    """Plan the next map and execute the transition against the callback.

    assign_partitions(stop_ch, node, partitions, states, ops) is the app's
    data plane (sync or async).  on_progress sees every progress snapshot.
    checkpoint_path, if set, saves each round's planned target map
    (atomically) before its orchestration begins; on a mid-orchestration
    crash, resume by re-running rebalance from the app's current map (the
    planner is idempotent at fixpoint, so the redo converges) or diff
    current vs the checkpointed target directly.

    max_recovery_rounds (requires fault-tolerant orchestrator options):
    after a pass that left MoveFailures or quarantined nodes, up to this
    many recovery passes replan with the quarantined nodes removed and
    the achieved map (dead placements stripped) as current.  session, a
    plan.session.PlannerSession covering the same partitions/nodes, makes
    the planning incremental: recovery replans warm-start off the solver
    carry when the failures were confined to the dead nodes.

    slo: an ``obs.slo.SloTracker`` to account availability/churn/lag
    against (pass your own when you also feed it to a ``MetricsServer``
    so the gauges stream live); one is created internally otherwise.
    Either way the tracker rides the orchestrator as a move observer,
    publishes ``slo.*`` gauges to the process recorder as the run
    progresses, and its final reading lands in ``RebalanceResult.slo``.
    """
    timer = PhaseTimer()
    rec = get_recorder()
    if slo is None:
        # "Serving" = the model's highest-priority (priority-0) states.
        top = min((st.priority for st in model.values()), default=0)
        slo = SloTracker(
            current_map,
            primary_states=[s for s, st in model.items()
                            if st.priority == top],
            clock=rec.now, recorder=rec)
    opts = orchestrator_options or OrchestratorOptions()
    ft = opts.fault_tolerant
    if max_recovery_rounds > 0 and not ft:
        raise ValueError(
            "max_recovery_rounds needs fault-tolerant orchestrator options "
            "(move_timeout_s / max_retries / quarantine_after): the legacy "
            "path aborts on the first error and records no failures to "
            "recover from")

    all_warnings: dict[str, list[str]] = {}

    def plan(cur: PartitionMap, removes: list[str], adds: list[str],
             warm_ok: bool, recovery: bool) -> PartitionMap:
        """One planner entry; merges warnings.  With a session: adopt
        ``cur`` unless the session's adopted state already matches
        (warm_ok — the recovery fast path), apply the delta, replan.
        Recovery rounds go through the session's dedicated entry
        (``recovery_replan``) so the failure-aware replan has exactly
        one spelling."""
        if session is None:
            next_map, warns = plan_next_map(
                cur, cur, nodes_all, removes, adds, model,
                plan_options, backend=backend)
        else:
            if not warm_ok and not _session_matches(session, cur):
                session.load_map(cur)  # cold: invalidates any carry
            if recovery:
                session.recovery_replan(removes)  # adds is always [] here
            else:
                if adds:
                    session.add_nodes(adds)
                if removes:
                    session.remove_nodes(removes)
                session.replan()
            next_map, warns = session.to_map("proposed")
        for k, v in warns.items():
            all_warnings.setdefault(k, []).extend(v)
        return next_map

    beg = current_map
    removes = list(nodes_to_remove or [])
    adds = list(nodes_to_add or [])
    rounds: list[RecoveryRound] = []
    all_failures: list[MoveFailure] = []
    events_total = 0
    health = opts.health
    warm_ok = False
    final: OrchestratorProgress = OrchestratorProgress()
    next_map: PartitionMap = beg
    achieved: Optional[PartitionMap] = None
    quarantined: list[str] = []

    for round_i in range(1 + max(max_recovery_rounds, 0)):
        phase = "plan" if round_i == 0 else f"recovery_plan_{round_i}"
        with timer.phase(phase):
            next_map = plan(beg, removes, adds, warm_ok,
                            recovery=round_i > 0)

        if checkpoint_path:
            with timer.phase("checkpoint"):
                save_partition_map(next_map, checkpoint_path)

        events = 0
        orch_phase = "orchestrate" if round_i == 0 \
            else f"recovery_orchestrate_{round_i}"
        with timer.phase(orch_phase):
            round_opts = opts
            if ft and health is not None:
                # Quarantine state carries across rounds: a node that
                # tripped in round k stays dark in round k+1 unless its
                # half-open probe heals it.
                round_opts = dataclasses.replace(opts, health=health)
            orch_nodes = [n for n in nodes_all if n not in quarantined]
            o = orchestrate_moves(
                model,
                round_opts,
                orch_nodes,
                beg,
                next_map,
                assign_partitions,
                find_move or lowest_weight_partition_move_for_node,
                move_observers=(slo,),
            )
            if round_i == 0:
                # The churn denominator: the PRIMARY plan's move count
                # is the minimum a perfect run would execute; recovery
                # rounds only ever add to the numerator.
                o.visit_next_moves(lambda m: slo.set_min_moves(
                    sum(len(nm.moves) for nm in m.values())))
            slo.attach_health(o.health)
            async for progress in o.progress_ch():
                events += 1
                final = progress
                if on_progress is not None:
                    on_progress(progress)
            o.stop()

        events_total += events
        round_failures = o.move_failures()
        all_failures.extend(round_failures)
        health = o.health
        quarantined = health.quarantined_nodes() if health is not None \
            else []
        rounds.append(RecoveryRound(
            round=round_i, dead_nodes=list(quarantined),
            failures=len(round_failures), progress_events=events,
            progress=final))
        if ft:
            achieved = _strip_nodes(o.achieved_map(), set(quarantined))
            # Mirror the presumption on the live SLO view: a quarantined
            # node's placements are lost, so availability drops NOW, not
            # after the recovery round re-places them.
            slo.strip_nodes(set(quarantined))

        if not ft or not round_failures:
            # Converged (or legacy mode, which never recovers): a
            # quarantined node with zero failures this round means the
            # plan already routed around it.  With a session, a clean
            # pass adopts the proposal so the next plan — this
            # rebalance's or a later one — warm-starts off the carry.
            if session is not None and not round_failures and \
                    not final.errors:
                session.apply()
            break
        if round_i >= max_recovery_rounds:
            break

        # -- set up the recovery round ------------------------------------
        rec.count("rebalance.recovery_rounds")
        if session is not None:
            # Warm fast path: failures confined to the dead nodes mean
            # the achieved state differs from the adopted proposal only
            # on rows that held a dead-node copy — exactly the rows
            # remove_nodes(dead) marks dirty, so the carry stays sound.
            confined = bool(quarantined) and all(
                f.node in set(quarantined) for f in round_failures)
            if confined:
                session.apply()
                warm_ok = True
            else:
                warm_ok = False
        beg = achieved
        # The original removal intent persists until drained: a node the
        # caller was decommissioning must not be re-adopted just because
        # a failed round left copies on it.  Quarantined nodes join it.
        removes = sorted(set(removes) | set(quarantined))
        adds = []

    slo.publish()
    return RebalanceResult(
        next_map=next_map,
        warnings=all_warnings,
        progress=final,
        progress_events=events_total,
        timer=timer,
        failures=all_failures,
        rounds=rounds,
        achieved_map=achieved,
        quarantined_nodes=list(quarantined),
        slo=slo.summary(),
    )


def rebalance(*args, **kwargs) -> RebalanceResult:
    """Synchronous wrapper around rebalance_async (runs its own loop)."""
    return asyncio.run(rebalance_async(*args, **kwargs))
