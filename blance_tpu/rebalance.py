"""App-level rebalance facade: plan -> diff -> orchestrate in one call.

The reference leaves this composition to the application (SURVEY.md §3.4:
plan or hand-build the end map, call OrchestrateMoves, drain ProgressCh,
Stop).  This module packages the canonical wiring, with the checkpoint
story built in: the PartitionMap IS the checkpoint (JSON-serializable by
design, reference api.go:30-35), so a crashed rebalance resumes by
re-planning from the current map and orchestrating the remaining diff —
the planner is pure and idempotent at fixpoint (plan_test.go:1888-1908).

Failure-aware recovery (docs/DESIGN.md "Failure semantics & recovery"):
when the orchestrator options enable fault tolerance (deadlines /
retries / quarantine) and ``max_recovery_rounds > 0``, an orchestration
pass that left failed moves or quarantined nodes re-enters the planner —
quarantined nodes become ``nodes_to_remove``, the reconstructed achieved
map (with dead-node placements presumed lost) becomes the current map —
and runs another bounded pass.  Each round's outcome lands in
``RebalanceResult.rounds``; the node health tracker carries across
rounds so a dead node stays dead.  With a ``PlannerSession`` supplied,
recovery replans warm-start off the session's solver carry whenever the
failures were confined to the dead nodes (the only rows that differ from
the adopted proposal are exactly the rows the removal marks dirty).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Iterable, Mapping, Optional

from .control import CycleEngine, CyclePlanner
from .core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
    copy_partition_map,
    partition_map_from_json,
    partition_map_to_json,
)
from .moves.calc import calc_partition_moves
from .obs import get_recorder
from .obs.slo import SloSummary, SloTracker
from .orchestrate.health import HealthTracker
from .orchestrate.orchestrator import (
    FindMoveFunc,
    MoveFailure,
    Orchestrator,
    OrchestratorOptions,
    OrchestratorProgress,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)
from .plan.api import plan_next_map
from .plan.greedy import sort_state_names
from .utils.atomicio import atomic_write_json
from .utils.trace import PhaseTimer

if TYPE_CHECKING:  # annotation-only
    from .durability.journal import JournalFeed
    from .plan.session import PlannerSession

__all__ = [
    "ClusterDelta",
    "DegradedPlacement",
    "RebalanceController",
    "RebalanceResult",
    "RecoveryRound",
    "count_moves",
    "rebalance",
    "rebalance_async",
    "save_partition_map",
    "load_partition_map",
]


@dataclass(frozen=True)
class ClusterDelta:
    """One cluster-membership / workload change fed to the control loop.

    ``add``: nodes joining (or returning — a previously failed node
    re-added starts with a clean breaker slate).  ``remove``: graceful
    decommissions — the data is still there, the next plans drain it
    off.  ``fail``: abrupt losses (spot preemption, zone outage) — the
    placements are presumed gone NOW, availability drops immediately
    and the controller re-places from the survivors.  Weight mappings
    are merged over the controller's running view (hot-tenant drift)."""

    add: tuple[str, ...] = ()
    remove: tuple[str, ...] = ()
    fail: tuple[str, ...] = ()
    partition_weights: Optional[Mapping[str, int]] = None
    node_weights: Optional[Mapping[str, int]] = None


@dataclass
class DegradedPlacement:
    """A structured graceful-degradation report — returned as DATA when
    capacity cannot hold the constraint set, instead of an exception or
    a silently partial map.

    ``reason`` is ``"no-candidate-nodes"`` (every node removed, failed
    or quarantined: current placements are kept as-is — or, on a
    recovery round whose achieved map was already stripped, the empty
    placement — rather than draining data to nowhere),
    ``"capacity-shed"`` (fewer candidates than constraint slots per
    partition: lower-priority replicas were shed first, primaries kept
    to the last node; ``shed`` maps state -> replicas dropped from its
    constraint), or ``"no-fixpoint"`` (the planner kept producing moves
    for the whole pass budget without failures — greedy balance under
    skewed weights can oscillate — so the cycle was cut off serving but
    not at the planner's preferred balance)."""

    reason: str
    nodes_available: int
    shed: dict[str, int] = field(default_factory=dict)
    partitions: int = 0


def count_moves(model: PartitionModel, beg_map: PartitionMap,
                end_map: PartitionMap,
                favor_min_nodes: bool = False) -> int:
    """Total orchestration moves the beg -> end transition needs (the
    per-partition move calculus the orchestrator itself runs).  Zero
    means beg IS end up to move semantics — the control loop's
    convergence check, and the simulator's offline-optimal churn
    denominator."""
    states = sort_state_names(model)
    return sum(
        len(calc_partition_moves(
            states, beg_map[name].nodes_by_state,
            end_map[name].nodes_by_state, favor_min_nodes))
        for name in beg_map)


@dataclass
class RecoveryRound:
    """Outcome of one orchestration pass (round 0 = the primary pass)."""

    round: int
    dead_nodes: list[str]  # quarantined when the pass ENDED
    failures: int  # MoveFailures recorded during this pass
    progress_events: int
    progress: OrchestratorProgress


@dataclass
class RebalanceResult:
    """Everything a caller needs after a full rebalance."""

    next_map: PartitionMap
    warnings: dict[str, list[str]]
    progress: OrchestratorProgress
    progress_events: int
    timer: PhaseTimer = field(default_factory=PhaseTimer)
    # -- fault-tolerant mode extras (empty/None in legacy mode) --
    failures: list[MoveFailure] = field(default_factory=list)
    rounds: list[RecoveryRound] = field(default_factory=list)
    # The reconstructed map the cluster actually reached (== next_map on
    # a clean run); populated only when fault tolerance is on.
    achieved_map: Optional[PartitionMap] = None
    quarantined_nodes: list[str] = field(default_factory=list)
    # End-of-run SLO snapshot (obs/slo.py): availability, churn,
    # convergence lag, per-node quarantine exposure.  The live gauges
    # stream on the exposition endpoint during the run; this is the
    # final reading.
    slo: Optional[SloSummary] = None
    # False when fault-tolerant recovery exhausted max_recovery_rounds
    # with failures still outstanding (or degraded below) — the
    # returned map is PARTIAL and must not read as success.
    # ``residual_failures`` summarizes what is still broken (node ->
    # outstanding MoveFailure count from the final round).  Legacy mode
    # has no recovery semantics and always reports True.
    converged: bool = True
    residual_failures: dict[str, int] = field(default_factory=dict)
    # Structured graceful degradation (e.g. a recovery replan with an
    # EMPTY candidate node set — every node quarantined); None on a
    # healthy run.
    degraded: Optional[DegradedPlacement] = None


def save_partition_map(pmap: PartitionMap, path: str) -> None:
    """Checkpoint a map as JSON, atomically and durably.

    One of the three users of the shared crash-atomic write recipe in
    :mod:`blance_tpu.utils.atomicio` (same-dir temp + file fsync +
    rename + DIRECTORY fsync); a crash mid-write leaves the previous
    checkpoint untouched, and a power failure after return cannot lose
    the rename.  The checkpoint's mode is preserved (umask default for
    a fresh file) so unprivileged readers keep working.
    """
    atomic_write_json(path, partition_map_to_json(pmap))


def load_partition_map(path: str) -> PartitionMap:
    with open(path) as f:
        return partition_map_from_json(json.load(f))


def _session_matches(session: "PlannerSession", cur: PartitionMap) -> bool:
    """True when the session's adopted current state already IS ``cur``
    — then load_map (which invalidates the warm carry) can be skipped
    and a repeat rebalance through the same session warm-starts its
    primary plan off the carry the previous call promoted."""
    try:
        current, _warns = session.to_map("current")
    except ValueError:
        # to_map's documented failure (nothing adopted yet / unknown
        # which): no adopted state means no match.  Anything else is a
        # real bug and must surface, not silently force a cold replan.
        return False
    return current == cur


def _strip_nodes(pmap: PartitionMap, nodes: set[str]) -> PartitionMap:
    """Drop every placement on ``nodes`` — the recovery presumption that
    a quarantined node's data is lost, so no 'del' move is owed to it."""
    if not nodes:
        return pmap
    return {
        name: Partition(name, {
            s: [n for n in ns if n not in nodes]
            for s, ns in p.nodes_by_state.items()})
        for name, p in pmap.items()
    }


async def rebalance_async(
    model: PartitionModel,
    current_map: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    assign_partitions: Callable[..., object],
    *,
    plan_options: Optional[PlanOptions] = None,
    orchestrator_options: Optional[OrchestratorOptions] = None,
    find_move: Optional[FindMoveFunc] = None,
    backend: str = "auto",
    on_progress: Optional[Callable[[OrchestratorProgress], None]] = None,
    checkpoint_path: Optional[str] = None,
    max_recovery_rounds: int = 0,
    session=None,
    slo: Optional[SloTracker] = None,
) -> RebalanceResult:
    """Plan the next map and execute the transition against the callback.

    assign_partitions(stop_ch, node, partitions, states, ops) is the app's
    data plane (sync or async).  on_progress sees every progress snapshot.
    checkpoint_path, if set, saves each round's planned target map
    (atomically) before its orchestration begins; on a mid-orchestration
    crash, resume by re-running rebalance from the app's current map (the
    planner is idempotent at fixpoint, so the redo converges) or diff
    current vs the checkpointed target directly.

    max_recovery_rounds (requires fault-tolerant orchestrator options):
    after a pass that left MoveFailures or quarantined nodes, up to this
    many recovery passes replan with the quarantined nodes removed and
    the achieved map (dead placements stripped) as current.  session, a
    plan.session.PlannerSession covering the same partitions/nodes, makes
    the planning incremental: recovery replans warm-start off the solver
    carry when the failures were confined to the dead nodes.

    slo: an ``obs.slo.SloTracker`` to account availability/churn/lag
    against (pass your own when you also feed it to a ``MetricsServer``
    so the gauges stream live); one is created internally otherwise.
    Either way the tracker rides the orchestrator as a move observer,
    publishes ``slo.*`` gauges to the process recorder as the run
    progresses, and its final reading lands in ``RebalanceResult.slo``.
    """
    timer = PhaseTimer()
    rec = get_recorder()
    if slo is None:
        # "Serving" = the model's highest-priority (priority-0) states.
        top = min((st.priority for st in model.values()), default=0)
        slo = SloTracker(
            current_map,
            primary_states=[s for s, st in model.items()
                            if st.priority == top],
            clock=rec.now, recorder=rec)
    # One rebalance call is one SLO incident: its time-to-converged
    # (slo.first_converged_lag_s — entry to the last required move) is
    # the makespan the critical-path scheduler minimizes; the rolling
    # convergence-lag gauge alone would under-report a long scheduled
    # tail (it resets on every executed move).
    slo.open_incident()
    try:
        opts = orchestrator_options or OrchestratorOptions()
        ft = opts.fault_tolerant
        if max_recovery_rounds > 0 and not ft:
            raise ValueError(
                "max_recovery_rounds needs fault-tolerant orchestrator options "
                "(move_timeout_s / max_retries / quarantine_after): the legacy "
                "path aborts on the first error and records no failures to "
                "recover from")

        all_warnings: dict[str, list[str]] = {}

        def plan(cur: PartitionMap, removes: list[str], adds: list[str],
                 warm_ok: bool, recovery: bool) -> PartitionMap:
            """One planner entry; merges warnings.  With a session: adopt
            ``cur`` unless the session's adopted state already matches
            (warm_ok — the recovery fast path), apply the delta, replan.
            Recovery rounds go through the session's dedicated entry
            (``recovery_replan``) so the failure-aware replan has exactly
            one spelling."""
            if session is None:
                next_map, warns = plan_next_map(
                    cur, cur, nodes_all, removes, adds, model,
                    plan_options, backend=backend)
            else:
                if not warm_ok and not _session_matches(session, cur):
                    session.load_map(cur)  # cold: invalidates any carry
                if recovery:
                    session.recovery_replan(removes)  # adds is always [] here
                else:
                    if adds:
                        session.add_nodes(adds)
                    if removes:
                        session.remove_nodes(removes)
                    session.replan()
                next_map, warns = session.to_map("proposed")
            for k, v in warns.items():
                all_warnings.setdefault(k, []).extend(v)
            return next_map

        beg = current_map
        removes = list(nodes_to_remove or [])
        adds = list(nodes_to_add or [])
        rounds: list[RecoveryRound] = []
        all_failures: list[MoveFailure] = []
        events_total = 0
        health = opts.health
        warm_ok = False
        final: OrchestratorProgress = OrchestratorProgress()
        next_map: PartitionMap = beg
        achieved: Optional[PartitionMap] = None
        quarantined: list[str] = []
        round_failures: list[MoveFailure] = []
        degraded: Optional[DegradedPlacement] = None

        for round_i in range(1 + max(max_recovery_rounds, 0)):
            if round_i > 0 and not [n for n in nodes_all if n not in removes]:
                # Every node is removed/quarantined: a recovery replan has
                # an EMPTY candidate set.  The achieved map was already
                # stripped of every dead placement, so the honest target is
                # the empty placement — surfaced as a structured
                # degradation, not a planner round that can place nothing
                # (and not a raise: the simulator's zone-outage scenarios
                # hit this in normal operation).
                degraded = DegradedPlacement(
                    reason="no-candidate-nodes", nodes_available=0,
                    partitions=len(beg))
                rec.count("rebalance.degraded")
                next_map = {name: Partition(name, {s: [] for s in model})
                            for name in beg}
                break
            phase = "plan" if round_i == 0 else f"recovery_plan_{round_i}"
            with timer.phase(phase):
                next_map = plan(beg, removes, adds, warm_ok,
                                recovery=round_i > 0)

            if checkpoint_path:
                with timer.phase("checkpoint"):
                    save_partition_map(next_map, checkpoint_path)

            events = 0
            orch_phase = "orchestrate" if round_i == 0 \
                else f"recovery_orchestrate_{round_i}"
            with timer.phase(orch_phase):
                round_opts = opts
                if ft and health is not None:
                    # Quarantine state carries across rounds: a node that
                    # tripped in round k stays dark in round k+1 unless its
                    # half-open probe heals it.
                    round_opts = dataclasses.replace(opts, health=health)
                orch_nodes = [n for n in nodes_all if n not in quarantined]
                o = orchestrate_moves(
                    model,
                    round_opts,
                    orch_nodes,
                    beg,
                    next_map,
                    assign_partitions,
                    find_move or lowest_weight_partition_move_for_node,
                    move_observers=(slo,),
                )
                if round_i == 0:
                    # The churn denominator: the PRIMARY plan's move count
                    # is the minimum a perfect run would execute; recovery
                    # rounds only ever add to the numerator.
                    o.visit_next_moves(lambda m: slo.set_min_moves(
                        sum(len(nm.moves) for nm in m.values())))
                slo.attach_health(o.health)
                async for progress in o.progress_ch():
                    events += 1
                    final = progress
                    if on_progress is not None:
                        on_progress(progress)
                o.stop()

            events_total += events
            round_failures = o.move_failures()
            all_failures.extend(round_failures)
            health = o.health
            quarantined = health.quarantined_nodes() if health is not None \
                else []
            rounds.append(RecoveryRound(
                round=round_i, dead_nodes=list(quarantined),
                failures=len(round_failures), progress_events=events,
                progress=final))
            if ft:
                achieved = _strip_nodes(o.achieved_map(), set(quarantined))
                # Mirror the presumption on the live SLO view: a quarantined
                # node's placements are lost, so availability drops NOW, not
                # after the recovery round re-places them.
                slo.strip_nodes(set(quarantined))

            if not ft or not round_failures:
                # Converged (or legacy mode, which never recovers): a
                # quarantined node with zero failures this round means the
                # plan already routed around it.  With a session, a clean
                # pass adopts the proposal so the next plan — this
                # rebalance's or a later one — warm-starts off the carry.
                if session is not None and not round_failures and \
                        not final.errors:
                    session.apply()
                break
            if round_i >= max_recovery_rounds:
                break

            # -- set up the recovery round ------------------------------------
            rec.count("rebalance.recovery_rounds")
            if session is not None:
                # Warm fast path: failures confined to the dead nodes mean
                # the achieved state differs from the adopted proposal only
                # on rows that held a dead-node copy — exactly the rows
                # remove_nodes(dead) marks dirty, so the carry stays sound.
                confined = bool(quarantined) and all(
                    f.node in set(quarantined) for f in round_failures)
                if confined:
                    session.apply()
                    warm_ok = True
                else:
                    warm_ok = False
            beg = achieved
            # The original removal intent persists until drained: a node the
            # caller was decommissioning must not be re-adopted just because
            # a failed round left copies on it.  Quarantined nodes join it.
            removes = sorted(set(removes) | set(quarantined))
            adds = []

        # Recovery exhaustion is DATA, not silence: a run that still has
        # failures outstanding after its last round (or that degraded to an
        # empty placement) is not converged, and the residual summary says
        # what is still broken — a partial map must never be
        # indistinguishable from success.
        residual: dict[str, int] = {}
        converged = True
        if ft and (round_failures or degraded is not None):
            converged = False
            for f in round_failures:
                residual[f.node] = residual.get(f.node, 0) + 1
            rec.count("rebalance.unconverged")

        slo.close_incident()
        slo.publish()
        return RebalanceResult(
            next_map=next_map,
            warnings=all_warnings,
            progress=final,
            progress_events=events_total,
            timer=timer,
            failures=all_failures,
            rounds=rounds,
            achieved_map=achieved,
            quarantined_nodes=list(quarantined),
            slo=slo.summary(),
            converged=converged,
            residual_failures=residual,
            degraded=degraded,
        )
    except BaseException:
        # A raise out of the episode is not an incident with a
        # makespan: a reused tracker must not carry a stale open
        # incident into its next rebalance call.
        slo.discard_incident()
        raise


def rebalance(*args, **kwargs) -> RebalanceResult:
    """Synchronous wrapper around rebalance_async (runs its own loop)."""
    return asyncio.run(rebalance_async(*args, **kwargs))


def _maps_equal(a: PartitionMap, b: PartitionMap) -> bool:
    """Placement equality up to empty state lists (an emptied state vs
    a never-present one).  In-list ORDER is kept — index 0 is "the
    primary" by contract."""
    def norm(m: PartitionMap) -> dict:
        return {name: {s: list(ns) for s, ns in p.nodes_by_state.items()
                       if ns}
                for name, p in m.items()}
    return norm(a) == norm(b)


class RebalanceController(CycleEngine):
    """The continuous-rebalance control loop (ROADMAP item 4).

    ``rebalance_async`` is one bounded episode; production is a loop:
    cluster deltas (:class:`ClusterDelta`) arrive at any time, and the
    controller keeps the cluster converging while it serves —

    - **debounce**: deltas arriving within ``debounce_s`` of each other
      coalesce into one planning cycle (a zone outage is dozens of node
      events, not dozens of rebalances);
    - **supersede**: a delta landing mid-rebalance CANCELS the in-flight
      transition (``Orchestrator.cancel``), waits for the wind-down, and
      resumes from ``achieved_map()`` — never from a stale plan;
    - **warm carry**: with a :class:`~blance_tpu.plan.session.
      PlannerSession`, clean cycles ride the solver carry across plans
      (load/adopt gated exactly like ``rebalance_async``);
    - **graceful degradation**: when the candidate set cannot hold the
      constraint set, lower-priority replicas are shed before primaries
      and a structured :class:`DegradedPlacement` lands in
      ``degraded_reports`` instead of an exception; an EMPTY candidate
      set keeps the current placements (never drains data to nowhere);
    - **convergence accounting**: each cycle replans until the move
      calculus reports zero moves; a cycle that exhausts
      ``max_passes_per_cycle`` with failures outstanding counts
      ``rebalance.unconverged`` and leaves the residue for the next
      delta.

    The generic debounce/coalesce/converge machinery is the extracted
    :class:`~blance_tpu.control.CycleEngine` (the fleet tier runs one
    engine per tenant on a single event loop, docs/FLEET.md); this
    class supplies the cluster-specific half: planning, orchestration,
    supersede, health and SLO accounting.  A
    :class:`~blance_tpu.control.CyclePlanner` (``planner=``) replaces
    the inline planning step with an AWAITED one — the seam that lets N
    controllers coalesce their converge cycles through one shared
    ``plan.service.PlanService`` fleet dispatch.  The planner path
    bypasses the session (mutually exclusive) and is itself bypassed by
    graceful degradation (capacity shed / empty candidate set), which
    stays on the local planner exactly like the session path.

    Single-task discipline (analysis/race_lint.py ``SHARED_STATE``):
    every mutation of the shared control state happens in a sync
    window, either on the app-facing surface (``submit``/``stop_soon``)
    or inside the controller task — the bounded rendezvous between them
    is the wake event plus the pending-delta list, taken atomically.

    Time comes exclusively from the recorder's clock, so the whole loop
    — debounce windows included — runs deterministically under
    ``testing.sched.DeterministicLoop`` (the ``testing/simulate`` tier
    replays a week of cluster life in seconds, bit-identically).
    """

    TASK_NAME = "rebalance-controller"

    def __init__(
        self,
        model: PartitionModel,
        nodes_all: list[str],
        current_map: PartitionMap,
        assign_partitions: Callable[..., object],
        *,
        plan_options: Optional[PlanOptions] = None,
        orchestrator_options: Optional[OrchestratorOptions] = None,
        backend: str = "greedy",
        session: "Optional[PlannerSession]" = None,
        planner: Optional[CyclePlanner] = None,
        find_move: Optional[FindMoveFunc] = None,
        debounce_s: float = 0.05,
        max_passes_per_cycle: int = 8,
        slo: Optional[SloTracker] = None,
        move_observers: tuple = (),
        journal: "Optional[JournalFeed]" = None,
    ) -> None:
        if session is not None and planner is not None:
            raise ValueError(
                "session and planner are mutually exclusive: the async "
                "planner path owns its own warm-carry lifecycle (the "
                "plan service's CarryCache), so a session's carry would "
                "never be consulted")
        self.model = model
        self._assign = assign_partitions
        self._find_move = find_move
        self._planner = planner
        # Private copy: the controller folds weight deltas into its
        # options view, and mutating a caller-shared PlanOptions would
        # leak this loop's weights into unrelated plans.
        self.opts = dataclasses.replace(plan_options) \
            if plan_options is not None else PlanOptions()
        self.orch_opts = orchestrator_options or OrchestratorOptions()
        self.backend = backend
        self.session = session
        self.max_passes_per_cycle = max(int(max_passes_per_cycle), 1)
        self._rec = get_recorder()
        super().__init__(debounce_s=debounce_s, clock=self._rec.now)
        self.current: PartitionMap = copy_partition_map(current_map)
        self._nodes: list[str] = list(nodes_all)
        self._removing: set[str] = set()  # graceful decommissions
        self._failed: set[str] = set()  # abrupt losses (stripped)
        self._pweights: dict[str, int] = dict(
            self.opts.partition_weights or {})
        self._nweights: dict[str, int] = dict(self.opts.node_weights or {})
        self._slo = slo
        self._observers = ((slo,) if slo is not None else ()) + \
            tuple(move_observers)
        # One breaker for the WHOLE loop: quarantine survives cycles
        # (a dead node stays dark) until an explicit re-add forgets it.
        if self.orch_opts.health is not None:
            self.health: Optional[HealthTracker] = self.orch_opts.health
        elif self.orch_opts.quarantine_after > 0:
            self.health = HealthTracker(
                threshold=self.orch_opts.quarantine_after,
                probe_after_s=self.orch_opts.probe_after_s,
                clock=self._rec.now)
        else:
            self.health = None
        if self._slo is not None and self.health is not None:
            self._slo.attach_health(self.health)

        self._inflight: Optional[Orchestrator] = None
        # Introspection / scoring surface:
        self.warnings: dict[str, list[str]] = {}
        self.failures: list[MoveFailure] = []
        self.degraded_reports: list[DegradedPlacement] = []
        self.passes = 0
        self.superseded = 0
        self.unconverged_cycles = 0
        # Called with (nodes, t) whenever placements are stripped (an
        # abrupt fail delta, or quarantined placements presumed lost) —
        # the simulator's event log needs every strip to make the SLO
        # account recomputable from the log alone.
        self.on_strip: list[Callable[[set[str], float], None]] = []
        # Durability feed (durability/journal.py, docs/DURABILITY.md):
        # every sync window writes a WAL record — delta intake
        # (_on_submit), cycle begin (_on_cycle), plan landed
        # (_converge), executed-batch achieved-map delta (the journal
        # rides _observers as a MoveObserver), strips, and quiesce
        # (plus a periodic snapshot at that idle edge).  The genesis
        # record below makes recovery self-contained before the first
        # snapshot.
        self._journal = journal
        if journal is not None:
            journal.record_genesis(
                self.current, self._nodes, self._removing, self._failed,
                self._pweights, self._nweights, t=self._rec.now())
            self._observers = self._observers + (journal,)
            self.on_strip.append(
                lambda nodes, t: journal.record_strip(sorted(nodes), t=t))

    # -- CycleEngine hooks (sync: single atomic windows) -------------------

    def _on_submit(self, delta: ClusterDelta) -> None:
        self._rec.count("sim.deltas")
        if self._slo is not None:
            # One busy episode = one SLO incident (first submit wins;
            # the next quiesce closes it with the time-to-last-required
            # -move sample, slo.first_converged_lag_s).
            self._slo.open_incident(self._rec.now())
        if self._journal is not None:
            self._journal.record_delta(delta, t=self._rec.now())

    def _on_cycle(self, n: int, deltas: int) -> None:
        if self._journal is not None:
            self._journal.record_cycle(n, deltas, t=self._rec.now())

    def _on_stop_soon(self) -> None:
        # Wind-down cancels any in-flight transition.
        o = self._inflight
        if o is not None:
            o.cancel()

    def _on_idle(self, t: float) -> None:
        if self._slo is not None:
            self._slo.close_incident(t)
        if self._journal is not None:
            # Quiesce record (map digest: the cheap divergence probe),
            # then maybe a snapshot — written at the idle edge so a
            # snapshot never captures a mid-cycle map.
            self._journal.record_quiesce_map(self.current, t=t)
            if self._journal.should_snapshot():
                self._journal.write_snapshot(self.snapshot_payload(t), t=t)

    def _on_exit(self) -> None:
        if self._slo is not None and not self._idle.is_set():
            # A crash / mid-episode stop is not a quiesce: the open
            # incident dies unrecorded (same discard-on-raise rule as
            # rebalance_async) instead of closing as an "instantly
            # converged" 0.0 lag sample.
            self._slo.discard_incident()

    async def quiesce(self) -> PartitionMap:
        """Wait until the controller is idle (every submitted delta
        planned, orchestrated and converged — or structurally degraded)
        and return the current map."""
        await self._idle.wait()
        return self.current

    def quarantined_nodes(self) -> list[str]:
        return self.health.quarantined_nodes() \
            if self.health is not None else []

    def snapshot_payload(self, t: float) -> dict:
        """The controller's durable state for one snapshot
        (durability/recover.py SNAPSHOT_FORMAT_VERSION): map +
        membership view + weights, HealthTracker state (open exposure
        intervals included), SloTracker horizon state, and the
        scheduler's CostModel aggregates when one is wired.  Carry /
        encode caches are deliberately absent — recovery demotes them
        to counted cold solves (docs/DURABILITY.md)."""
        cost = getattr(self.orch_opts.scheduler, "cost_model", None)
        return {
            "version": 1,
            "map": {name: p.to_json()
                    for name, p in sorted(self.current.items())},
            "nodes": list(self._nodes),
            "removing": sorted(self._removing),
            "failed": sorted(self._failed),
            "pweights": dict(sorted(self._pweights.items())),
            "nweights": dict(sorted(self._nweights.items())),
            "health": (self.health.to_dict(t)
                       if self.health is not None else None),
            "slo": (self._slo.to_dict(t)
                    if self._slo is not None else None),
            "cost": cost.to_json() if cost is not None else None,
        }

    def live_nodes(self) -> list[str]:
        """Nodes currently eligible as placement candidates (known,
        not decommissioning, not failed, not quarantined), in tie-break
        order — the simulator's offline-optimal baseline node set."""
        return self._candidates()

    def pending_tasks(self) -> "list[asyncio.Task[object]]":
        """Unfinished orchestration/controller tasks — the no-orphan
        probe for the supersede explorer scenario."""
        out: "list[asyncio.Task[object]]" = []
        if self._task is not None and not self._task.done():
            out.append(self._task)
        o = self._inflight
        if o is not None:
            out.extend(o.pending_tasks())
        return out

    def _apply_deltas(self, deltas: Iterable[ClusterDelta]) -> None:
        """Fold deltas into the membership/weight view, IN ORDER (a
        fail followed by a re-add in one burst comes back clean).  One
        sync window: placements strip atomically with the view."""
        weights_changed = False
        for delta in deltas:
            for n in delta.add:
                if n not in self._nodes:
                    self._nodes.append(n)
                self._removing.discard(n)
                if n in self._failed:
                    self._failed.discard(n)
                if self.health is not None:
                    self.health.forget(n)
            self._removing.update(
                n for n in delta.remove if n in self._nodes)
            fresh = [n for n in delta.fail
                     if n in self._nodes and n not in self._failed]
            if fresh:
                self._failed.update(fresh)
                before = self.current
                self.current = _strip_nodes(self.current, set(fresh))
                t = self._rec.now()
                if self._slo is not None:
                    self._slo.strip_nodes(set(fresh), t)
                for hook in self.on_strip:
                    hook(set(fresh), t)
                # Encode residency (docs/DESIGN.md): an async planner
                # holding resident encode state patches its prev at
                # the holder rows instead of re-encoding the stripped
                # map next cycle.
                notify = getattr(self._planner, "notify_strip", None)
                if notify is not None:
                    notify(set(fresh), before, self.current)
            if delta.partition_weights:
                self._pweights.update(delta.partition_weights)
                weights_changed = True
            if delta.node_weights:
                self._nweights.update(delta.node_weights)
                weights_changed = True
        self.opts.partition_weights = dict(self._pweights) or None
        self.opts.node_weights = dict(self._nweights) or None
        if self.session is not None:
            self._mirror_session(weights_changed)

    def _mirror_session(self, weights_changed: bool) -> None:
        """Push the folded membership/weight view into the session.
        Weight updates invalidate the carry (they re-price everything)
        so they are mirrored only when this burst actually changed
        them; membership changes keep the carry warm via the session's
        own dirty masks.

        The dark set mirrored as removed includes QUARANTINED nodes —
        the session must never plan onto a node whose mover is
        excluded, or the pass wedges on a moverless target — and a
        node the session still counts removed but the controller
        considers eligible again (a failed node re-added, a healed
        breaker) is re-added, clearing the session's removal flag:
        returned capacity must not stay dark."""
        session = self.session
        assert session is not None
        dark = self._removing | self._failed | set(self.quarantined_nodes())
        known = set(session.nodes)
        back = [n for n in self._nodes
                if n not in known
                or (n in set(session.removed_nodes) and n not in dark)]
        if back:
            session.add_nodes(back)
        gone = sorted(dark - set(session.removed_nodes))
        if gone:
            session.remove_nodes(gone)
        if weights_changed:
            if self._pweights:
                session.set_partition_weights(dict(self._pweights))
            if self._nweights:
                session.set_node_weights(dict(self._nweights))

    def _candidates(self) -> list[str]:
        dark = self._removing | self._failed | set(self.quarantined_nodes())
        return [n for n in self._nodes if n not in dark]

    def _mover_nodes(self) -> list[str]:
        """Nodes that get movers this pass: failed and quarantined
        nodes are gone (their queued work must drain as failures, and
        feeding them would burn the retry budget); GRACEFUL removals
        keep movers — their 'del' moves are real work."""
        dark = self._failed | set(self.quarantined_nodes())
        return [n for n in self._nodes if n not in dark]

    # -- planning with graceful degradation --------------------------------

    def _effective_constraints(self) -> dict[str, int]:
        out = {s: st.constraints for s, st in self.model.items()}
        for s, c in (self.opts.model_state_constraints or {}).items():
            if s in out:
                out[s] = c
        return out

    def _shed_plan(self, n_candidates: int) \
            -> tuple[Optional[dict[str, int]], dict[str, int]]:
        """(degraded constraints, shed per state) when the candidate
        set cannot hold the full constraint set; (None, {}) when no
        shedding is needed.  Lowest-priority states shed first; the
        top-priority state keeps at least one copy."""
        eff = self._effective_constraints()
        total = sum(eff.values())
        if total <= n_candidates:
            return None, {}
        top = min((st.priority for st in self.model.values()), default=0)
        shed: dict[str, int] = {}
        # Highest priority VALUE (least important) first; name-sorted
        # within a tier for determinism.
        for s in sorted(eff, key=lambda s: (-self.model[s].priority, s)):
            floor = 1 if self.model[s].priority == top else 0
            while total > n_candidates and eff[s] > floor:
                eff[s] -= 1
                shed[s] = shed.get(s, 0) + 1
                total -= 1
        return eff, shed

    def _plan(self, candidates: list[str]) \
            -> tuple[Optional[PartitionMap], Optional[DegradedPlacement]]:
        """One planning step.  (None, report) when there is nothing a
        plan could place (empty candidate set: keep current placements
        rather than draining data to nowhere)."""
        if not candidates:
            return None, DegradedPlacement(
                reason="no-candidate-nodes", nodes_available=0,
                partitions=len(self.current))
        removes = sorted(self._removing | self._failed |
                         set(self.quarantined_nodes()))
        degraded_constraints, shed = self._shed_plan(len(candidates))
        report = None
        if degraded_constraints is not None:
            report = DegradedPlacement(
                reason="capacity-shed", nodes_available=len(candidates),
                shed=shed, partitions=len(self.current))
        if self.session is not None and report is None:
            next_map, warns = self._plan_session()
        else:
            opts = self.opts
            if degraded_constraints is not None:
                # Shedding bypasses the session: the session's encoded
                # statics pin the full constraint set.
                opts = dataclasses.replace(
                    self.opts,
                    model_state_constraints=degraded_constraints)
            next_map, warns = plan_next_map(
                self.current, self.current, list(self._nodes), removes,
                [], self.model, opts, backend=self.backend)
        for k, v in warns.items():
            self.warnings.setdefault(k, []).extend(v)
        return next_map, report

    def _plan_session(self) -> tuple[PartitionMap, dict[str, list[str]]]:
        session = self.session
        assert session is not None
        if not _session_matches(session, self.current):
            session.load_map(self.current)  # cold: invalidates the carry
        # Re-push membership before EVERY session plan (weights stay:
        # the session's own opts already carry them, and re-encodes
        # read them back in): the breaker can quarantine a node
        # between passes, and a plan that still targets it would wedge
        # on a moverless mover.
        self._mirror_session(weights_changed=False)
        session.replan()
        return session.to_map("proposed")

    async def _plan_cycle(self, candidates: list[str]) \
            -> tuple[Optional[PartitionMap], Optional[DegradedPlacement]]:
        """One planning step, through the async ``planner`` seam when
        one is wired and the cycle is healthy.  Graceful degradation
        (empty candidate set, capacity shed) bypasses the planner onto
        the local path, exactly like it bypasses a session — the
        planner's encoded statics pin the full constraint set."""
        if self._planner is not None and candidates and \
                self._shed_plan(len(candidates))[0] is None:
            removes = sorted(self._removing | self._failed |
                             set(self.quarantined_nodes()))
            next_map, warns = await self._planner.plan_cycle(
                self.current, list(self._nodes), removes, self.model,
                self.opts)
            for k, v in warns.items():
                self.warnings.setdefault(k, []).extend(v)
            return next_map, None
        return self._plan(candidates)

    # -- one converge cycle -------------------------------------------------

    async def _converge(self) -> None:
        """Plan/orchestrate until the move calculus reports zero moves,
        a new delta supersedes the cycle, or the pass budget runs out."""
        passes = 0
        while not self._stopping:
            next_map, report = await self._plan_cycle(self._candidates())
            if report is not None:
                self.degraded_reports.append(report)
                self._rec.count("sim.degraded_plans")
            if next_map is None:
                break
            n_moves = count_moves(self.model, self.current, next_map,
                                  self.orch_opts.favor_min_nodes)
            if n_moves == 0:
                if self.session is not None and \
                        _maps_equal(self.current, next_map):
                    # Fixpoint reached with the proposal == current:
                    # adopt it so the NEXT cycle warm-starts.
                    self.session.apply()
                break
            passes += 1
            self.passes += 1
            self._rec.count("sim.rebalances")
            if self._journal is not None:
                self._journal.record_plan(passes, n_moves,
                                          t=self._rec.now())
            superseded, failures = await self._one_pass(next_map)
            if superseded:
                return
            if passes >= self.max_passes_per_cycle:
                # The pass budget is a HARD bound, failures or not: a
                # planner that keeps reshuffling (greedy balance under
                # skewed weights has states with no fixpoint — plans
                # oscillate) must not spin the control loop forever.
                # The cycle ends unconverged, structurally: the map is
                # serving (every executed pass was complete
                # make-before-break work), the residue waits for the
                # next delta.
                self.unconverged_cycles += 1
                self._rec.count("rebalance.unconverged")
                if not failures:
                    self.degraded_reports.append(DegradedPlacement(
                        reason="no-fixpoint",
                        nodes_available=len(self._candidates()),
                        partitions=len(self.current)))
                    self._rec.count("sim.degraded_plans")
                break

    async def _one_pass(self, next_map: PartitionMap) \
            -> tuple[bool, list[MoveFailure]]:
        """One orchestration pass toward ``next_map``; True when a new
        delta superseded it mid-flight (resume happens in the outer
        loop, from the achieved map adopted here either way)."""
        opts = self.orch_opts
        if self.health is not None:
            opts = dataclasses.replace(opts, health=self.health)
        if self._journal is not None and opts.epoch_fence is None:
            # Every dispatched move is stamped with the journal dir's
            # fenced epoch: a completion arriving after a recovery
            # bumped the fence is rejected and counted, never applied
            # (durability.stale_epoch_rejections).
            opts = dataclasses.replace(opts,
                                       epoch_fence=self._journal.fence)
        o = orchestrate_moves(
            self.model, opts, self._mover_nodes(), self.current, next_map,
            self._assign, self._find_move, move_observers=self._observers)
        self._inflight = o
        drain = asyncio.ensure_future(self._drain_progress(o))
        superseded = False
        while not drain.done():
            waiter = asyncio.ensure_future(self._wake_wait())
            await asyncio.wait({drain, waiter},
                               return_when=asyncio.FIRST_COMPLETED)
            if not waiter.done():
                waiter.cancel()
                try:
                    await waiter
                except asyncio.CancelledError:
                    pass
            if drain.done():
                break
            if self._pending and not self._stopping:
                # Supersede: the plan in flight no longer matches the
                # cluster.  Cancel, wait the full wind-down (no orphan
                # tasks), resume from the achieved map.
                superseded = True
                self.superseded += 1
                self._rec.count("sim.superseded")
            o.cancel()
            await o.wait_drained()
            break
        await drain
        self._adopt(o, superseded=superseded)
        return superseded, o.move_failures()

    async def _drain_progress(self, o: Orchestrator) -> None:
        async for _progress in o.progress_ch():
            pass
        o.stop()

    def _adopt(self, o: Orchestrator, superseded: bool = False) -> None:
        """Fold one finished pass into the controller view (sync: one
        atomic window).  Quarantined placements are presumed lost, like
        rebalance_async's recovery presumption."""
        quarantined = set(o.health.quarantined_nodes()) \
            if o.health is not None else set()
        achieved = o.achieved_map()
        if quarantined:
            achieved = _strip_nodes(achieved, quarantined)
            t = self._rec.now()
            if self._slo is not None:
                self._slo.strip_nodes(quarantined, t)
            for hook in self.on_strip:
                hook(set(quarantined), t)
        failures = o.move_failures()
        self.failures.extend(failures)
        self.current = achieved
        self._inflight = None
        notify = getattr(self._planner, "notify_pass", None)
        if notify is not None:
            # Encode residency: a clean-hinted pass (fully drained, no
            # cancel/supersede/failures/quarantine/errors) lets the
            # planner adopt its proposal's packed assignment as the
            # next resident prev; the planner itself still verifies the
            # changed rows landed verbatim, and anything off-hint
            # demotes to a cold re-encode.
            clean = (not superseded and not self._stopping
                     and not failures and not quarantined
                     and o._progress.tot_cancel == 0
                     and not o._progress.errors)
            notify(achieved, o.end_map, clean)
        if self.session is not None and not failures and \
                not quarantined and \
                _maps_equal(self.current, o.end_map):
            # Clean pass: the proposal landed verbatim — adopt it so
            # the next plan rides the warm carry.
            self.session.apply()
