"""Public planning entry point with backend selection.

``plan_next_map`` is the equivalent of the reference's PlanNextMapEx
(reference: /root/reference/api.go:147-157).  Backends:

- "greedy": the exact sequential planner (semantics oracle; plan/greedy.py).
- "native": the same exact algorithm with the hot loop in C++ (plan/native.py
            + native/planner.cpp) — bit-identical results, ~100x throughput;
            falls back to "greedy" when unsupported hooks are in play.
- "tpu":    the batched cost-tensor planner (plan/tensor.py) — whole-problem
            scoring on device, constraint repair, sharded over partitions.
- "auto":   "tpu" for large problems, "native" (or "greedy") otherwise.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..core.types import (
    HierarchyRules,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)
from ..obs import get_recorder
from .greedy import plan_next_map_greedy

if TYPE_CHECKING:  # annotation-only
    from ..utils.trace import PhaseTimer

__all__ = ["plan_next_map", "plan_next_map_legacy"]

# Below this many (partitions x nodes), the exact greedy is faster than a
# device round-trip; above it, the batched solver wins.  The library
# default for backend="auto"; override per deployment with
# PlanOptions.auto_tpu_threshold (the calibration behind this constant is
# one host class — crossovers move with interconnect and host CPU).
_AUTO_TPU_THRESHOLD = 256 * 1024


def plan_next_map(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]] = None,
    nodes_to_add: Optional[list[str]] = None,
    model: Optional[PartitionModel] = None,
    opts: Optional[PlanOptions] = None,
    backend: str = "greedy",
    timer: Optional["PhaseTimer"] = None,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """Compute the next balanced partition map.

    Returns (next_map, warnings) where warnings is keyed by partition name
    (constraint shortfalls degrade to warnings, never errors — reference
    plan.go:231-235).  ``timer`` (utils.trace.PhaseTimer) attributes
    wall-clock to encode / solve / decode on the tpu backend.
    """
    if model is None:
        raise ValueError("model is required")
    opts = opts or PlanOptions()

    requested = backend
    if backend == "auto":
        size = len(partitions_to_assign) * len(nodes_all)
        threshold = (_AUTO_TPU_THRESHOLD
                     if opts.auto_tpu_threshold is None
                     else int(opts.auto_tpu_threshold))
        backend = "tpu" if size >= threshold else "native"

    with get_recorder().span(
            "plan.plan_next_map", backend=backend, requested=requested,
            partitions=len(partitions_to_assign), nodes=len(nodes_all)):
        if backend == "greedy":
            return plan_next_map_greedy(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, nodes_to_add, model, opts)
        if backend == "native":
            from .native import plan_next_map_native  # deferred: may compile

            return plan_next_map_native(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, nodes_to_add, model, opts)
        if backend == "tpu":
            if opts.fused_pipeline:
                # Fused fast path: one jitted encode→solve→diff→pack
                # dispatch (plan/tensor.plan_pipeline); the map is
                # bit-identical to the staged path's.  The on-device
                # move diff rides along — callers that want it call
                # plan_pipeline directly (this signature returns only
                # (map, warnings)).
                from .tensor import plan_pipeline  # deferred: imports jax

                next_map, warnings, _ = plan_pipeline(
                    prev_map, partitions_to_assign, nodes_all,
                    nodes_to_remove, nodes_to_add, model, opts,
                    timer=timer, want_moves=False)
                return next_map, warnings
            from .tensor import plan_next_map_tpu  # deferred: imports jax

            return plan_next_map_tpu(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, nodes_to_add, model, opts, timer=timer)
        raise ValueError(f"unknown backend: {backend!r}")


def plan_next_map_legacy(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    model_state_constraints: Optional[dict[str, int]] = None,
    partition_weights: Optional[dict[str, int]] = None,
    state_stickiness: Optional[dict[str, int]] = None,
    node_weights: Optional[dict[str, int]] = None,
    node_hierarchy: Optional[dict[str, str]] = None,
    hierarchy_rules: Optional["HierarchyRules"] = None,
    backend: str = "greedy",
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """Positional-options compatibility shim mirroring the reference's
    deprecated PlanNextMap signature (api.go:109-132); prefer plan_next_map
    with PlanOptions."""
    return plan_next_map(
        prev_map, partitions_to_assign, nodes_all,
        nodes_to_remove, nodes_to_add, model,
        PlanOptions(
            model_state_constraints=model_state_constraints,
            partition_weights=partition_weights,
            state_stickiness=state_stickiness,
            node_weights=node_weights,
            node_hierarchy=node_hierarchy,
            hierarchy_rules=hierarchy_rules,
        ),
        backend=backend,
    )
