"""Encode residency: the tenant's encoded problem as delta-patched state.

PR 13 made the fleet's *solves* one coalesced dispatch and the solver
state resident (``SolveCarry``/``CarryCache``), but every converge cycle
still re-ran the whole host round trip: ``encode_problem`` from the
``PartitionMap`` (string interning + the full ``[P, S, R]`` prev
scatter + the Python weight/stickiness/hierarchy loops), a fresh
``TenantProblem``, and a full ``decode_assignment`` back to a brand-new
map — O(cluster) host work per cycle even when the delta was one dark
node.  Following GSPMD's one-program-many-shapes discipline
(arXiv:2105.04663) and the on-device mapping thesis of GPU-accelerated
process mapping (arXiv:2510.12196), this module makes the ENCODED
problem resident too:

- :class:`EncodedState` holds one tenant's interned id tables
  (node/partition indexes, per-level hierarchy group-id interns), the
  live ``DenseProblem`` arrays, the per-row fill ``counts`` and the
  held decoded map — everything a cycle used to rebuild from strings.
- Delta-apply kernels patch it in O(delta): an abrupt-fail strip
  removes the dark nodes' placements from exactly the holder rows
  (``core.encode.strip_prev_rows`` — the array twin of re-encoding the
  stripped map), weight drift writes only the touched
  weight/stickiness rows, a dark-set change flips only the changed
  ``valid_node`` entries, and a node ADD appends columns (weights,
  validity, hierarchy group ids via the resident intern tables — the
  zero-fill-new-columns recipe ``pad_carry_nodes`` uses for the solver
  carry).  Existing columns are untouched by construction:
  ``core.hierarchy.level_group_ids`` interns group ids first-seen in
  node order, so appended nodes can never renumber existing ones.
- The post-cycle apply replaces ``prev`` with the solve's PACKED
  assignment — a scatter over exactly the rows the solve changed
  (``core.encode.pack_slot_rows``, decode's own pack spelling) — so
  adopting a proposal costs O(changed rows), not a re-encode of the
  whole map.
- Decode is incremental too: the held map is patched at the changed
  rows (same ``Partition`` row spelling as ``decode_assignment``'s
  fast branch) and shortfall warnings regenerate from the resident
  ``counts``; the full ``decode_assignment`` runs only on a cold
  cycle's first decode.

The CONSERVATIVE protocol (the ServicePlanner side lives in
``blance_tpu/fleetloop.py``): warm state is keyed to the *identity* of
the controller's current map object — any off-protocol event (a pass
that didn't land the proposal verbatim, a supersede, a shape change, a
statics change, a cache eviction) demotes to a full re-encode, never a
stale map.  Cold is always correct: it is ``encode_problem`` on the
current inputs, and ``tests/test_encode_resident.py`` pins the patched
arrays bit-equal to that re-encode across every delta family.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..core.encode import (
    DenseProblem,
    NPArray,
    decode_assignment,
    pack_slot_rows,
    strip_prev_rows,
)
from ..core.hierarchy import find_ancestor
from ..core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)

__all__ = ["EncodedState", "Proposal", "build_encoded_state"]

_WARN_FMT = ("could not meet constraints: %d, stateName: %s,"
             " partitionName: %s")


@dataclass
class Proposal:
    """One un-adopted solve outcome, held until the pass lands.

    ``packed`` is the solve's assignment with every row's non-empty
    slots packed left — exactly what a fresh ``encode_problem`` of
    ``map`` would scatter, so adoption makes it the next ``prev``
    without re-encoding.  ``changed`` names the rows that differ from
    the pre-solve ``prev`` (the only rows a clean pass may move)."""

    map: PartitionMap
    packed: NPArray  # [P, S, R] int32
    counts: NPArray  # [P, S] int64 per-row filled slots
    changed: list[str]


def _gid_interns(nodes: list[str], parents: Optional[dict[str, str]],
                 max_level: int) -> list[dict[str, int]]:
    """Per-level ancestor-name -> group-id tables, replaying
    ``core.hierarchy.level_group_ids``'s exact first-seen interning so
    appending a node reuses (or extends) the SAME id space the resident
    ``gids`` rows were built with."""
    out: list[dict[str, int]] = []
    get = (parents or {}).get
    names = list(nodes)
    for level in range(max_level + 1):
        if level:
            names = [get(nm, "") for nm in names]
        table: dict[str, int] = {}
        for nm in names:
            if nm not in table:
                table[nm] = len(table)
        out.append(table)
    return out


class EncodedState:
    """One tenant's resident encoded problem (module doc).

    Mutated only from the tenant's own control-loop task (the
    ServicePlanner discipline); the shared :class:`~blance_tpu.plan.
    carry.EncodeCache` only ever drops whole states, which costs a cold
    re-encode, never staleness."""

    __slots__ = (
        "problem", "node_index", "pindex", "gid_interns", "max_level",
        "counts", "map", "expected", "pending", "mod",
        "model", "hierarchy", "hrules", "msc", "ss", "ss_standalone",
        "pw", "nw", "removes",
    )

    def __init__(self, problem: DenseProblem, current: PartitionMap,
                 removes: frozenset[str], model: PartitionModel,
                 opts: PlanOptions) -> None:
        self.problem = problem
        self.node_index = {n: i for i, n in enumerate(problem.nodes)}
        self.pindex = {p: i for i, p in enumerate(problem.partitions)}
        self.max_level = problem.gids.shape[0] - 1
        self.gid_interns = _gid_interns(
            problem.nodes, opts.node_hierarchy, self.max_level)
        self.counts: NPArray = \
            (problem.prev >= 0).sum(axis=2).astype(np.int64)
        # The held decoded map: None until a decode-produced proposal is
        # adopted — a caller-supplied map may spell rows differently
        # (missing vs empty state keys), so the first decode after a
        # cold encode is always the full one.
        self.map: Optional[PartitionMap] = None
        # Identity token: the exact map object ``prev`` encodes.  Warm
        # cycles require ``current is expected`` — anything else is a
        # divergence and demotes to cold.
        self.expected: Optional[PartitionMap] = current
        self.pending: Optional[Proposal] = None
        self.mod: list[tuple[int, str]] = [
            (si, s) for si, s in enumerate(problem.states)
            if int(problem.constraints[si]) > 0]
        # Statics: identity-tracked; a swap demotes to cold.
        self.model = model
        self.hierarchy = opts.node_hierarchy
        self.hrules = opts.hierarchy_rules
        self.msc = opts.model_state_constraints
        self.ss = opts.state_stickiness
        self.ss_standalone = bool(opts.state_stickiness_standalone)
        # Weight-dict snapshots for the O(delta) diff.
        self.pw: dict[str, Any] = dict(opts.partition_weights or {})
        self.nw: dict[str, Any] = dict(opts.node_weights or {})
        self.removes = removes

    # -- bookkeeping ---------------------------------------------------------

    def nbytes(self) -> int:
        pr = self.problem
        total = 0
        for arr in (pr.prev, pr.partition_weights, pr.node_weights,
                    pr.valid_node, pr.stickiness, pr.gids, pr.gid_valid,
                    self.counts):
            total += int(np.asarray(arr).nbytes)
        if self.pending is not None:
            total += int(self.pending.packed.nbytes)
            total += int(self.pending.counts.nbytes)
        return total

    def statics_match(self, model: PartitionModel,
                      opts: PlanOptions) -> bool:
        """True when every encode-time static still holds (identity
        checks — the controller never swaps these mid-loop).  With
        ``state_stickiness`` configured, any partition-weight change
        also fails the check: stickiness resolution couples the two
        (core/encode.py), so the rare re-priced-with-state-stickiness
        cycle re-encodes cold rather than model the interplay."""
        if not (model is self.model
                and opts.node_hierarchy is self.hierarchy
                and opts.hierarchy_rules is self.hrules
                and opts.model_state_constraints is self.msc
                and opts.state_stickiness is self.ss
                and bool(opts.state_stickiness_standalone)
                == self.ss_standalone):
            return False
        if self.ss is not None and \
                (opts.partition_weights or {}) != self.pw:
            return False
        return True

    def shape_drifted(self) -> bool:
        """True when a fresh ``encode_problem`` of the current map
        would pick a different slot depth R (the widest row shrank
        below — or a constraint override pushed past — the resident
        one): shapes are jit statics, so the cycle must re-encode cold
        exactly like the pre-residency planner did."""
        pr = self.problem
        c_max = int(pr.constraints.max()) if pr.constraints.size else 0
        r_need = max(c_max,
                     int(self.counts.max()) if self.counts.size else 0,
                     1)
        return r_need != pr.R

    # -- delta-apply kernels -------------------------------------------------

    def apply_nodes(self, nodes: list[str],
                    opts: PlanOptions) -> Optional[tuple[int, int]]:
        """Fold the cycle's node list in.  Unchanged: (0, 0).  A pure
        append extends every [N]-shaped column in O(new nodes) —
        weights, validity, hierarchy group ids via the resident intern
        tables (the ``pad_carry_nodes`` zero-fill recipe, with real
        values instead of zeros) — and returns (nodes added, bytes
        written).  Anything else (reorder, removal, duplicate) returns
        None: demote to cold."""
        pr = self.problem
        old = pr.nodes
        if nodes == old:
            return 0, 0
        if len(nodes) <= len(old) or nodes[:len(old)] != old:
            return None
        fresh = nodes[len(old):]
        if any(n in self.node_index for n in fresh):
            return None
        nw = opts.node_weights or {}
        add_w = np.array([nw.get(n, 1) for n in fresh], np.float32)
        add_valid = np.array([n not in self.removes for n in fresh],
                             bool)
        levels = self.max_level + 1
        add_gids = np.empty((levels, len(fresh)), np.int32)
        add_gvalid = np.empty((levels, len(fresh)), bool)
        for j, n in enumerate(fresh):
            for level in range(levels):
                name = n if level == 0 else find_ancestor(
                    n, self.hierarchy, level)
                table = self.gid_interns[level]
                gid = table.get(name)
                if gid is None:
                    gid = len(table)
                    table[name] = gid
                add_gids[level, j] = gid
                add_gvalid[level, j] = name != ""
        pr.node_weights = np.concatenate([pr.node_weights, add_w])
        pr.valid_node = np.concatenate([pr.valid_node, add_valid])
        pr.gids = np.concatenate([pr.gids, add_gids], axis=1)
        pr.gid_valid = np.concatenate([pr.gid_valid, add_gvalid],
                                      axis=1)
        pr.nodes = list(nodes)
        for j, n in enumerate(fresh):
            self.node_index[n] = len(old) + j
        nbytes = int(add_w.nbytes + add_valid.nbytes + add_gids.nbytes
                     + add_gvalid.nbytes)
        return len(fresh), nbytes

    def apply_removes(self, removes: frozenset[str]) -> int:
        """Flip ``valid_node`` for exactly the nodes whose dark status
        changed; returns entries flipped."""
        if removes == self.removes:
            return 0
        valid = self.problem.valid_node
        flips = 0
        for n in self.removes ^ removes:
            ni = self.node_index.get(n)
            if ni is not None:
                valid[ni] = n not in removes
                flips += 1
        self.removes = removes
        return flips

    def apply_weights(self, opts: PlanOptions) -> tuple[int, int]:
        """Write exactly the weight/stickiness rows the option dicts
        changed (encode_problem's resolution per row: partition weight
        else default 1, stickiness = that weight else 1.5 — the
        state-stickiness interplay is excluded by statics_match).
        Returns (rows written, bytes written)."""
        rows = 0
        nbytes = 0
        new_pw = opts.partition_weights or {}
        if new_pw != self.pw:
            pweights = self.problem.partition_weights
            stick = self.problem.stickiness
            touched = set()
            for k, v in new_pw.items():
                if k in self.pindex and self.pw.get(k) != v:
                    touched.add(k)
            for k in self.pw:
                if k not in new_pw and k in self.pindex:
                    touched.add(k)
            for name in touched:
                pi = self.pindex[name]
                v = new_pw.get(name)
                wv = np.float32(1.0 if v is None else v)
                sv = np.float32(1.5 if v is None else v)
                if pweights[pi] != wv or stick[pi, 0] != sv:
                    pweights[pi] = wv
                    stick[pi, :] = sv
                    rows += 1
                    nbytes += 4 + 4 * stick.shape[1]
            self.pw = dict(new_pw)
        new_nw = opts.node_weights or {}
        if new_nw != self.nw:
            nweights = self.problem.node_weights
            touched = set()
            for k, v in new_nw.items():
                if k in self.node_index and self.nw.get(k) != v:
                    touched.add(k)
            for k in self.nw:
                if k not in new_nw and k in self.node_index:
                    touched.add(k)
            for name in touched:
                ni = self.node_index[name]
                wv = np.float32(1.0 if new_nw.get(name) is None
                                else new_nw[name])
                if nweights[ni] != wv:
                    nweights[ni] = wv
                    rows += 1
                    nbytes += 4
            self.nw = dict(new_nw)
        return rows, nbytes

    def apply_strip(self, nodes: set[str],
                    after: PartitionMap) -> tuple[int, int]:
        """An abrupt-fail strip: remove the dark nodes' placements from
        their holder rows (prev re-packed via the decode pack spelling)
        and patch the held map's rows to the strip spelling; ``after``
        becomes the new identity token.  Any un-adopted proposal is
        stale by definition (it was solved from the pre-strip prev) and
        is discarded.  Returns (rows patched, bytes written)."""
        pr = self.problem
        ids = np.array(sorted(self.node_index[n] for n in nodes
                              if n in self.node_index), np.int32)
        self.pending = None
        self.expected = after
        if ids.size == 0:
            return 0, 0
        new_prev, dirty = strip_prev_rows(pr.prev, ids)
        pr.prev = new_prev
        rows = int(dirty.sum())
        if rows:
            self.counts[dirty] = \
                (new_prev[dirty] >= 0).sum(axis=2).astype(np.int64)
            if self.map is not None:
                patched = dict(self.map)
                for pi in np.flatnonzero(dirty).tolist():
                    pname = pr.partitions[pi]
                    p = patched[pname]
                    patched[pname] = Partition(pname, {
                        s: [n for n in ns if n not in nodes]
                        for s, ns in p.nodes_by_state.items()})
                self.map = patched
        return rows, rows * (pr.S * pr.R * 4 + pr.S * 8)

    def adopt(self, proposal: Proposal,
              expected: PartitionMap) -> tuple[int, int]:
        """The post-cycle apply: the landed proposal's packed
        assignment becomes ``prev`` (a scatter over exactly the rows
        the solve changed — here a whole-array swap, since the packed
        table was built by patching a copy of ``prev`` at those rows),
        the proposal map becomes the held map, and ``expected`` (the
        controller's new current object) the identity token.  Returns
        (rows adopted, bytes)."""
        pr = self.problem
        pr.prev = proposal.packed
        self.counts = proposal.counts
        self.map = proposal.map
        self.expected = expected
        self.pending = None
        rows = len(proposal.changed)
        return rows, rows * (pr.S * pr.R * 4 + pr.S * 8)

    # -- incremental decode --------------------------------------------------

    def decode(self, assign: NPArray, current: PartitionMap,
               removes: list[str]) -> tuple[
                   PartitionMap, dict[str, list[str]], bool, int]:
        """Decode a solve against the resident state: patch the held
        map at the changed rows (full ``decode_assignment`` only when
        no canonical held map exists yet), regenerate shortfall
        warnings from the resident counts, and stage the proposal for
        adoption.  Returns (map, warnings, was_full_decode, changed
        rows).  Bit-identity to the full decode is pinned by
        tests/test_encode_resident.py."""
        pr = self.problem
        prev = pr.prev
        changed_idx = np.flatnonzero(
            (assign != prev).any(axis=(1, 2)))
        sub = np.ascontiguousarray(assign[changed_idx], np.int32)
        packed_rows, counts_rows = pack_slot_rows(sub)
        packed = prev.copy()
        packed[changed_idx] = packed_rows
        counts_new = self.counts.copy()
        counts_new[changed_idx] = counts_rows
        warnings: dict[str, list[str]]
        full = self.map is None
        if full:
            next_map, warnings = decode_assignment(
                pr, assign, current, removes)
        else:
            next_map = dict(self.map)
            # Vectorized over the changed rows, decode_assignment's
            # exact spelling per modeled state: one object-array name
            # gather + tolist per state, rows sliced by their counts.
            names_arr = np.asarray(pr.nodes, dtype=object)
            rows_per_state: list[list[list[str]]] = []
            for si, _sname in self.mod:
                ids = packed_rows[:, si, :]
                nested = names_arr[np.maximum(ids, 0)].tolist()
                cts = counts_rows[:, si].tolist()
                rows_per_state.append(
                    [row[:c] for row, c in zip(nested, cts)])
            mod_names = [s for _si, s in self.mod]
            for j, pi in enumerate(changed_idx.tolist()):
                pname = pr.partitions[pi]
                next_map[pname] = Partition(pname, dict(zip(
                    mod_names, (rows[j] for rows in rows_per_state))))
            # Shortfall warnings, decode_assignment's exact loop (state
            # order, then partition index order) off the updated counts.
            warnings = {}
            for si, sname in self.mod:
                want = int(pr.constraints[si])
                short = np.nonzero(counts_new[:, si] < want)[0]
                for pi in short:
                    pname = pr.partitions[pi]
                    warnings.setdefault(pname, []).append(
                        _WARN_FMT % (want, sname, pname))
        self.pending = Proposal(
            map=next_map, packed=packed, counts=counts_new,
            changed=[pr.partitions[i] for i in changed_idx.tolist()])
        return next_map, warnings, full, int(changed_idx.size)


def build_encoded_state(
    problem: DenseProblem,
    current: PartitionMap,
    removes: list[str],
    model: PartitionModel,
    opts: PlanOptions,
) -> Optional[EncodedState]:
    """Residency entry: wrap a freshly encoded problem as resident
    state, or None when the tenant is out of protocol — a degenerate
    problem, or a map with pass-through states (unmodeled or
    zero-constraint states in some partition's source: decode must then
    consult the live map per row, so its output cannot be patched from
    arrays alone and ``prev`` cannot be rebuilt from the packed
    assignment).  Out-of-protocol tenants simply stay on the full
    re-encode path."""
    if problem.P == 0 or problem.S == 0 or problem.N == 0:
        return None
    solved = {s for si, s in enumerate(problem.states)
              if int(problem.constraints[si]) > 0}
    for p in current.values():
        if not (p.nodes_by_state.keys() <= solved):
            return None
    return EncodedState(problem, current, frozenset(removes), model,
                        opts)
