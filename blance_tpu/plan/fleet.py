"""Fleet-scale multi-tenant batch planning: vmapped bucket-class solves.

Production deployments of the paper's scenario (cbgt/FTS-style) rebalance
hundreds of tenant *indexes* concurrently — each its own small, fully
independent planning problem — yet every ``solve_dense`` call is
one-at-a-time, so a fleet replan pays hundreds of device dispatches for
work that fits in one.  This module is the batch tier:

- tenants are admitted as :class:`TenantProblem`\\ s and grouped into
  **batch classes**: the PR-2 shape buckets (core/encode.py
  ``bucket_size``) on (P, N) plus the solver statics (S, R, constraints,
  rules).  Same class == same compiled program, the GSPMD bucketed-
  compilation insight (arXiv:2105.04663) lifted from "repeated calls"
  to "concurrent tenants".
- each class stacks its tenants' padded arrays into ``[B, P, S, R]`` /
  ``[B, S, N]`` batch tensors (core/encode.py ``pad_problem_arrays`` +
  ``stack_problem_arrays`` — the same inert-padding contract the
  bucketed single-problem path uses, so pad rows provably cannot
  perturb real rows) and runs the dense auction solver under
  ``jax.vmap``: one device dispatch per class, per-element results
  bit-identical to the single-problem path (pinned by
  tests/test_fleet.py).
- warm tenants (a caller-provided :class:`plan.tensor.SolveCarry` +
  dirty mask, typically via a :class:`plan.carry.CarryCache`) run the
  one-sweep carry-seeded repair under vmap, with the same per-element
  acceptance flags as ``solve_dense_warm``; declined elements fall back
  into the class's cold batch.
- with a 1-D ``jax.sharding.Mesh`` the batch axis is sharded over the
  mesh via ``shard_map`` — tenant solves are embarrassingly parallel
  (no cross-tenant collectives), so every device solves its slice of
  the class concurrently.  This composes with, rather than replaces,
  parallel/sharded.py: a tenant too large to batch still takes the
  partition-sharded single-problem path.

The per-element arithmetic is exactly the single-problem bucketed
path's: padded shapes, the real partition count threaded as the traced
``p_real`` fill denominator.  The sequential reference for every fleet
solve is therefore ``solve_dense_converged`` / ``solve_dense_warm`` on
the same padded arrays — and the results match those bit-for-bit.

The asyncio front door (request coalescing, backpressure, per-tenant
carry cache) lives in plan/service.py; this module is the synchronous
compute core.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.encode import (
    DenseProblem,
    NPArray,
    bucket_size,
    pad_problem_arrays,
    pad_to,
    stack_problem_arrays,
)
from ..obs import device as _device
from ..obs import get_recorder
from .carry import capacity_shrank, effective_dirty
from .tensor import (
    Constraints,
    Rules,
    SolveCarry,
    _check_tier_band_scale,
    _solve_dense_converged_impl,
    _used_by_state,
    _warm_repair,
    resolve_default_fused_score,
    resolve_fused_score,
)

__all__ = ["TenantProblem", "BatchClass", "FleetResult", "batch_class_of",
           "validate_tenant", "solve_fleet", "FLEET_AXIS"]

# Default mesh axis name for fleet batch sharding (make_mesh's "parts"
# axis is accepted too — any 1-D mesh works, the axis carries no
# collectives).
FLEET_AXIS = "fleet"


class BatchClass(NamedTuple):
    """One compiled-program equivalence class of tenant problems."""

    p: int  # bucketed partition count (bucket_size(P_real))
    n: int  # bucketed node count (bucket_size(N_real))
    s: int  # states
    r: int  # slot depth
    levels: int  # hierarchy levels (gids rows)
    constraints: tuple[int, ...]
    rules: tuple[tuple[tuple[int, int], ...], ...]


@dataclass(frozen=True)
class TenantProblem:
    """One tenant's dense planning problem, ready to batch.

    Arrays follow plan/tensor.py solve_dense's positional layout.  The
    optional ``carry``/``dirty`` pair requests the warm path: ``carry``
    must match ``prev`` exactly (the solve_dense_warm contract — the
    CarryCache's consume() validates this for service callers) and
    ``dirty`` marks the partitions the delta since the carry may move.
    """

    key: str
    prev: NPArray  # [P, S, R] int32, -1 empty
    partition_weights: NPArray  # [P] float32
    node_weights: NPArray  # [N] float32
    valid_node: NPArray  # [N] bool
    stickiness: NPArray  # [P, S] float32
    gids: NPArray  # [L, N] int32
    gid_valid: NPArray  # [L, N] bool
    constraints: tuple[int, ...]
    rules: tuple[tuple[tuple[int, int], ...], ...]
    carry: Optional[SolveCarry] = None
    dirty: Optional[NPArray] = None

    @classmethod
    def from_dense(cls, key: str, problem: DenseProblem,
                   carry: Optional[SolveCarry] = None,
                   dirty: Optional[NPArray] = None,
                   prev: Optional[NPArray] = None) -> "TenantProblem":
        """Wrap an encoded DenseProblem (``prev`` overrides the encode-
        time seed — pass a session's live ``current``)."""
        return cls(
            key=key,
            prev=np.asarray(problem.prev if prev is None else prev,
                            np.int32),
            partition_weights=np.asarray(problem.partition_weights,
                                         np.float32),
            node_weights=np.asarray(problem.node_weights, np.float32),
            valid_node=np.asarray(problem.valid_node, bool),
            stickiness=np.asarray(problem.stickiness, np.float32),
            gids=np.asarray(problem.gids, np.int32),
            gid_valid=np.asarray(problem.gid_valid, bool),
            constraints=tuple(int(c) for c in problem.constraints),
            rules=tuple(tuple(problem.rules.get(si, ()))
                        for si in range(problem.S)),
            carry=carry,
            dirty=dirty,
        )


@dataclass
class FleetResult:
    """One tenant's solve outcome (arrays at the REAL, unpadded shape)."""

    key: str
    assign: NPArray  # [P, S, R] int32
    carry: Optional[SolveCarry]  # rebuilt warm-start state, real-N used
    warm: bool  # solved by an accepted one-sweep repair
    sweeps: int  # converged-loop passes executed
    klass: Optional[BatchClass]  # None for degenerate (empty) problems


def batch_class_of(t: TenantProblem) -> BatchClass:
    """The tenant's batch class: bucketed shape + solver statics."""
    p, s, r = t.prev.shape
    n = t.node_weights.shape[0]
    return BatchClass(
        p=bucket_size(p), n=bucket_size(n), s=s, r=r,
        levels=t.gids.shape[0],
        constraints=tuple(int(c) for c in t.constraints),
        rules=tuple(tuple(rl) for rl in t.rules))


def validate_tenant(t: TenantProblem) -> None:
    """Raise ValueError when one tenant's problem cannot be solved —
    the per-tenant preconditions the single-problem entry points check,
    plus cross-array shape consistency (a malformed array would
    otherwise only explode inside the batched solve).  solve_fleet runs
    this for every admitted tenant (a raise fails the whole call); the
    plan service runs it per request BEFORE batching, so one tenant's
    bad arrays fail that request alone instead of its co-batched
    neighbors."""
    prev = np.asarray(t.prev)
    if prev.ndim != 3:
        raise ValueError(
            f"tenant {t.key!r}: prev must be [P, S, R], got shape "
            f"{prev.shape}")
    p, s, r = prev.shape
    n = np.asarray(t.node_weights).shape[0]
    shapes = {
        "partition_weights": (np.asarray(t.partition_weights).shape,
                              (p,)),
        "stickiness": (np.asarray(t.stickiness).shape, (p, s)),
        "valid_node": (np.asarray(t.valid_node).shape, (n,)),
        "gids": (np.asarray(t.gids).shape[-1:], (n,)),
        "gid_valid": (np.asarray(t.gid_valid).shape,
                      np.asarray(t.gids).shape),
    }
    if t.dirty is not None:
        shapes["dirty"] = (np.asarray(t.dirty).shape, (p,))
    for name, (got, want) in shapes.items():
        if tuple(got) != tuple(want):
            raise ValueError(
                f"tenant {t.key!r}: {name} shape {tuple(got)} does not "
                f"match prev/nodes (want {tuple(want)})")
    if t.constraints and max(t.constraints) > r:
        raise ValueError(
            f"tenant {t.key!r}: prev slot depth R={r} "
            f"< max constraints {max(t.constraints)}")
    # Host-side guard parity with the single-problem entry points.
    _check_tier_band_scale(
        t.prev, t.partition_weights, t.node_weights, t.valid_node,
        t.stickiness, t.constraints, t.rules)


# -- batched device programs -------------------------------------------------
#
# Module-level jits with static (constraints, rules, ...) so every batch
# class compiles exactly once and every later dispatch of the class hits
# the jit cache (the whole point of bucketed batching).  The per-element
# body is the SAME traced code as the single-problem path —
# _solve_dense_converged_impl / _warm_repair with the traced p_real fill
# denominator — so vmap only adds the batch dimension, and per-element
# outputs are bit-identical to single solves (tests/test_fleet.py pins
# this, cold and warm).


@partial(jax.jit, static_argnames=("constraints", "rules",
                                   "max_iterations", "fused_score"))
def _fleet_cold_batch(
    prev: jnp.ndarray,  # [B, P, S, R]
    pweights: jnp.ndarray,  # [B, P]
    nweights: jnp.ndarray,  # [B, N]
    valid: jnp.ndarray,  # [B, N]
    stickiness: jnp.ndarray,  # [B, P, S]
    gids: jnp.ndarray,  # [B, L, N]
    gid_valid: jnp.ndarray,  # [B, L, N]
    p_real: jnp.ndarray,  # [B] f32 — real partition counts
    constraints: Constraints,
    rules: Rules,
    max_iterations: int = 10,
    fused_score: str = "off",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched cold fixpoint: (assign[B,P,S,R], sweeps[B], used[B,S,N]).

    ``used`` is each element's carry table (_used_by_state, the same
    scatter the single-problem carry_from_assignment runs) so the next
    warm solve seeds bit-identically without B little host jits."""
    def one(prev1, pw1, nw1, valid1, stick1, gids1, gv1, p1):
        out, sweeps = _solve_dense_converged_impl(
            prev1, pw1, nw1, valid1, stick1, gids1, gv1, constraints,
            rules, max_iterations=max_iterations, fused_score=fused_score,
            p_real=p1)
        used = _used_by_state(out, pw1, nw1.shape[0], out.shape[1])
        return out, sweeps, used

    return jax.vmap(one)(prev, pweights, nweights, valid, stickiness,
                         gids, gid_valid, p_real)


@partial(jax.jit, static_argnames=("constraints", "rules", "fused_score"))
def _fleet_warm_batch(
    prev: jnp.ndarray,  # [B, P, S, R]
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    dirty: jnp.ndarray,  # [B, P] bool (pad rows True: not a ripple)
    carry_used: jnp.ndarray,  # [B, S, N]
    p_real: jnp.ndarray,  # [B]
    constraints: Constraints,
    rules: Rules,
    fused_score: str = "off",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched one-sweep warm repair: (assign, used, ok) per element."""
    def one(prev1, pw1, nw1, valid1, stick1, gids1, gv1, dirty1, cu1, p1):
        return _warm_repair(
            prev1, pw1, nw1, valid1, stick1, gids1, gv1, dirty1, cu1,
            constraints, rules, fused_score=fused_score, p_real=p1)

    return jax.vmap(one)(prev, pweights, nweights, valid, stickiness,
                         gids, gid_valid, dirty, carry_used, p_real)


# Mesh-sharded variants, built lazily per (mesh, statics) and cached —
# rebuilding jax.jit(shard_map(...)) per call would defeat the jit
# cache.  Bounded: a fleet deployment has a handful of classes and one
# mesh.
_MESH_FN_CACHE: dict[tuple[object, ...], Any] = {}
_MESH_FN_CACHE_MAX = 128


def _mesh_callable(mesh, warm: bool, constraints: Constraints, rules: Rules,
                   max_iterations: int, fused_score: str):
    """jit(shard_map(vmap(solver))) with the batch axis sharded.

    Tenant solves are independent — no collectives ride the mesh axis —
    so in/out specs shard every operand's leading (batch) dimension and
    nothing is replicated.  The replication checker is disabled the same
    way parallel/sharded.py does for while-loop bodies (pre-vma JAX has
    no replication rule for while; nothing here is replicated anyway).
    """
    from ..parallel.sharded import _build_checked, _shard_map
    from jax.sharding import PartitionSpec

    key = (mesh, warm, constraints, rules, max_iterations, fused_score)
    fn = _MESH_FN_CACHE.get(key)
    if fn is not None:
        # Move-to-end: insertion order doubles as LRU recency.
        _MESH_FN_CACHE[key] = _MESH_FN_CACHE.pop(key)
        return fn
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"fleet batch sharding wants a 1-D mesh, got axes "
            f"{mesh.axis_names}")
    axis = mesh.axis_names[0]
    sh = PartitionSpec(axis)
    if warm:
        body = partial(_fleet_warm_batch, constraints=constraints,
                       rules=rules, fused_score=fused_score)
        n_in = 10
    else:
        body = partial(_fleet_cold_batch, constraints=constraints,
                       rules=rules, max_iterations=max_iterations,
                       fused_score=fused_score)
        n_in = 8
    sm = partial(_shard_map, body, mesh=mesh, in_specs=(sh,) * n_in,
                 out_specs=(sh, sh, sh))
    fn = jax.jit(_build_checked(sm, False))
    while len(_MESH_FN_CACHE) >= _MESH_FN_CACHE_MAX:
        # Evict the least-recently-used wrapper only — clearing the
        # whole table would force every hot class to retrace.
        _MESH_FN_CACHE.pop(next(iter(_MESH_FN_CACHE)))
    _MESH_FN_CACHE[key] = fn
    return fn


# -- host orchestration ------------------------------------------------------


def _normalized(t: TenantProblem) -> TenantProblem:
    """Dtype-normalize a tenant's arrays (solver dtypes, C-contiguous)."""
    return TenantProblem(
        key=t.key,
        prev=np.ascontiguousarray(t.prev, np.int32),
        partition_weights=np.ascontiguousarray(t.partition_weights,
                                               np.float32),
        node_weights=np.ascontiguousarray(t.node_weights, np.float32),
        valid_node=np.ascontiguousarray(t.valid_node, bool),
        stickiness=np.ascontiguousarray(t.stickiness, np.float32),
        gids=np.ascontiguousarray(t.gids, np.int32),
        gid_valid=np.ascontiguousarray(t.gid_valid, bool),
        constraints=tuple(int(c) for c in t.constraints),
        rules=tuple(tuple(rl) for rl in t.rules),
        carry=t.carry,
        dirty=None if t.dirty is None
        else np.ascontiguousarray(t.dirty, bool),
    )


def _padded_solver_arrays(t: TenantProblem,
                          k: BatchClass) -> tuple[NPArray, ...]:
    """One tenant's arrays padded to its class shape (inert padding)."""
    return pad_problem_arrays(
        t.prev, t.partition_weights, t.node_weights, t.valid_node,
        t.stickiness, t.gids, t.gid_valid, k.p, k.n)


def _warm_eligible(t: TenantProblem, rec,
                   record: bool) -> Optional[NPArray]:
    """The tenant's effective dirty mask when the warm path may run,
    else None (demoted to cold).  Mirrors PlannerSession.replan's
    gating: a carry + dirty mask must be present, the carry must match
    prev's shape, and the host capacity precheck must not predict a
    clean-holder displacement (which the repair could never accept)."""
    if t.carry is None or t.dirty is None:
        return None
    carry_assign = np.asarray(t.carry.assign)
    used = np.asarray(t.carry.used)
    if carry_assign.shape != t.prev.shape or \
            used.shape != (t.prev.shape[1], t.node_weights.shape[0]):
        if record:
            rec.count("plan.solve.carry_miss")
        return None
    dirty = effective_dirty(t.dirty, t.prev, t.constraints)
    if capacity_shrank(used, t.prev, t.partition_weights,
                       t.node_weights, t.valid_node, t.constraints,
                       dirty):
        # Grown cluster: the trim pass would displace clean holders —
        # the repair could never be accepted, so skip straight to cold
        # instead of wasting a sweep (PlannerSession parity).
        if record:
            rec.count("plan.solve.carry_miss")
        return None
    return dirty


def _pad_batch(stacked: Sequence[NPArray],
               b_target: int) -> tuple[list[NPArray], int]:
    """Pad the batch axis to ``b_target`` by replicating the last
    element (a real problem solves to a real answer, discarded) —
    returns (padded arrays, padded B)."""
    b = stacked[0].shape[0]
    if b_target <= b:
        return list(stacked), b
    reps = np.full(b_target - b, b - 1, np.intp)
    return [np.concatenate([a, a[reps]]) for a in stacked], b_target


def _dispatch(fn_args: list[NPArray], mesh, warm: bool,
              k: BatchClass, max_iterations: int, fused_score: str,
              rec, record: bool,
              batch_floor: int = 1) -> tuple[NPArray, ...]:
    """Run one class batch on device (vmapped; mesh-sharded when given);
    returns host arrays, batch padding stripped.

    The batch axis is itself a static jit shape, so it gets the same
    bucketing treatment as P and N: B pads up to ``bucket_size(B)``
    (and to mesh divisibility), so a service whose coalesced batch
    sizes drift round to round reuses one compiled program per bucket
    instead of recompiling per size.  ``batch_floor`` additionally
    rounds B UP to a minimum before bucketing: at small B the buckets
    step by 1, so a fleet of control loops whose coalesced sizes
    wander 1..N would compile one program per size — flooring them
    onto one shared program trades a few inert pad elements for a
    bounded compiled-program count (docs/FLEET.md)."""
    b_real = fn_args[0].shape[0]
    b_target = bucket_size(max(b_real, batch_floor))
    ent = "fleet.warm" if warm else "fleet.cold"
    if mesh is not None:
        n_dev = int(np.prod(mesh.devices.shape))
        b_target += (-b_target) % n_dev
        fn_args, b_padded = _pad_batch(fn_args, b_target)
        fn = _mesh_callable(mesh, warm, k.constraints, k.rules,
                            max_iterations, fused_score)
        from jax.sharding import NamedSharding, PartitionSpec

        spec = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
        # device_put straight off the host arrays: shards host->devices
        # in one placement (jnp.asarray first would commit every operand
        # to the default device and then reshard — double transfer).
        dev_args = [jax.device_put(a, spec) for a in fn_args]
        _device.maybe_publish_cost(
            ent, f"{k.p}x{k.n}xB{b_padded}", fn, *dev_args)
        # Dispatch-time jaxpr-constant uploads are implicit transfers by
        # jax's classification but intrinsic to compilation — the same
        # scoped allow parallel/sharded.py documents.
        with jax.transfer_guard("allow"), _device.entry(ent):
            outs = fn(*dev_args)
    else:
        fn_args, b_padded = _pad_batch(fn_args, b_target)
        dev_args = [jnp.asarray(a) for a in fn_args]
        batch_fn = _fleet_warm_batch if warm else _fleet_cold_batch
        statics = dict(constraints=k.constraints, rules=k.rules,
                       fused_score=fused_score)
        if not warm:
            statics["max_iterations"] = max_iterations
        _device.maybe_publish_cost(
            ent, f"{k.p}x{k.n}xB{b_padded}", batch_fn, *dev_args,
            **statics)
        with _device.entry(ent):
            outs = batch_fn(*dev_args, **statics)
    if record:
        rec.observe("fleet.batch_tenants", float(b_real))
        rec.observe("fleet.batch_occupancy",
                    b_real / b_padded if b_padded else 0.0)
        # Host->device transfer accounting: the stacked batch tensors
        # this dispatch ships (deterministic — a pure function of the
        # batch's shapes, so exposition text stays replay-identical).
        rec.count("fleet.h2d_bytes",
                  sum(int(np.asarray(a).nbytes) for a in fn_args))
    return tuple(np.asarray(o)[:b_real] for o in outs)


def _count_solve(rec, sweeps: int) -> None:
    """One solved element's plan.solve.* accounting — the
    tensor._record_sweeps spelling, routed to THIS recorder (the
    executor-thread path must not fall back to the process global)."""
    rec.count("plan.solve.calls")
    rec.count("plan.solve.sweeps", sweeps)
    rec.observe("plan.solve.sweeps", sweeps)


def _real_carry(assign: NPArray, used_padded: NPArray,
                n_real: int) -> SolveCarry:
    """Strip node padding off a batched element's carry table.  Pad
    columns are invalid nodes with zero fill (inert-padding contract),
    so the slice is exact; prices re-derive as the per-node sum.  The
    slice is COPIED (explicitly — at bucket-exact sizes it is already
    contiguous): a view would pin the whole [B, S, N] batch tensor
    alive per tenant while CarryCache's byte accounting sees only the
    slice."""
    used = used_padded[:, :n_real].copy()
    return SolveCarry(prices=used.sum(axis=0), assign=assign, used=used)


def _trace_attrs(trace_ids: Optional[dict[str, str]],
                 keys: Sequence[str]) -> dict[str, str]:
    """Span attrs carrying the batch members' trace ids (capped: a
    thousand-tenant batch must not serialize a novel per span)."""
    if not trace_ids:
        return {}
    ids = [str(trace_ids[k]) for k in keys if k in trace_ids]
    if not ids:
        return {}
    shown = ",".join(ids[:16])
    if len(ids) > 16:
        shown += f",+{len(ids) - 16}"
    return {"trace_ids": shown}


def solve_fleet(
    problems: Sequence[TenantProblem],
    *,
    mesh=None,
    max_iterations: int = 10,
    fused_score: Optional[str] = None,
    record: bool = True,
    recorder=None,
    trace_ids: Optional[dict[str, str]] = None,
    batch_floor: int = 1,
) -> list[FleetResult]:
    """Solve every tenant, batched by bucket class: one device dispatch
    per (class, warm/cold) instead of one per tenant.

    Results are returned in input order, each bit-identical to running
    that tenant through the single-problem path on the same padded
    arrays (``solve_dense_converged`` / ``solve_dense_warm`` with the
    class shape and the tenant's real-P fill denominator).  Tenants
    with a ``carry`` + ``dirty`` pair attempt the one-sweep warm repair
    first; declined elements (ripple / fresh over-capacity — the same
    per-element flags the single warm path checks) fall back into the
    class's cold batch, exactly like a session's warm decline.

    ``mesh`` (1-D) shards each class's batch axis over the devices via
    shard_map — tenant solves are independent, so this is pure
    data-parallel scale-out.  ``fused_score`` None resolves the module
    default per class shape, like every other solve entry point.

    obs: per-batch ``fleet.batch_tenants`` / ``fleet.batch_occupancy``
    histograms and a ``fleet.dispatch`` span per device dispatch with
    the ``fleet.dispatch_s`` histogram; per-tenant ``plan.solve.*``
    carry/sweep counters mirror the single-problem spellings.
    ``recorder`` overrides the process recorder (the plan service
    passes its own so executor-thread solves report to the right one).
    ``trace_ids`` (tenant key → trace id, the plan service's
    :class:`obs.tracectx.TraceContext` ids) rides into each
    ``fleet.dispatch`` span's attrs so a request's device dispatch is
    findable from its trace id in Perfetto and the JSONL sink.
    """
    rec = recorder if recorder is not None else get_recorder()
    results: dict[int, FleetResult] = {}
    tenants = [_normalized(t) for t in problems]

    by_class: dict[BatchClass, list[int]] = {}
    for i, t in enumerate(tenants):
        # Validate FIRST: a malformed prev must surface as the keyed
        # per-tenant diagnostic, not an opaque shape-unpack error.
        validate_tenant(t)
        p, s, _r = t.prev.shape
        n = t.node_weights.shape[0]
        if p == 0 or n == 0 or s == 0:
            # Degenerate problem: nothing to place (PlannerSession
            # returns current unchanged for these).
            results[i] = FleetResult(
                key=t.key, assign=t.prev.copy(), carry=None, warm=False,
                sweeps=0, klass=None)
            continue
        by_class.setdefault(batch_class_of(t), []).append(i)

    for k, idxs in by_class.items():
        mode = fused_score
        if mode is None:
            mode = resolve_default_fused_score(k.p, k.n)
        else:
            mode = resolve_fused_score(mode, k.p, k.n)

        warm_idx: list[int] = []
        warm_dirty: dict[int, NPArray] = {}
        cold_idx: list[int] = []
        for i in idxs:
            dirty = _warm_eligible(tenants[i], rec, record)
            if dirty is None:
                cold_idx.append(i)
            else:
                warm_idx.append(i)
                warm_dirty[i] = dirty

        if warm_idx:
            batch = []
            for i in warm_idx:
                t = tenants[i]
                arrs = _padded_solver_arrays(t, k)
                # Pad rows are marked dirty (their synthetic assignments
                # must not read as a ripple) and the carry table's pad
                # columns are zero-fill — the parallel/sharded.py warm
                # layout, element-wise.
                dirty_p = pad_to(warm_dirty[i], 0, k.p, True)
                cu = pad_to(np.asarray(t.carry.used, np.float32), 1,
                            k.n, 0.0)
                batch.append(arrs + (dirty_p, cu,
                                     np.float32(t.prev.shape[0])))
                if record:
                    rec.observe(
                        "plan.solve.dirty_fraction",
                        float(warm_dirty[i].mean())
                        if warm_dirty[i].size else 0.0)
            stacked = list(stack_problem_arrays(batch))
            t0 = rec.now()
            with rec.span("fleet.dispatch", warm=True,
                          tenants=len(warm_idx),
                          klass=f"{k.p}x{k.n}",
                          **_trace_attrs(trace_ids,
                                         [tenants[i].key
                                          for i in warm_idx])):
                out_b, used_b, ok_b = _dispatch(
                    stacked, mesh, True, k, max_iterations, mode, rec,
                    record, batch_floor=batch_floor)
            if record:
                rec.observe("fleet.dispatch_s", rec.now() - t0)
                rec.count("fleet.batches")
            for j, i in enumerate(warm_idx):
                t = tenants[i]
                if bool(ok_b[j]):
                    p_real = t.prev.shape[0]
                    n_real = t.node_weights.shape[0]
                    # Copy off the batch tensor: a view per tenant would
                    # pin the whole [B, P, S, R] array alive (row
                    # slices are contiguous, so ascontiguousarray
                    # would no-op into a view).
                    assign = out_b[j][:p_real].copy()
                    if record:
                        _count_solve(rec, 1)
                        rec.count("plan.solve.carry_hit")
                    results[i] = FleetResult(
                        key=t.key, assign=assign,
                        carry=_real_carry(assign, used_b[j], n_real),
                        warm=True, sweeps=1, klass=k)
                else:
                    # Declined repair: same accounting as
                    # solve_dense_warm's decline, then the cold batch
                    # picks the tenant up.
                    if record:
                        rec.count("plan.solve.warm_fallback")
                        rec.count("plan.solve.sweeps", 1)
                    cold_idx.append(i)

        if cold_idx:
            batch = []
            for i in cold_idx:
                t = tenants[i]
                arrs = _padded_solver_arrays(t, k)
                batch.append(arrs + (np.float32(t.prev.shape[0]),))
            stacked = list(stack_problem_arrays(batch))
            t0 = rec.now()
            with rec.span("fleet.dispatch", warm=False,
                          tenants=len(cold_idx),
                          klass=f"{k.p}x{k.n}",
                          **_trace_attrs(trace_ids,
                                         [tenants[i].key
                                          for i in cold_idx])):
                out_b, sweeps_b, used_b = _dispatch(
                    stacked, mesh, False, k, max_iterations, mode, rec,
                    record, batch_floor=batch_floor)
            if record:
                rec.observe("fleet.dispatch_s", rec.now() - t0)
                rec.count("fleet.batches")
            for j, i in enumerate(cold_idx):
                t = tenants[i]
                p_real = t.prev.shape[0]
                n_real = t.node_weights.shape[0]
                assign = out_b[j][:p_real].copy()
                if record:
                    _count_solve(rec, int(sweeps_b[j]))
                results[i] = FleetResult(
                    key=t.key, assign=assign,
                    carry=_real_carry(assign, used_b[j], n_real),
                    warm=False, sweeps=int(sweeps_b[j]), klass=k)

    return [results[i] for i in range(len(tenants))]
