"""Exact greedy planner — the semantics oracle and "cpu" backend.

This reimplements the reference's greedy placement algorithm faithfully
(reference: /root/reference/plan.go:23-331) so that golden-output tests hold
and so the batched TPU backend (blance_tpu.plan.tensor) has an oracle to
cross-validate against.  It is a fresh Python implementation driven by the
semantics in SURVEY.md §2.2/§3.1, not a translation: state flows through
explicit ``_PlanContext``/``NodeScoreContext`` objects instead of closures
over package globals, and hooks come from ``PlanOptions``.

Semantic notes preserved on purpose (each cites the reference):
- stickiness defaults 1.5; partition_weights[partition] overrides it; the
  state_stickiness table is consulted only when partition_weights is present
  (quirk, plan.go:104-115) unless opts.state_stickiness_standalone.
- node score = stateNodeCounts + nodeToNode/numPartitions
  + 0.001*nodePartitionCounts/numPartitions, divided by positive node weight,
  boosted for negative weight, minus stickiness if the node already holds
  this state for this partition (plan.go:634-689).
- score ties break by node position in nodes_all (plan.go:617-628).
- partitions sort: on-removed-nodes first, then never-touched-added-nodes,
  then heavier first, then zero-padded-numeric-else-raw name (plan.go:519-562).
- convergence loop feeds the output back as prev/next and clears the node
  deltas, up to max_iterations (plan.go:23-58).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

import numpy as np

from ..core.hierarchy import (
    include_exclude_nodes_intersect,
    parents_to_children,
)
from ..core.setops import strings_dedup, strings_intersect, strings_remove
from ..obs import get_recorder
from ..core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
    copy_partition_map,
)

__all__ = [
    "plan_next_map_greedy",
    "sort_state_names",
    "count_state_nodes",
    "NodeScoreContext",
    "default_node_score",
]


# ---------------------------------------------------------------------------
# State ordering and counting helpers
# ---------------------------------------------------------------------------


def sort_state_names(model: PartitionModel) -> list[str]:
    """State names ordered by priority ASC then name ASC (plan.go:437-470)."""
    return sorted(model.keys(), key=lambda s: (model[s].priority, s))


def count_state_nodes(
    pmap: PartitionMap, partition_weights: Optional[dict[str, int]]
) -> dict[str, dict[str, int]]:
    """state -> node -> weighted partition count (plan.go:374-399)."""
    rv: dict[str, dict[str, int]] = {}
    for pname, partition in pmap.items():
        w = 1
        if partition_weights is not None:
            w = partition_weights.get(pname, 1)
        for state, nodes in partition.nodes_by_state.items():
            s = rv.setdefault(state, {})
            for node in nodes:
                s[node] = s.get(node, 0) + w
    return rv


def _adjust_state_node_counts(
    counts: dict[str, dict[str, int]], state: str, nodes: list[str], amt: int
) -> None:
    """counts[state][node] += amt for each node (plan.go:353-363)."""
    s = counts.setdefault(state, {})
    for node in nodes:
        s[node] = s.get(node, 0) + amt


def _remove_nodes_from_nodes_by_state(
    nodes_by_state: dict[str, list[str]],
    remove: list[str],
    on_removed: Optional[Callable[[str, str, list[str]], None]] = None,
) -> dict[str, list[str]]:
    """Copy with nodes removed; callback sees actually-removed nodes
    (plan.go:408-421)."""
    rv: dict[str, list[str]] = {}
    for state, nodes in nodes_by_state.items():
        if on_removed is not None:
            on_removed(state, strings_intersect(nodes, remove))
        rv[state] = strings_remove(nodes, remove)
    return rv


def flatten_nodes_by_state(nodes_by_state: dict[str, list[str]]) -> list[str]:
    """All nodes across states, concatenated (plan.go:425-431)."""
    rv: list[str] = []
    for nodes in nodes_by_state.values():
        rv.extend(nodes)
    return rv


# ---------------------------------------------------------------------------
# Node scoring
# ---------------------------------------------------------------------------


@dataclass
class NodeScoreContext:
    """Everything the node score formula reads (plan.go:566-578).

    Passed to custom scorers (the CustomNodeSorter extension point,
    plan.go:580) so applications can replace the formula while the framework
    keeps the position tie-break.
    """

    state_name: str
    partition: Partition
    num_partitions: int
    top_priority_node: str
    state_node_counts: dict[str, dict[str, int]]
    node_to_node_counts: dict[str, dict[str, int]]
    node_partition_counts: dict[str, int]
    node_positions: dict[str, int]
    node_weights: Optional[dict[str, int]]
    stickiness: float
    node_score_booster: Optional[object] = None


def default_node_score(ctx: NodeScoreContext, node: str) -> float:
    """The balance/stickiness score; lower is better (plan.go:634-689)."""
    lower_priority_balance = 0.0
    if ctx.num_partitions > 0:
        m = ctx.node_to_node_counts.get(ctx.top_priority_node)
        if m is not None:
            lower_priority_balance = m.get(node, 0) / ctx.num_partitions

    filled = 0.0
    if ctx.num_partitions > 0:
        c = ctx.node_partition_counts.get(node)
        if c is not None:
            filled = (0.001 * c) / ctx.num_partitions

    current = 0.0
    for state_node in ctx.partition.nodes_by_state.get(ctx.state_name, ()):
        if state_node == node:
            current = ctx.stickiness  # Minimise movement.

    r = float(ctx.state_node_counts.get(ctx.state_name, {}).get(node, 0))
    r += lower_priority_balance
    r += filled

    if ctx.node_weights is not None and node in ctx.node_weights:
        w = ctx.node_weights[node]
        if w > 0:
            r /= float(w)
        elif w < 0 and ctx.node_score_booster is not None:
            r += ctx.node_score_booster(w, current)

    return r - current


def _sort_nodes(ctx: NodeScoreContext, nodes: list[str],
                scorer: Callable[[NodeScoreContext, str], float]) -> list[str]:
    """Sort by score ASC, ties by node position in nodes_all (plan.go:617-628)."""
    return sorted(
        nodes,
        key=lambda n: (scorer(ctx, n), ctx.node_positions.get(n, 0)),
    )


# ---------------------------------------------------------------------------
# Partition ordering
# ---------------------------------------------------------------------------


def _partition_name_key(name: str) -> str:
    """Zero-pad positive-integer-looking names to width 10 for sortability.

    The reference formats with %10d, which right-aligns with *spaces*
    (plan.go:524-528); spaces compare below digits so equal-width numerics
    order numerically.  Replicated exactly for golden parity.
    """
    digits = name[1:] if name[:1] in ("+", "-") else name
    # Match Go strconv.Atoi: optional sign then ASCII digits only, int64 range.
    if not digits or not all("0" <= c <= "9" for c in digits):
        return name
    n = int(name)
    if n < 0 or n >= 2**63:
        return name
    return f"{n:>10d}"


def sorted_by_partition_name(names: "Iterable[str]") -> list[str]:
    """Sort names by (zero-padded-numeric-else-raw key, name) — the static
    component of the reference's partition order (plan.go:524-528).

    Vectorized for large inputs: plain ASCII-digit names (the overwhelmingly
    common shape) get their sort key built with numpy byte-string ops and
    ordered via lexsort; signed or >18-digit numerics fall back to
    `_partition_name_key` per element, and any non-ASCII input drops the
    whole batch back to the pure-Python path.  Byte-wise bytes comparison
    equals Go's string comparison for ASCII, so the order is identical."""
    names = list(names)
    if len(names) < 4096:
        return sorted(names, key=lambda n: (_partition_name_key(n), n))
    try:
        arr = np.asarray(names, dtype="S")
    except UnicodeEncodeError:
        return sorted(names, key=lambda n: (_partition_name_key(n), n))
    lens = np.char.str_len(arr)
    digit = np.char.isdigit(arr) & (lens <= 18)
    width = max(int(arr.dtype.itemsize), 10)
    keys = arr.astype(f"S{width}")
    if digit.any():
        d = arr[digit]
        stripped = np.char.lstrip(d, b"0")
        stripped = np.where(stripped == b"", b"0", stripped)
        keys[digit] = np.char.rjust(stripped, 10)
    odd = np.char.startswith(arr, b"+") | np.char.startswith(arr, b"-") \
        | (np.char.isdigit(arr) & (lens > 18))
    for i in np.nonzero(odd)[0]:
        keys[i] = _partition_name_key(names[i]).encode()
    order = np.lexsort((arr, keys))
    return [names[i] for i in order]


def _partition_weight_key(weight: int) -> str:
    """Heavier-first sortable weight key (plan.go:533-539); shared with the
    native backend's static rank so the encodings cannot drift."""
    return f"{999999999 - weight:>10d}"


def _partition_sort_score(
    partition: Partition,
    state_name: str,
    prev_map: Optional[PartitionMap],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    partition_weights: Optional[dict[str, int]],
) -> tuple[str, str, str]:
    """Composite sort key (plan.go:519-562); tuple compare = the reference's
    element-wise string-vector compare (plan.go:495-513)."""
    name_key = _partition_name_key(partition.name)

    weight = 1
    if partition_weights is not None:
        weight = partition_weights.get(partition.name, 1)
    weight_key = _partition_weight_key(weight)

    # Category 0: partitions whose previous holders of this state sit on
    # to-be-removed nodes (plan.go:541-550).
    if prev_map is not None and nodes_to_remove:
        last = prev_map.get(partition.name)
        if last is not None:
            lpnbs = last.nodes_by_state.get(state_name)
            if lpnbs and strings_intersect(lpnbs, nodes_to_remove):
                return ("0", weight_key, name_key)

    # Category 1: partitions not yet landed on any newly added node
    # (plan.go:553-559).  Mirrors the reference's nil-vs-empty distinction:
    # an empty-but-present nodes_to_add still triggers this branch.
    if nodes_to_add is not None:
        fnbs = flatten_nodes_by_state(partition.nodes_by_state)
        if not strings_intersect(fnbs, nodes_to_add):
            return ("1", weight_key, name_key)

    return ("2", weight_key, name_key)


def _sort_partitions(
    partitions: list[Partition],
    state_name: str,
    prev_map: Optional[PartitionMap],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    partition_weights: Optional[dict[str, int]],
) -> list[Partition]:
    return sorted(
        partitions,
        key=lambda p: (
            _partition_sort_score(
                p, state_name, prev_map, nodes_to_remove, nodes_to_add, partition_weights
            ),
            p.name,
        ),
    )


# ---------------------------------------------------------------------------
# The planner
# ---------------------------------------------------------------------------


@dataclass
class _PlanContext:
    """Mutable single-pass planner state (the closure captures in plan.go:60-303)."""

    prev_map: PartitionMap
    nodes_all: list[str]
    nodes_next: list[str]
    nodes_to_remove: list[str]
    # None vs [] is meaningful for the category-1 sort branch (plan.go:554).
    nodes_to_add: Optional[list[str]]
    model: PartitionModel
    opts: PlanOptions
    node_positions: dict[str, int]
    hierarchy_children: dict[str, list[str]]
    state_node_counts: dict[str, dict[str, int]] = field(default_factory=dict)
    warnings: dict[str, list[str]] = field(default_factory=dict)


def _top_priority_state_name(model: PartitionModel) -> str:
    """Highest-priority (lowest number) state; name breaks ties
    deterministically (the reference's map-iteration pick at plan.go:126-132
    is only deterministic when the top priority is unique)."""
    if not model:
        return ""
    return min(model.keys(), key=lambda s: (model[s].priority, s))


def _find_best_nodes(
    ctx: _PlanContext,
    partition: Partition,
    state_name: str,
    constraints: int,
    node_to_node_counts: dict[str, dict[str, int]],
) -> list[str]:
    """Ordered best-fit candidate nodes for (partition, state) (plan.go:98-248)."""
    opts = ctx.opts

    # Stickiness resolution, preserving the reference quirk (plan.go:104-115):
    # state_stickiness applies only when partition_weights is present (unless
    # the standalone compat switch is on).
    stickiness = 1.5
    if opts.partition_weights is not None:
        if partition.name in opts.partition_weights:
            stickiness = float(opts.partition_weights[partition.name])
        elif opts.state_stickiness is not None and state_name in opts.state_stickiness:
            stickiness = float(opts.state_stickiness[state_name])
    elif opts.state_stickiness_standalone and opts.state_stickiness is not None:
        if state_name in opts.state_stickiness:
            stickiness = float(opts.state_stickiness[state_name])

    # Total load per node across all states, rebuilt per call (plan.go:118-124).
    node_partition_counts: dict[str, int] = {}
    for node_counts in ctx.state_node_counts.values():
        for node, cnt in node_counts.items():
            node_partition_counts[node] = node_partition_counts.get(node, 0) + cnt

    top_state = _top_priority_state_name(ctx.model)
    top_nodes = partition.nodes_by_state.get(top_state, [])
    top_priority_node = top_nodes[0] if top_nodes else ""

    state_priority = ctx.model[state_name].priority

    def exclude_higher_priority(nodes: list[str]) -> list[str]:
        # Leave holders of superior states untouched (plan.go:146-156).
        for s, s_nodes in partition.nodes_by_state.items():
            ms = ctx.model.get(s)
            if ms is not None and ms.priority < state_priority:
                nodes = strings_remove(nodes, s_nodes)
        return nodes

    candidates = exclude_higher_priority(list(ctx.nodes_next))

    score_ctx = NodeScoreContext(
        state_name=state_name,
        partition=partition,
        num_partitions=len(ctx.prev_map),
        top_priority_node=top_priority_node,
        state_node_counts=ctx.state_node_counts,
        node_to_node_counts=node_to_node_counts,
        node_partition_counts=node_partition_counts,
        node_positions=ctx.node_positions,
        node_weights=opts.node_weights,
        stickiness=stickiness,
        node_score_booster=opts.node_score_booster,
    )
    if opts.node_sorter is not None:
        # Full-sorter replacement (reference CustomNodeSorter,
        # plan.go:566-580): the hook owns score AND tie-break policy.
        def sort_candidates(nodes):
            out = list(opts.node_sorter(score_ctx, nodes))
            if sorted(out) != sorted(nodes):
                # A hook that drops/duplicates/invents nodes would silently
                # corrupt placement (missing candidates look like unmet
                # constraints, invented ones place onto ghost nodes) —
                # reject it loudly at the boundary instead.
                from collections import Counter

                want, got = Counter(nodes), Counter(out)
                missing = sorted((want - got).elements())[:3]
                extra = sorted((got - want).elements())[:3]
                raise ValueError(
                    "node_sorter must return a permutation of its input "
                    f"nodes: got {len(out)} nodes from {len(nodes)}"
                    f"{', missing ' + repr(missing) if missing else ''}"
                    f"{', unexpected/duplicated ' + repr(extra) if extra else ''}"
                    f" (partition {partition.name!r}, state {state_name!r})")
            return out
    else:
        scorer = opts.node_scorer or default_node_score

        def sort_candidates(nodes):
            return _sort_nodes(score_ctx, nodes, scorer)
    candidates = sort_candidates(candidates)
    # Scoring-cost attribution: how many candidates each (partition, state)
    # pick had to score — the distribution that explains greedy wall-clock.
    get_recorder().observe("plan.greedy.candidates", len(candidates))

    if opts.hierarchy_rules is not None:
        # Hierarchy pass (plan.go:174-226): each rule contributes up to
        # ``constraints`` picks anchored on the primary plus picks so far.
        hierarchy_nodes: list[str] = []
        for rule in opts.hierarchy_rules.get(state_name, []):
            anchor = top_priority_node
            if anchor == "" and hierarchy_nodes:
                anchor = hierarchy_nodes[0]
            for _ in range(constraints):
                h_candidates = include_exclude_nodes_intersect(
                    [anchor] + hierarchy_nodes,
                    rule.include_level,
                    rule.exclude_level,
                    opts.node_hierarchy,
                    ctx.hierarchy_children,
                )
                h_candidates = strings_intersect(h_candidates, ctx.nodes_next)
                h_candidates = exclude_higher_priority(h_candidates)
                h_candidates = sort_candidates(h_candidates)
                if h_candidates:
                    hierarchy_nodes.append(h_candidates[0])
                elif candidates:
                    hierarchy_nodes.append(candidates[0])
        candidates = strings_dedup(hierarchy_nodes + candidates)

    if len(candidates) >= constraints:
        candidates = candidates[:constraints]
    else:
        ctx.warnings.setdefault(partition.name, []).append(
            "could not meet constraints: %d, stateName: %s, partitionName: %s"
            % (constraints, state_name, partition.name)
        )

    # Replica-spread accounting (plan.go:238-245).
    m = node_to_node_counts.setdefault(top_priority_node, {})
    for node in candidates:
        m[node] = m.get(node, 0) + 1

    return candidates


def _assign_state_to_partitions(
    ctx: _PlanContext, next_partitions: list[Partition], state_name: str, constraints: int
) -> None:
    """Assign one state across all partitions in sorted order (plan.go:253-303)."""
    ordered = _sort_partitions(
        next_partitions,
        state_name,
        ctx.prev_map,
        ctx.nodes_to_remove,
        ctx.nodes_to_add,
        ctx.opts.partition_weights,
    )

    # higher-priority node -> {lower-priority node: count}; fresh per state.
    node_to_node_counts: dict[str, dict[str, int]] = {}

    for partition in ordered:
        weight = 1
        if ctx.opts.partition_weights is not None:
            weight = ctx.opts.partition_weights.get(partition.name, 1)

        def dec(state: str, nodes: list[str]) -> None:
            if nodes:
                _adjust_state_node_counts(ctx.state_node_counts, state, nodes, -weight)

        nodes_to_assign = _find_best_nodes(
            ctx, partition, state_name, constraints, node_to_node_counts
        )

        # Uninstall the state's old holders and the newly chosen nodes from
        # every state, keeping counts consistent (plan.go:290-297).
        partition.nodes_by_state = _remove_nodes_from_nodes_by_state(
            partition.nodes_by_state, partition.nodes_by_state.get(state_name, []), dec
        )
        partition.nodes_by_state = _remove_nodes_from_nodes_by_state(
            partition.nodes_by_state, nodes_to_assign, dec
        )
        partition.nodes_by_state[state_name] = nodes_to_assign
        _adjust_state_node_counts(ctx.state_node_counts, state_name, nodes_to_assign, weight)


def _plan_next_map_inner(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: list[str],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: PlanOptions,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """One planning pass (plan.go:60-331)."""
    node_positions = {node: i for i, node in enumerate(nodes_all)}
    nodes_next = strings_remove(nodes_all, nodes_to_remove)
    hierarchy_children = parents_to_children(opts.node_hierarchy)

    # Deep-clone the partitions to assign, strip removed nodes, and fix a
    # deterministic base order (plan.go:83-89 sorts by name key only).
    next_partitions = [p.copy() for p in partitions_to_assign.values()]
    for p in next_partitions:
        p.nodes_by_state = _remove_nodes_from_nodes_by_state(
            p.nodes_by_state, nodes_to_remove
        )
    next_partitions.sort(key=lambda p: (_partition_name_key(p.name), p.name))

    ctx = _PlanContext(
        prev_map=prev_map,
        nodes_all=nodes_all,
        nodes_next=nodes_next,
        nodes_to_remove=nodes_to_remove,
        nodes_to_add=nodes_to_add,
        model=model,
        opts=opts,
        node_positions=node_positions,
        hierarchy_children=hierarchy_children,
        state_node_counts=count_state_nodes(prev_map, opts.partition_weights),
    )

    for state_name in sort_state_names(model):
        constraints = model[state_name].constraints
        if opts.model_state_constraints is not None:
            constraints = opts.model_state_constraints.get(state_name, constraints)
        if constraints > 0:
            _assign_state_to_partitions(ctx, next_partitions, state_name, constraints)

    return {p.name: p for p in next_partitions}, ctx.warnings


def plan_next_map_greedy(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: Optional[PlanOptions] = None,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """Plan the next balanced map; convergence loop (plan.go:23-58).

    Runs the inner pass up to opts.max_iterations times; between iterations
    the output is fed back as both prev and to-assign and the node deltas are
    cleared, so iteration 2+ re-balances on a stable node set.  Unlike the
    reference, the caller's maps are never mutated.
    """
    opts = opts or PlanOptions()

    with get_recorder().span(
            "plan.greedy", partitions=len(partitions_to_assign),
            nodes=len(nodes_all)):
        return _plan_next_map_greedy(
            prev_map, partitions_to_assign, nodes_all, nodes_to_remove,
            nodes_to_add, model, opts)


def _plan_next_map_greedy(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: PlanOptions,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    prev_map = copy_partition_map(prev_map)
    partitions_to_assign = copy_partition_map(partitions_to_assign)
    nodes_all = list(nodes_all)
    nodes_to_remove = list(nodes_to_remove) if nodes_to_remove is not None else []
    # nil-vs-empty matters for the category-1 partition sort branch
    # (plan.go:554); preserve None distinctly.
    nta: Optional[list[str]] = list(nodes_to_add) if nodes_to_add is not None else None

    next_map: PartitionMap = {}
    warnings: dict[str, list[str]] = {}

    for _ in range(max(1, opts.max_iterations)):
        next_map, warnings = _plan_next_map_inner(
            prev_map, partitions_to_assign, nodes_all,
            nodes_to_remove, nta, model, opts,
        )
        # Fixpoint check over the assigned partitions only (plan.go:35-45).
        if all(
            prev_map.get(p.name) is not None
            and p.nodes_by_state == prev_map[p.name].nodes_by_state
            for p in next_map.values()
        ):
            break
        # Feed forward and clear deltas (plan.go:49-55).
        for p in next_map.values():
            prev_map[p.name] = p
            partitions_to_assign[p.name] = p
        nodes_all = strings_remove(nodes_all, nodes_to_remove)
        nodes_to_remove = []
        nta = []

    return next_map, warnings
