"""blance_tpu.plan subpackage."""
