"""Long-lived dense planning sessions.

``plan_next_map`` (plan/api.py) is a pure function of PartitionMaps, like
the reference's PlanNextMapEx (reference api.go:147-157) — every call pays
the string<->id marshalling toll at the edges.  At 100k partitions that
toll dominates wall-clock (BASELINE.md), and a real cluster rebalances the
*same* index repeatedly: same partitions, same states, a slowly-changing
node set.

``PlannerSession`` amortizes everything that doesn't change: interning
tables, model/rule encoding, hierarchy group ids, the compiled solver, and
the current dense assignment.  The steady-state loop is

    session.remove_nodes(["n7"])       # cluster delta, O(delta)
    proposed = session.replan()        # on-device solve, no marshalling
    nodes, states, ops = session.moves()   # on-device diff vs current
    session.apply()                    # adopt the proposed assignment

with PartitionMaps materializing only at the edges (``load_map`` /
``to_map``) for checkpoints and app hand-off.  An optional mesh runs the
solve sharded over the partition axis (parallel/sharded.py).

Replans are INCREMENTAL by default: every apply() promotes the solve's
auction state (a plan.tensor.SolveCarry — prices, assignment, per-state
fill) to the session's warm carry, and each cluster delta marks the
partitions it can actually move in a dirty mask.  The next replan() then
runs one carry-seeded repair sweep instead of the full cold fixpoint —
bit-identical to the cold result by construction, at roughly half the
sweeps — and falls back to the cold solve whenever the repair leaks
outside the dirty mask, a capacity rail shrank under held load, the
solve engine fails, or the post-solve audit flags a violation.  See
docs/DESIGN.md "Incremental replanning" for the carry lifecycle and
docs/OBSERVABILITY.md for the plan.solve.warm/carry_* signals.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Optional

import numpy as np

from ..core.encode import NPArray, decode_assignment, encode_problem
from ..core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)
from .carry import CarryCache, capacity_shrank, effective_dirty

if TYPE_CHECKING:  # annotation-only: keep jax imports lazy at runtime
    from jax.sharding import Mesh

    from ..core.encode import DenseProblem
    from .tensor import Constraints, Rules, SolveCarry

__all__ = ["PlannerSession"]


class PlannerSession:
    """Stateful dense planner for one logical index.

    Parameters
    ----------
    model: state name -> PartitionModelState (priorities + constraints).
    nodes: every node that may ever appear, in tie-break order (node order
        is the planner's deterministic tie-break, reference plan.go:617-628).
    partitions: partition names; placement order is the planner's canonical
        name sort.
    opts: planner knobs; weights/stickiness/hierarchy are encoded once.
    mesh: optional jax.sharding.Mesh — shards the solve over partitions.
    """

    def __init__(
        self,
        model: PartitionModel,
        nodes: list[str],
        partitions: list[str],
        opts: Optional[PlanOptions] = None,
        mesh: Optional["Mesh"] = None,
        carry_cache: Optional[CarryCache] = None,
        cache_key: str = "session",
    ) -> None:
        self.model = model
        self.opts = opts or PlanOptions()
        self.mesh = mesh
        self._removed: set[str] = set()
        self._nodes = list(nodes)
        self._partition_names = list(partitions)
        self._reencode(prev_map={})
        # current/proposed dense assignments [P, S, R] int32, -1 = empty.
        self.current = self._problem.prev.copy()
        self.proposed: Optional[NPArray] = None
        # Warm-start state (docs/DESIGN.md "Incremental replanning") now
        # lives in a plan.carry.CarryCache entry — the session is a thin
        # view over one key.  The entry holds the SolveCarry matching
        # ``current`` (valid iff entry.current is literally the
        # ``current`` array it was built against — identity, because
        # every adoption path replaces the array), the pending carry of
        # ``proposed`` (promoted by apply()), and the dirty/dirty-post
        # masks (marks recorded after the pending proposal was solved
        # carry forward on apply(), not clear).  A shared cache (the
        # plan service's per-tenant store) can be passed in; by default
        # each session owns a private, unbounded one.
        self._carries = carry_cache if carry_cache is not None \
            else CarryCache()
        self._ckey = cache_key
        self._carries.entry(self._ckey, len(self._partition_names))

    # -- encoding ------------------------------------------------------------

    def _reencode(self, prev_map: PartitionMap) -> None:
        """(Re)build the dense problem statics; prev_map seeds ``prev``."""
        pta = {name: Partition(name, {}) for name in self._partition_names}
        self._problem = encode_problem(
            prev_map, pta, self._nodes, sorted(self._removed),
            self.model, self.opts)
        self._node_index = {n: i for i, n in enumerate(self._problem.nodes)}

    @property
    def problem(self) -> "DenseProblem":
        """The encoded statics (DenseProblem).

        ``problem.prev`` is only the encode-time seed (all -1, or the last
        load_map snapshot) — it goes stale after add_nodes()/replan()/
        apply().  ``self.current`` is the authoritative live assignment."""
        return self._problem

    # -- cluster membership ----------------------------------------------------

    def add_nodes(self, names: list[str]) -> None:
        """Add nodes (new capacity attracts load on the next replan).

        Dirty-mask delta: partitions with a holder in a hierarchy group
        the new node joins are marked (their rule-tier floor may have
        improved, so a warm repair must let them re-bid).  Balance-side
        displacement — existing nodes' capacity share shrinking under the
        grown cluster — is caught by replan()'s capacity precheck, which
        routes grown clusters to the cold solve rather than guessing
        which holders the trim pass will displace."""
        grew = False
        added = []
        for n in names:
            self._removed.discard(n)
            if n not in self._node_index:
                self._nodes.append(n)
                self._node_index[n] = len(self._nodes) - 1
                added.append(n)
                grew = True
        if grew:
            current = self.current
            self._reencode(prev_map={})
            # Node ids are append-only, so the old assignment is still valid.
            r_new = self._problem.R
            if r_new > current.shape[2]:
                pad = np.full(
                    current.shape[:2] + (r_new - current.shape[2],),
                    -1, np.int32)
                current = np.concatenate([current, pad], axis=2)
                # ``current`` was replaced; the carry no longer matches
                # any live assignment array (the recorded delta masks
                # still do — only the carry drops).
                self._carries.drop_carry_keep_dirty(self._ckey)
            self.current = current
            self._pad_carry_nodes()
            self._mark_dirty_for_added(
                [self._node_index[n] for n in added])
        else:
            self._problem.valid_node[:] = [
                n not in self._removed for n in self._problem.nodes]

    def remove_nodes(self, names: list[str]) -> None:
        """Mark nodes for removal: the next replan drains them.

        Dirty-mask delta: exactly the partitions holding a copy on a
        removed node — a vectorized scan of ``current`` against the
        removed ids (microseconds at the north-star scale)."""
        self._removed.update(names)
        self._problem.valid_node[:] = [
            n not in self._removed for n in self._problem.nodes]
        ids = [self._node_index[n] for n in names if n in self._node_index]
        if ids:
            arr = np.asarray(ids, np.int32)
            mask = np.isin(self.current, arr).any(axis=(1, 2))
            if self.proposed is not None:
                # The pending proposal may have moved load ONTO the
                # victim: if it is adopted, those rows are the delta.
                mask |= np.isin(self.proposed, arr).any(axis=(1, 2))
            self._mark_dirty(mask)

    def set_node_weights(self, node_weights: dict[str, int]) -> None:
        """Re-weight nodes in place (capacity shares + score divisors).

        A model/weight change re-prices every node, so the warm carry is
        invalidated — the next replan solves cold and rebuilds it."""
        self.opts.node_weights = dict(node_weights)
        prob = self._problem
        for ni, n in enumerate(prob.nodes):
            prob.node_weights[ni] = node_weights.get(n, 1)
        self.invalidate_carry()

    def set_partition_weights(self, weights: dict[str, int]) -> None:
        """Re-weight partitions in place (hot-tenant drift: the
        continuous-rebalance controller's weight-delta path).  Missing
        names fall back to weight 1, mirroring the encoder's default.

        A weight change re-prices every partition's bids — not just the
        renamed ones — so the warm carry is invalidated and the next
        replan solves cold and rebuilds it (same contract as
        ``set_node_weights``)."""
        self.opts.partition_weights = dict(weights)
        prob = self._problem
        for pi, name in enumerate(prob.partitions):
            prob.partition_weights[pi] = weights.get(name, 1)
        self.invalidate_carry()

    def invalidate_carry(self) -> None:
        """Drop the warm-start state: the next replan() solves cold.

        Called automatically on load_map / weight changes; call it
        manually after mutating ``current``, ``opts``, or the problem
        arrays directly."""
        self._carries.invalidate(self._ckey)

    # -- warm-start internals (thin views over the CarryCache entry) ---------

    @property
    def _carry(self) -> Optional["SolveCarry"]:
        """The live warm carry (None = the next replan solves cold).
        Read-only view for callers/tests; the lifecycle lives in
        plan.carry.CarryCache."""
        e = self._carries.peek(self._ckey)
        return e.carry if e is not None else None

    def _mark_dirty(self, mask: NPArray) -> None:
        """Record delta marks.  Marks land in the post-proposal mask
        while a proposal is pending: the pending solve did not see this
        delta, so apply() must carry these forward instead of clearing
        them with the absorbed ones."""
        self._carries.mark_dirty(self._ckey, mask,
                                 pending=self.proposed is not None)

    def _pad_carry_nodes(self) -> None:
        """Grow the carries' [N]-shaped arrays after add_nodes: fresh
        nodes hold nothing, so zero-fill keeps them exact.  BOTH the
        live carry and the pending one (a delta can land between
        replan() and apply(), and apply() will promote the pending
        carry into the grown problem)."""
        self._carries.pad_nodes(self._ckey, self._problem.N)

    def _mark_dirty_for_added(self, new_ids: list[int]) -> None:
        """Adds can improve a partition's attainable rule tier: any
        partition holding a copy in a hierarchy group the new node
        joins may now prefer the new node for rule reasons, so it must
        be allowed to re-bid under a warm repair."""
        prob = self._problem
        if not new_ids or not prob.rules or not self.current.size:
            return
        assigns = [self.current]
        if self.proposed is not None:
            assigns.append(self.proposed)
        levels = {inc for rl in prob.rules.values() for (inc, _exc) in rl}
        for a_arr in assigns:
            held = a_arr >= 0
            cur = np.clip(a_arr, 0, prob.N - 1)
            for lv in levels:
                for a in new_ids:
                    if not prob.gid_valid[lv, a]:
                        continue
                    g = prob.gids[lv, a]
                    self._mark_dirty(
                        ((prob.gids[lv][cur] == g) & held).any(axis=(1, 2)))

    def _capacity_shrank(self, carry: "SolveCarry",
                         dirty: NPArray) -> bool:
        """Host-side warm-decline precheck, delegated to
        plan.carry.capacity_shrank (the extracted spelling the fleet
        tier shares); the session contributes its mesh shard count for
        the quantization allowance."""
        prob = self._problem
        shards = 1
        if self.mesh is not None:
            from ..parallel.sharded import PARTITION_AXIS

            axes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            shards = axes.get(PARTITION_AXIS, 1)
        return capacity_shrank(
            np.asarray(carry.used), self.current, prob.partition_weights,
            prob.node_weights, prob.valid_node, prob.constraints, dirty,
            shards=shards)

    @property
    def nodes(self) -> list[str]:
        return list(self._problem.nodes)

    @property
    def removed_nodes(self) -> list[str]:
        return sorted(self._removed)

    # -- map edges ---------------------------------------------------------------

    def load_map(self, prev_map: PartitionMap) -> None:
        """Adopt an existing PartitionMap as the current assignment.

        Raises on placements the session cannot represent (nodes outside
        the session's node list) — silently treating a live placement as
        vacant would let the next replan double-book it.  Unmodeled states
        are dropped (the session covers modeled states only; keep the
        PartitionMap if you need unmodeled-state passthrough).
        """
        # Validate BEFORE re-encoding so a rejected map leaves the session's
        # state (problem statics included) untouched.
        unknown_parts = set(prev_map) - set(self._partition_names)
        if unknown_parts:
            raise ValueError(
                "load_map: partitions outside this session: "
                f"{sorted(unknown_parts)[:8]}")
        modeled = set(self._problem.states)
        known = self._node_index
        unknown = sorted({
            node
            for partition in prev_map.values()
            for sname, ns in partition.nodes_by_state.items()
            if sname in modeled
            for node in ns if node not in known})
        if unknown:
            raise ValueError(
                "load_map: placements on nodes outside this session "
                f"(would be silently dropped): {unknown[:8]}")
        self._reencode(prev_map=prev_map)
        self.current = self._problem.prev.copy()
        self.proposed = None
        self.invalidate_carry()  # the adopted map is a cold start

    def to_map(
        self, which: str = "current"
    ) -> tuple[PartitionMap, dict[str, list[str]]]:
        """Materialize ``current`` or ``proposed`` as (PartitionMap,
        warnings); the session's checkpoint format, like the reference's
        JSON-taggable maps (api.go:30-35)."""
        if which not in ("current", "proposed"):
            raise ValueError(f"to_map: unknown which={which!r}")
        assign = self.proposed if which == "proposed" else self.current
        if assign is None:
            raise ValueError("no proposed assignment; call replan() first")
        pta = {name: Partition(name, {}) for name in self._partition_names}
        return decode_assignment(
            self._problem, assign, pta, sorted(self._removed))

    # -- the loop -------------------------------------------------------------

    def replan(self) -> NPArray:
        """Solve placement from ``current`` on device; stores and returns
        the proposed assignment (does not adopt it — see apply()).

        Incremental by default: with a valid warm carry (built by the
        previous replan, promoted by apply()) the solve is one
        carry-seeded repair sweep restricted to the delta's dirty rows —
        bit-identical to the cold fixpoint, at a fraction of the sweeps.
        Falls back to the cold solve when the carry is missing/stale,
        capacity shrank under held load, the repair leaked outside the
        dirty mask, the engine failed, or the post-solve audit found a
        violation (docs/DESIGN.md "Incremental replanning")."""
        import jax.numpy as jnp

        from . import tensor as _tensor
        from ..obs import get_recorder
        from .tensor import resolve_default_fused_score

        prob = self._problem
        rules = tuple(tuple(prob.rules.get(si, ())) for si in range(prob.S))
        constraints = tuple(int(c) for c in prob.constraints)
        if prob.P == 0 or prob.N == 0 or prob.S == 0:
            self.proposed = self.current.copy()
            return self.proposed

        rec = get_recorder()
        iters = max(int(self.opts.max_iterations), 1)
        mode = resolve_default_fused_score(prob.P, prob.N)

        # Warm attempt: consume the carry (its buffers may be donated
        # into the repair), accept only a delta-contained repair.  The
        # consume merges post-proposal marks first — this solve absorbs
        # every delta recorded so far, including any that arrived after
        # a previous (unapplied) proposal.
        carry, dirty_base = self._carries.consume(self._ckey, self.current)
        if carry is None:
            rec.count("plan.solve.carry_miss")
        assign = new_carry = None
        if carry is not None:
            dirty = effective_dirty(dirty_base, self.current,
                                    prob.constraints)
            if self._capacity_shrank(carry, dirty):
                # Grown cluster: the trim pass will displace clean
                # holders — the repair could never be accepted, so skip
                # straight to cold instead of wasting a sweep.
                rec.count("plan.solve.carry_miss")
            else:
                assign, new_carry = self._warm_solve(
                    carry, dirty, constraints, rules, mode)
                if assign is not None and self._audit_gate(prob, assign):
                    # Constraint violation in the repaired result: the
                    # warm shortcut is not trustworthy here — cold-solve.
                    rec.count("plan.solve.warm_fallback")
                    assign = new_carry = None
                if assign is not None:
                    # A hit means the replan really did cost one sweep
                    # end-to-end: counted only after every gate (device
                    # acceptance AND the audit) passed.
                    rec.count("plan.solve.carry_hit")

        if assign is None:
            if self.mesh is not None:
                from ..parallel.sharded import solve_dense_sharded

                assign, new_carry = solve_dense_sharded(
                    self.mesh, self.current, prob.partition_weights,
                    prob.node_weights, prob.valid_node, prob.stickiness,
                    prob.gids, prob.gid_valid, constraints, rules,
                    max_iterations=iters, return_carry=True)
            else:
                assign, _engine, new_carry = \
                    _tensor.solve_converged_resilient(
                        jnp.asarray(self.current),
                        jnp.asarray(prob.partition_weights),
                        jnp.asarray(prob.node_weights),
                        jnp.asarray(prob.valid_node),
                        jnp.asarray(prob.stickiness),
                        jnp.asarray(prob.gids),
                        jnp.asarray(prob.gid_valid),
                        constraints, rules, max_iterations=iters,
                        mode=mode,
                        allow_fallback=_tensor._FUSED_SCORE_DEFAULT
                        == "auto",
                        context="PlannerSession.replan",
                        return_carry=True)
        from .tensor import maybe_validate

        maybe_validate(prob, assign, self.opts.validate_assignment,
                       "PlannerSession.replan")
        self.proposed = assign
        self._carries.store_pending(self._ckey, new_carry)
        return assign

    def _warm_solve(
        self, carry: "SolveCarry", dirty: NPArray,
        constraints: "Constraints", rules: "Rules", mode: str,
    ) -> tuple[Optional[NPArray], Optional["SolveCarry"]]:
        """One warm repair attempt; (None, None) on decline/failure."""
        from . import tensor as _tensor
        from ..obs import get_recorder

        prob = self._problem
        try:
            if self.mesh is not None:
                from ..parallel.sharded import solve_dense_sharded

                return solve_dense_sharded(
                    self.mesh, self.current, prob.partition_weights,
                    prob.node_weights, prob.valid_node, prob.stickiness,
                    prob.gids, prob.gid_valid, constraints, rules,
                    dirty=dirty, carry=carry, return_carry=True,
                    warm_only=True)
            # No p_real: the warm repair must run the exact arithmetic
            # of the session's cold path (both leave total_p a
            # compile-time constant), or low-bit differences would read
            # as divergence from the cold fixpoint.
            return _tensor.solve_dense_warm(
                self.current, prob.partition_weights, prob.node_weights,
                prob.valid_node, prob.stickiness, prob.gids,
                prob.gid_valid, constraints, rules, dirty=dirty,
                carry=carry, fused_score=mode)
        except (ValueError, TypeError):
            raise  # deterministic input errors: same on the cold path
        except Exception as e:
            # Engine/runtime failure during the repair (HBM, lowering):
            # degrade to the cold resilient path, which has its own
            # engine fallback — never let the warm shortcut be the
            # reason a replan errors.
            import warnings as _warnings

            first = (str(e).splitlines() or [""])[0][:200]
            _warnings.warn(
                f"blance_tpu PlannerSession.replan: warm repair failed "
                f"({type(e).__name__}: {first}); falling back to a cold "
                f"solve", UserWarning, stacklevel=3)
            get_recorder().count("plan.solve.warm_fallback")
            return None, None

    def _audit_gate(self, prob: "DenseProblem",
                    assign: NPArray) -> bool:
        """True when the audit policy is active AND finds violations —
        the warm path's fall-back-to-cold condition.  Respects
        opts.validate_assignment exactly like maybe_validate (None =
        auto), so explicitly disabled validation also disables the
        gate."""
        from .tensor import _audit_rules_nest, _VALIDATE_AUTO_CELLS, \
            check_assignment

        validate = self.opts.validate_assignment
        if validate is None:
            validate = _audit_rules_nest(prob) or \
                prob.P * prob.N <= _VALIDATE_AUTO_CELLS
        if not validate:
            return False
        return any(check_assignment(prob, assign).values())

    def recovery_replan(self, dead_nodes: list[str]) -> NPArray:
        """Failure-aware re-entry (rebalance_async recovery rounds):
        drain ``dead_nodes`` — nodes the orchestrator quarantined mid-
        transition — and replan.  ``remove_nodes`` marks exactly the
        partitions holding a copy on a dead node dirty, so when the
        session's carry is live (the failed pass's proposal was adopted
        and its failures were confined to the dead nodes) this replan is
        the one-sweep warm repair rather than a cold fixpoint, falling
        back to cold under the usual gates.  Returns the proposed
        assignment; materialize with ``to_map("proposed")`` and adopt
        with ``apply()`` once the recovery transition lands."""
        self.remove_nodes(list(dead_nodes))
        return self.replan()

    def replan_with_moves(
        self, favor_min_nodes: bool = False
    ) -> tuple[NPArray, tuple[NPArray, NPArray, NPArray]]:
        """Fused replan: solve + move diff + decode pack in ONE donated
        device dispatch (the plan pipeline, ROADMAP item 3).

        Semantically ``replan()`` followed by ``moves(favor_min_nodes)``
        — bit-identical proposed assignment AND move arrays, pinned by
        tests — but the steady-state delta replan pays a single device
        round trip: the warm one-sweep repair, the prev-vs-next diff and
        the decode pack run inside one jitted program with the previous
        assignment and consumed carry donated into the outputs.  Falls
        back exactly like replan() (cold pipeline on carry miss/decline/
        audit violation; staged solve on engine failure).  Stores
        ``proposed`` and the pending carry like replan()."""
        from ..obs import get_recorder
        from .tensor import maybe_validate, resolve_default_fused_score

        prob = self._problem
        rules = tuple(tuple(prob.rules.get(si, ())) for si in range(prob.S))
        constraints = tuple(int(c) for c in prob.constraints)
        if prob.P == 0 or prob.N == 0 or prob.S == 0:
            self.proposed = self.current.copy()
            L = 2 * prob.S * max(self.current.shape[2], 1)
            empty = np.full((prob.P, L), -1, np.int32)
            return self.proposed, (empty, empty.copy(), empty.copy())

        rec = get_recorder()
        rec.count("plan.pipeline.calls")
        iters = max(int(self.opts.max_iterations), 1)
        mode = resolve_default_fused_score(prob.P, prob.N)

        carry, dirty_base = self._carries.consume(self._ckey, self.current)
        if carry is None:
            rec.count("plan.solve.carry_miss")
        result = None
        if carry is not None:
            from .carry import effective_dirty

            dirty = effective_dirty(dirty_base, self.current,
                                    prob.constraints)
            if self._capacity_shrank(carry, dirty):
                rec.count("plan.solve.carry_miss")
            else:
                result = self._warm_pipeline(
                    carry, dirty, constraints, rules, mode,
                    favor_min_nodes)
                if result is not None and \
                        self._audit_gate(prob, result[0]):
                    rec.count("plan.solve.warm_fallback")
                    result = None
                if result is not None:
                    rec.count("plan.solve.carry_hit")
                    rec.count("plan.pipeline.warm")

        if result is None:
            result = self._cold_pipeline(constraints, rules, iters, mode,
                                         favor_min_nodes)
        assign, new_carry, darrs = result
        maybe_validate(prob, assign, self.opts.validate_assignment,
                       "PlannerSession.replan_with_moves")
        self.proposed = assign
        self._carries.store_pending(self._ckey, new_carry)
        return assign, darrs

    def _warm_pipeline(
        self, carry: "SolveCarry", dirty: NPArray,
        constraints: "Constraints", rules: "Rules", mode: str,
        favor_min_nodes: bool,
    ) -> Optional[tuple[Any, ...]]:
        """One warm pipeline dispatch; None on decline/failure.
        Returns (assign, next_carry, (d_nodes, d_states, d_ops))."""
        import jax.numpy as jnp

        from . import tensor as _tensor
        from ..obs import device as _obs_device
        from ..obs import get_recorder
        from .tensor import Constraints, Rules, SolveCarry

        prob = self._problem
        rec = get_recorder()
        dirty_np = np.asarray(dirty, bool)
        try:
            if self.mesh is not None:
                # solve_pipeline_sharded records dirty_fraction itself
                # (like solve_dense_sharded on the staged path).
                from ..parallel.sharded import solve_pipeline_sharded

                return solve_pipeline_sharded(
                    self.mesh, self.current, prob.partition_weights,
                    prob.node_weights, prob.valid_node, prob.stickiness,
                    prob.gids, prob.gid_valid, constraints, rules,
                    favor_min_nodes=favor_min_nodes, dirty=dirty_np,
                    carry=carry, warm_only=True)
            rec.observe("plan.solve.dirty_fraction",
                        float(dirty_np.mean()) if dirty_np.size else 0.0)
            t0 = rec.now()
            with rec.span("plan.pipeline.dispatch", warm=True,
                          engine=mode), \
                    _obs_device.entry("pipeline.warm"):
                (out, prices, used, ok, d_nodes, d_states, d_ops,
                 _packed, _counts) = _tensor._pipeline_warm_donating(
                    jnp.asarray(self.current),
                    jnp.asarray(prob.partition_weights),
                    jnp.asarray(prob.node_weights),
                    jnp.asarray(prob.valid_node),
                    jnp.asarray(prob.stickiness),
                    jnp.asarray(prob.gids),
                    jnp.asarray(prob.gid_valid),
                    jnp.asarray(dirty_np),
                    jnp.asarray(carry.used),
                    constraints, rules, fused_score=mode,
                    favor_min_nodes=favor_min_nodes)
                accepted = bool(ok)
            rec.observe("plan.pipeline.dispatch_s", rec.now() - t0)
            if not accepted:
                rec.count("plan.solve.warm_fallback")
                rec.count("plan.solve.sweeps", 1)  # the spent repair
                return None
            _tensor._record_sweeps(1)
            rec.set_attr("warm", True)
            return (np.asarray(out),
                    SolveCarry(prices=prices, assign=out, used=used),
                    (np.asarray(d_nodes), np.asarray(d_states),
                     np.asarray(d_ops)))
        except (ValueError, TypeError):
            raise  # deterministic input errors: same on the cold path
        except Exception as e:
            import warnings as _warnings

            first = (str(e).splitlines() or [""])[0][:200]
            _warnings.warn(
                f"blance_tpu PlannerSession.replan_with_moves: warm "
                f"pipeline failed ({type(e).__name__}: {first}); falling "
                f"back to a cold solve", UserWarning, stacklevel=3)
            rec.count("plan.solve.warm_fallback")
            return None

    def _cold_pipeline(
        self, constraints: "Constraints", rules: "Rules", iters: int,
        mode: str, favor_min_nodes: bool,
    ) -> tuple[Any, ...]:
        """Cold pipeline dispatch (mesh-sharded when the session has a
        mesh); returns (assign, next_carry, diff arrays)."""
        from . import tensor as _tensor

        prob = self._problem
        if self.mesh is not None:
            from ..parallel.sharded import solve_pipeline_sharded

            return solve_pipeline_sharded(
                self.mesh, self.current, prob.partition_weights,
                prob.node_weights, prob.valid_node, prob.stickiness,
                prob.gids, prob.gid_valid, constraints, rules,
                max_iterations=iters, favor_min_nodes=favor_min_nodes)
        assign, _sweeps, new_carry, darrs, _packed = \
            _tensor._dispatch_pipeline_cold(
                self.current, prob.partition_weights, prob.node_weights,
                prob.valid_node, prob.stickiness, prob.gids,
                prob.gid_valid, constraints, rules, max_iterations=iters,
                fused_score=mode,
                allow_fallback=_tensor._FUSED_SCORE_DEFAULT == "auto",
                favor_min_nodes=favor_min_nodes, entry="pipeline.cold")
        return assign, new_carry, darrs

    def moves(
        self, favor_min_nodes: bool = False
    ) -> tuple[NPArray, NPArray, NPArray]:
        """On-device diff current -> proposed: (nodes, states, ops) as
        [P, L] arrays with -1 padding (see moves/batch.py for codes).
        Row i is partition ``self.problem.partitions[i]``."""
        import jax.numpy as jnp

        from ..moves.batch import diff_assignments

        if self.proposed is None:
            raise ValueError("no proposed assignment; call replan() first")
        r = max(self.current.shape[2], self.proposed.shape[2])

        def widen(a):
            if a.shape[2] == r:
                return a
            pad = np.full(a.shape[:2] + (r - a.shape[2],), -1, np.int32)
            return np.concatenate([a, pad], axis=2)

        d_nodes, d_states, d_ops = diff_assignments(
            jnp.asarray(widen(self.current)),
            jnp.asarray(widen(self.proposed)),
            favor_min_nodes=favor_min_nodes)
        return np.asarray(d_nodes), np.asarray(d_states), np.asarray(d_ops)

    def apply(self) -> None:
        """Adopt the proposed assignment as current (the app moved the
        data); removed nodes no longer hold anything after this.

        Also promotes the solve's carry to the session's warm-start
        state and retires the dirty marks the adopted solve absorbed;
        marks from deltas recorded AFTER that solve ran (held in the
        post-proposal mask) carry forward, so the next warm replan still
        re-bids exactly the partitions those deltas can move."""
        if self.proposed is None:
            raise ValueError("no proposed assignment; call replan() first")
        self.current = self.proposed
        self.proposed = None
        self._carries.promote(self._ckey, self.current)
