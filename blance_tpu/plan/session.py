"""Long-lived dense planning sessions.

``plan_next_map`` (plan/api.py) is a pure function of PartitionMaps, like
the reference's PlanNextMapEx (reference api.go:147-157) — every call pays
the string<->id marshalling toll at the edges.  At 100k partitions that
toll dominates wall-clock (BASELINE.md), and a real cluster rebalances the
*same* index repeatedly: same partitions, same states, a slowly-changing
node set.

``PlannerSession`` amortizes everything that doesn't change: interning
tables, model/rule encoding, hierarchy group ids, the compiled solver, and
the current dense assignment.  The steady-state loop is

    session.remove_nodes(["n7"])       # cluster delta, O(delta)
    proposed = session.replan()        # on-device solve, no marshalling
    nodes, states, ops = session.moves()   # on-device diff vs current
    session.apply()                    # adopt the proposed assignment

with PartitionMaps materializing only at the edges (``load_map`` /
``to_map``) for checkpoints and app hand-off.  An optional mesh runs the
solve sharded over the partition axis (parallel/sharded.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.encode import decode_assignment, encode_problem
from ..core.types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)

__all__ = ["PlannerSession"]


class PlannerSession:
    """Stateful dense planner for one logical index.

    Parameters
    ----------
    model: state name -> PartitionModelState (priorities + constraints).
    nodes: every node that may ever appear, in tie-break order (node order
        is the planner's deterministic tie-break, reference plan.go:617-628).
    partitions: partition names; placement order is the planner's canonical
        name sort.
    opts: planner knobs; weights/stickiness/hierarchy are encoded once.
    mesh: optional jax.sharding.Mesh — shards the solve over partitions.
    """

    def __init__(
        self,
        model: PartitionModel,
        nodes: list[str],
        partitions: list[str],
        opts: Optional[PlanOptions] = None,
        mesh=None,
    ) -> None:
        self.model = model
        self.opts = opts or PlanOptions()
        self.mesh = mesh
        self._removed: set[str] = set()
        self._nodes = list(nodes)
        self._partition_names = list(partitions)
        self._reencode(prev_map={})
        # current/proposed dense assignments [P, S, R] int32, -1 = empty.
        self.current = self._problem.prev.copy()
        self.proposed: Optional[np.ndarray] = None

    # -- encoding ------------------------------------------------------------

    def _reencode(self, prev_map: PartitionMap) -> None:
        """(Re)build the dense problem statics; prev_map seeds ``prev``."""
        pta = {name: Partition(name, {}) for name in self._partition_names}
        self._problem = encode_problem(
            prev_map, pta, self._nodes, sorted(self._removed),
            self.model, self.opts)
        self._node_index = {n: i for i, n in enumerate(self._problem.nodes)}

    @property
    def problem(self):
        """The encoded statics (DenseProblem).

        ``problem.prev`` is only the encode-time seed (all -1, or the last
        load_map snapshot) — it goes stale after add_nodes()/replan()/
        apply().  ``self.current`` is the authoritative live assignment."""
        return self._problem

    # -- cluster membership ----------------------------------------------------

    def add_nodes(self, names: list[str]) -> None:
        """Add nodes (new capacity attracts load on the next replan)."""
        grew = False
        for n in names:
            self._removed.discard(n)
            if n not in self._node_index:
                self._nodes.append(n)
                self._node_index[n] = len(self._nodes) - 1
                grew = True
        if grew:
            current = self.current
            self._reencode(prev_map={})
            # Node ids are append-only, so the old assignment is still valid.
            r_new = self._problem.R
            if r_new > current.shape[2]:
                pad = np.full(
                    current.shape[:2] + (r_new - current.shape[2],),
                    -1, np.int32)
                current = np.concatenate([current, pad], axis=2)
            self.current = current
        else:
            self._problem.valid_node[:] = [
                n not in self._removed for n in self._problem.nodes]

    def remove_nodes(self, names: list[str]) -> None:
        """Mark nodes for removal: the next replan drains them."""
        self._removed.update(names)
        self._problem.valid_node[:] = [
            n not in self._removed for n in self._problem.nodes]

    @property
    def nodes(self) -> list[str]:
        return list(self._problem.nodes)

    @property
    def removed_nodes(self) -> list[str]:
        return sorted(self._removed)

    # -- map edges ---------------------------------------------------------------

    def load_map(self, prev_map: PartitionMap) -> None:
        """Adopt an existing PartitionMap as the current assignment.

        Raises on placements the session cannot represent (nodes outside
        the session's node list) — silently treating a live placement as
        vacant would let the next replan double-book it.  Unmodeled states
        are dropped (the session covers modeled states only; keep the
        PartitionMap if you need unmodeled-state passthrough).
        """
        # Validate BEFORE re-encoding so a rejected map leaves the session's
        # state (problem statics included) untouched.
        unknown_parts = set(prev_map) - set(self._partition_names)
        if unknown_parts:
            raise ValueError(
                "load_map: partitions outside this session: "
                f"{sorted(unknown_parts)[:8]}")
        modeled = set(self._problem.states)
        known = self._node_index
        unknown = sorted({
            node
            for partition in prev_map.values()
            for sname, ns in partition.nodes_by_state.items()
            if sname in modeled
            for node in ns if node not in known})
        if unknown:
            raise ValueError(
                "load_map: placements on nodes outside this session "
                f"(would be silently dropped): {unknown[:8]}")
        self._reencode(prev_map=prev_map)
        self.current = self._problem.prev.copy()
        self.proposed = None

    def to_map(
        self, which: str = "current"
    ) -> tuple[PartitionMap, dict[str, list[str]]]:
        """Materialize ``current`` or ``proposed`` as (PartitionMap,
        warnings); the session's checkpoint format, like the reference's
        JSON-taggable maps (api.go:30-35)."""
        if which not in ("current", "proposed"):
            raise ValueError(f"to_map: unknown which={which!r}")
        assign = self.proposed if which == "proposed" else self.current
        if assign is None:
            raise ValueError("no proposed assignment; call replan() first")
        pta = {name: Partition(name, {}) for name in self._partition_names}
        return decode_assignment(
            self._problem, assign, pta, sorted(self._removed))

    # -- the loop -------------------------------------------------------------

    def replan(self) -> np.ndarray:
        """Solve placement from ``current`` on device; stores and returns
        the proposed assignment (does not adopt it — see apply())."""
        import jax.numpy as jnp

        from . import tensor as _tensor
        from .tensor import resolve_default_fused_score

        prob = self._problem
        rules = tuple(tuple(prob.rules.get(si, ())) for si in range(prob.S))
        constraints = tuple(int(c) for c in prob.constraints)
        if prob.P == 0 or prob.N == 0 or prob.S == 0:
            self.proposed = self.current.copy()
            return self.proposed

        iters = max(int(self.opts.max_iterations), 1)
        if self.mesh is not None:
            from ..parallel.sharded import solve_dense_sharded

            assign = solve_dense_sharded(
                self.mesh, self.current, prob.partition_weights,
                prob.node_weights, prob.valid_node, prob.stickiness,
                prob.gids, prob.gid_valid, constraints, rules,
                max_iterations=iters)
        else:
            assign, _engine = _tensor.solve_converged_resilient(
                jnp.asarray(self.current),
                jnp.asarray(prob.partition_weights),
                jnp.asarray(prob.node_weights),
                jnp.asarray(prob.valid_node),
                jnp.asarray(prob.stickiness),
                jnp.asarray(prob.gids),
                jnp.asarray(prob.gid_valid),
                constraints, rules, max_iterations=iters,
                mode=resolve_default_fused_score(prob.P, prob.N),
                allow_fallback=_tensor._FUSED_SCORE_DEFAULT == "auto",
                context="PlannerSession.replan")
        from .tensor import maybe_validate

        maybe_validate(prob, assign, self.opts.validate_assignment,
                       "PlannerSession.replan")
        self.proposed = assign
        return assign

    def moves(
        self, favor_min_nodes: bool = False
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """On-device diff current -> proposed: (nodes, states, ops) as
        [P, L] arrays with -1 padding (see moves/batch.py for codes).
        Row i is partition ``self.problem.partitions[i]``."""
        import jax.numpy as jnp

        from ..moves.batch import diff_assignments

        if self.proposed is None:
            raise ValueError("no proposed assignment; call replan() first")
        r = max(self.current.shape[2], self.proposed.shape[2])

        def widen(a):
            if a.shape[2] == r:
                return a
            pad = np.full(a.shape[:2] + (r - a.shape[2],), -1, np.int32)
            return np.concatenate([a, pad], axis=2)

        d_nodes, d_states, d_ops = diff_assignments(
            jnp.asarray(widen(self.current)),
            jnp.asarray(widen(self.proposed)),
            favor_min_nodes=favor_min_nodes)
        return np.asarray(d_nodes), np.asarray(d_states), np.asarray(d_ops)

    def apply(self) -> None:
        """Adopt the proposed assignment as current (the app moved the
        data); removed nodes no longer hold anything after this."""
        if self.proposed is None:
            raise ValueError("no proposed assignment; call replan() first")
        self.current = self.proposed
        self.proposed = None
