"""Asyncio plan service: the fleet solver's coalescing front door.

plan/fleet.py turns B same-class tenant solves into one device dispatch;
this module supplies the B.  An asyncio service accepts per-tenant plan
requests, coalesces everything that arrives within a tunable admission
window into one fleet batch, solves it off-loop (a single-worker
executor serializes device access while the event loop keeps admitting),
and resolves each request's future with its tenant's result:

    service = PlanService(admission_window_s=0.002)
    await service.start()
    result = await service.submit(TenantProblem(...))   # FleetResult
    await service.stop()

Design points:

- **Admission window**: the dispatcher takes the first queued request,
  then keeps admitting until ``admission_window_s`` elapses (or
  ``max_batch`` fills).  A longer window buys bigger batches (fewer
  dispatches per solve) at the cost of per-request latency — the
  ``fleet.admission_latency_s`` histogram vs ``fleet.batch_tenants`` is
  the tuning signal (docs/FLEET.md).  While a batch is solving, the
  next window's requests queue up, so a saturated service pipelines
  admission against device compute.
- **Backpressure**: the request queue is bounded (``max_pending``);
  ``submit`` awaits queue space, so producers slow to the service's
  throughput instead of growing an unbounded backlog.
- **Per-tenant warm carries**: results are adopted into a keyed
  :class:`plan.carry.CarryCache` (shared or service-owned, LRU byte
  budget).  A request whose ``prev`` equals the tenant's cached
  assignment — and that states its delta via ``dirty`` — rides the
  one-sweep warm repair, bit-identically to a per-tenant
  ``PlannerSession`` doing the same (the cache consume/store lifecycle
  is the session's, value-matched because service callers rebuild
  arrays per request).
- **Admission fairness**: ``fair_share`` bounds one tenant's share of
  a coalescing window; over-quota requests roll to the next batch
  (oldest first) and count ``fleet.starved_admissions`` — a chatty
  tenant cannot starve its neighbors' converge cycles (docs/FLEET.md
  "Fleet of control loops").
- **Shared state** (analysis/race_lint.py SHARED_STATE): ``_closed``,
  ``_task``, the queue and the ``_deferred`` carry-over list are
  touched by ``submit``/``stop`` (the app-facing surface) and the
  dispatcher task; every mutation sits in a single no-await window,
  and the carry cache is written ONLY from the dispatcher task, so
  cache state cannot interleave mid-batch.
"""

from __future__ import annotations

import asyncio
import dataclasses
import warnings
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from functools import partial
from typing import TYPE_CHECKING, Optional

from ..obs import get_recorder
from ..utils.hostclock import perf_now
from ..obs.tracectx import RequestTimeline, TraceContext, TraceIdSource
from .carry import CarryCache
from .fleet import FleetResult, TenantProblem, solve_fleet, validate_tenant

if TYPE_CHECKING:  # annotation-only
    from jax.sharding import Mesh

    from ..obs import Recorder

__all__ = ["PlanService", "PlanServiceClosed"]


class PlanServiceClosed(RuntimeError):
    """The service is stopped (or stopped while the request waited)."""


@dataclass
class _Request:
    problem: TenantProblem
    future: "asyncio.Future[FleetResult]"
    t_submit: float
    # End-to-end trace: minted at submit, marks appended as the request
    # crosses each stage, recorded (spans + segment histograms) at
    # resolution.  docs/OBSERVABILITY.md "Request decomposition".
    timeline: Optional[RequestTimeline] = None


_STOP = object()  # queue sentinel: drain and exit


class PlanService:
    """Coalescing asyncio front door over :func:`plan.fleet.solve_fleet`.

    Parameters
    ----------
    admission_window_s: how long the dispatcher keeps admitting after
        the first request of a batch (0 = batch only what is already
        queued — lowest latency, smallest batches).
    max_pending: bounded request queue length; ``submit`` awaiting
        space IS the backpressure.
    max_batch: hard cap on tenants per fleet batch.
    mesh: optional 1-D device mesh; fleet batches shard their batch
        axis over it (plan/fleet.py).
    carry_cache: shared per-tenant warm-carry store; by default the
        service owns one bounded to ``carry_bytes`` and
        ``carry_entries`` keys (churning tenant keys must not grow the
        entry table forever).
    fair_share: bounded per-tenant share of one coalescing window — at
        most this many requests per tenant key land in a batch; the
        excess rolls to the NEXT batch (admitted first there, oldest
        first, quota applied again).  Cross-tenant admission fairness
        for the fleet-of-loops tier: a chatty tenant churning deltas
        cannot fill a window and starve its neighbors' converge
        cycles.  Every deferral counts ``fleet.starved_admissions`` so
        starvation is observable, and deferral never changes a result —
        the deferred request solves in a later batch with the same
        inputs (docs/FLEET.md).  None (default) disables the quota.
    batch_floor: pad every dispatch's batch axis up to at least this
        many elements before bucketing.  Small coalesced batches wander
        ``B = 1..N`` where the batch buckets step by 1, so a fleet of
        control loops would compile one program per size; the floor
        trades a few inert pad elements for ONE compiled program per
        bucket class (the fleet controller defaults it to 16; 1 here =
        the exact pre-floor behavior).
    """

    def __init__(
        self,
        *,
        admission_window_s: float = 0.002,
        max_pending: int = 256,
        max_batch: int = 1024,
        mesh: Optional["Mesh"] = None,
        carry_cache: Optional[CarryCache] = None,
        carry_bytes: Optional[int] = 64 << 20,
        carry_entries: Optional[int] = 16384,
        max_iterations: int = 10,
        recorder: Optional["Recorder"] = None,
        inline_solve: bool = False,
        fair_share: Optional[int] = None,
        batch_floor: int = 1,
    ) -> None:
        if max_pending <= 0 or max_batch <= 0:
            raise ValueError("max_pending and max_batch must be positive")
        if fair_share is not None and fair_share < 1:
            raise ValueError(f"fair_share must be >= 1, got {fair_share}")
        self.admission_window_s = float(admission_window_s)
        self.max_batch = int(max_batch)
        self.fair_share = fair_share
        # Pad every dispatch's batch axis up to at least this many
        # elements before bucketing (plan/fleet.py _dispatch): a fleet
        # of control loops whose coalesced sizes wander 1..N trades a
        # few inert pad elements for ONE compiled program per class
        # instead of one per batch size (docs/FLEET.md).
        self.batch_floor = int(batch_floor)
        self.mesh = mesh
        self.max_iterations = int(max_iterations)
        # inline_solve runs the fleet batch on the dispatcher coroutine
        # instead of a worker thread: admission no longer pipelines
        # against device compute (don't use it in production), but the
        # service becomes loop-only — which is what lets the PR-5
        # DeterministicLoop drive it, making the whole request-tracing
        # plane (segments, trace ids, histograms) a pure function of
        # the seeded schedule.
        self.inline_solve = bool(inline_solve)
        self._rec = recorder if recorder is not None else get_recorder()
        self._trace_ids = TraceIdSource()
        self.carry_cache = carry_cache if carry_cache is not None \
            else CarryCache(max_bytes=carry_bytes,
                            max_entries=carry_entries,
                            recorder=self._rec)
        # Cumulative HOST wall-clock seconds spent inside the fleet
        # solve (single writer: the solve runs on the dispatcher
        # coroutine or the one-thread executor).  perf_counter time,
        # not the recorder clock — the bench phase-split's "device"
        # share (fleet.dispatch_s is virtual under DeterministicLoop).
        self.host_solve_s = 0.0
        self._queue: "asyncio.Queue[object]" = \
            asyncio.Queue(maxsize=max_pending)
        # Over-quota requests rolled out of a coalescing window by the
        # fairness bound; dispatcher-task-owned (admitted, oldest
        # first, at the head of the next window).
        self._deferred: list[_Request] = []
        self._task: Optional["asyncio.Task[None]"] = None
        self._closed = False
        self._executor: Optional[ThreadPoolExecutor] = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        """Spawn the dispatcher task (idempotent)."""
        if self._closed:
            raise PlanServiceClosed("PlanService is stopped")
        if self._task is not None:
            return
        if not self.inline_solve:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="plan-fleet")
        task = asyncio.get_running_loop().create_task(
            self._run(), name="PlanService._run")
        task.add_done_callback(self._on_run_done)
        self._task = task

    async def stop(self) -> None:
        """Stop admitting, finish the in-flight batch, fail the rest.

        Requests still queued (or arriving concurrently with the stop)
        get :class:`PlanServiceClosed`; the dispatcher exits after the
        sentinel drains.  Idempotent by construction — and still
        performs the cleanup half (drain, executor shutdown) when the
        dispatcher already died and its done-callback flipped
        ``_closed``, so a crashed service never leaks its worker
        thread."""
        self._closed = True
        if self._task is not None and not self._task.done():
            await self._queue.put(_STOP)
        task = self._task
        if task is not None:
            # A crashed dispatcher's exception was already surfaced by
            # _on_run_done; gather(return_exceptions=True) awaits the
            # exit without re-raising it out of cleanup.
            await asyncio.gather(task, return_exceptions=True)
        self._task = None
        self._drain_pending()
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    def _drain_pending(self) -> None:
        """Fail every request still queued (single no-await window).

        A drained stop sentinel is re-queued: submit()'s post-put
        closed-check may drain concurrently with stop(), and stealing
        the sentinel would strand stop() awaiting a dispatcher that
        never sees it."""
        deferred, self._deferred = self._deferred, []
        for req in deferred:
            if not req.future.done():
                req.future.set_exception(
                    PlanServiceClosed("PlanService stopped"))
        stops = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if req is _STOP:
                stops += 1
                continue
            assert isinstance(req, _Request)
            if not req.future.done():
                req.future.set_exception(
                    PlanServiceClosed("PlanService stopped"))
        if stops:
            try:
                self._queue.put_nowait(_STOP)
            except asyncio.QueueFull:
                # Unreachable today (the drain runs to QueueEmpty in one
                # no-await window), and even a lost sentinel cannot wedge
                # the dispatcher: _run's _closed check below is the
                # second exit.
                pass

    def _on_run_done(self, task: "asyncio.Task[None]") -> None:
        """Dispatcher exit observer: a crashed dispatcher must neither
        vanish silently (the ASY101 class) nor strand queued waiters."""
        if task.cancelled():
            exc: Optional[BaseException] = None
        else:
            exc = task.exception()
        if exc is None:
            return
        self._rec.count("fleet.dispatcher_crashes")
        warnings.warn(
            f"blance_tpu PlanService dispatcher died: "
            f"{type(exc).__name__}: {exc}", UserWarning)
        self._closed = True
        self._drain_pending()

    # -- the app-facing surface ----------------------------------------------

    async def submit(self, problem: TenantProblem,
                     ctx: Optional[TraceContext] = None) -> FleetResult:
        """Plan one tenant; resolves when its batch lands.

        Awaiting queue space is the backpressure contract; the result
        is bit-identical to solving the tenant alone on the single-
        problem path (plan/fleet.py's guarantee).

        A :class:`TraceContext` is minted here (or passed in by a
        caller propagating a wider trace) and rides the request end to
        end: at resolution the request's latency is recorded as one
        ``fleet.request`` span, one span per lifecycle segment, and
        ``fleet.request_segment_s{segment=...}`` histogram samples —
        the segments tile [submit, resolve] exactly, so their sum IS
        the end-to-end latency."""
        if self._closed or self._task is None:
            raise PlanServiceClosed(
                "PlanService is not running (call start(), not stopped)")
        rec = self._rec
        rec.count("fleet.requests")
        fut: "asyncio.Future[FleetResult]" = \
            asyncio.get_running_loop().create_future()
        t_submit = rec.now()
        timeline = RequestTimeline(
            ctx if ctx is not None else self._trace_ids.mint(), t_submit)
        await self._queue.put(_Request(problem, fut, t_submit, timeline))
        if self._closed:
            # The service stopped (or its dispatcher died) while this
            # submit was blocked on a full queue: the crash-path drain
            # may already have run, so our just-enqueued request could
            # otherwise sit in a queue nobody reads — drain it (and any
            # neighbors) into PlanServiceClosed instead of hanging.
            self._drain_pending()
        rec.set_gauge("fleet.queue_depth", float(self._queue.qsize()))
        return await fut

    # -- the dispatcher task -------------------------------------------------

    def _over_quota(self, key: str, counts: dict[str, int]) -> bool:
        return self.fair_share is not None and \
            counts.get(key, 0) >= self.fair_share

    def _defer(self, req: _Request) -> None:
        """Roll one over-quota request to the next window (sync window;
        the starved counter is the starvation observable — one count
        per deferral event, so a request stuck behind a chatty tenant
        for several windows counts several times)."""
        self._deferred.append(req)
        self._rec.count("fleet.starved_admissions")

    async def _admit_batch(self, first: _Request) -> tuple[
            list[_Request], bool]:
        """Coalesce requests for one fleet batch: deferred carry-overs
        from prior windows first (oldest first), then everything
        already queued plus whatever arrives within the admission
        window — each admission subject to the per-tenant
        ``fair_share`` quota.  Returns (batch, stop_seen)."""
        loop = asyncio.get_running_loop()
        batch = [first]
        counts = {first.problem.key: 1}
        carried, self._deferred = self._deferred, []
        for i, req in enumerate(carried):
            if len(batch) >= self.max_batch:
                # Plain capacity pressure, not starvation: the rest of
                # the carry-overs roll forward WITHOUT counting the
                # starved metric (it measures fair-share deferrals
                # only — docs/OBSERVABILITY.md).
                self._deferred.extend(carried[i:])
                break
            if self._over_quota(req.problem.key, counts):
                self._defer(req)
            else:
                counts[req.problem.key] = \
                    counts.get(req.problem.key, 0) + 1
                batch.append(req)
        deadline = loop.time() + self.admission_window_s
        while len(batch) < self.max_batch:
            timeout = deadline - loop.time()
            if timeout <= 0:
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
            else:
                try:
                    nxt = await asyncio.wait_for(
                        self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
            if nxt is _STOP:
                return batch, True
            assert isinstance(nxt, _Request)
            if nxt.timeline is not None:
                nxt.timeline.mark("admission", self._rec.now())
            if self._over_quota(nxt.problem.key, counts):
                self._defer(nxt)
                continue
            counts[nxt.problem.key] = counts.get(nxt.problem.key, 0) + 1
            batch.append(nxt)
        return batch, False

    def _with_cached_carry(self, t: TenantProblem) -> TenantProblem:
        """Validate the request and attach the tenant's cached warm
        carry when it is warm-eligible: an explicit carry passes
        through untouched; otherwise a cached carry is consumed and
        used iff it matches the request's ``prev`` by value AND the
        request states its delta (``dirty``).  Cold requests count a
        carry miss, mirroring PlannerSession.replan's accounting.

        Validation runs HERE (per request, inside the dispatcher's
        fail-alone guard) rather than only inside solve_fleet, so one
        tenant's bad arrays fail that request alone — never its
        co-batched neighbors."""
        validate_tenant(t)
        if t.carry is not None:
            return t
        carry, cached_dirty = self.carry_cache.consume(
            t.key, t.prev, match="equal")
        if carry is None or t.dirty is None:
            self._rec.count("plan.solve.carry_miss")
            return t
        return dataclasses.replace(
            t, carry=carry, dirty=t.dirty | cached_dirty)

    def _solve_batch(self, problems: list[TenantProblem],
                     trace_ids: dict[str, str]) -> tuple[
                         float, float, list[FleetResult]]:
        """The executor-side (or inline) solve, stamped on the
        recorder's clock: (t_solve_start, t_solve_end, results).  The
        stamps are what split a request's ``executor_queue`` segment
        (batch closed → solver started) from its ``device`` segment."""
        rec = self._rec
        t_start = rec.now()
        w0 = perf_now()
        results = solve_fleet(
            problems, mesh=self.mesh,
            max_iterations=self.max_iterations, recorder=rec,
            trace_ids=trace_ids, batch_floor=self.batch_floor)
        self.host_solve_s += perf_now() - w0
        return t_start, rec.now(), results

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        rec = self._rec
        while True:
            if self._deferred:
                # Deferred carry-overs open the next window immediately
                # — a starved tenant must not additionally wait for
                # fresh traffic.  (Their "admission" mark was stamped
                # at the original dequeue.)
                first = self._deferred.pop(0)
            else:
                nxt = await self._queue.get()
                if nxt is _STOP:
                    return
                assert isinstance(nxt, _Request)
                first = nxt
                if first.timeline is not None:
                    first.timeline.mark("admission", rec.now())
            if self._closed:
                # Second exit (belt for a lost stop sentinel): a closed
                # service must never process new batches; stop()'s
                # drain owns whatever is still queued.
                if not first.future.done():
                    first.future.set_exception(
                        PlanServiceClosed("PlanService stopped"))
                return
            batch = [first]
            stop_seen = False
            # EVERY admitted request's future resolves inside this try:
            # a failure anywhere in the batch path fails the batch's
            # futures rather than stranding their submit() callers, and
            # the service stays up for the next batch.
            try:
                batch, stop_seen = await self._admit_batch(first)
                rec.set_gauge("fleet.queue_depth",
                              float(self._queue.qsize()))
                t_batched = rec.now()
                pairs = []
                for r in batch:
                    if r.timeline is not None:
                        r.timeline.mark("coalesce", t_batched)
                    try:
                        pairs.append(
                            (r, self._with_cached_carry(r.problem)))
                    except Exception as e:
                        # A malformed request fails alone; its
                        # co-batched neighbors still solve.
                        if not r.future.done():
                            r.future.set_exception(e)
                if pairs:
                    trace_ids = {
                        r.problem.key: r.timeline.ctx.trace_id
                        for r, _ in pairs if r.timeline is not None}
                    problems = [p for _, p in pairs]
                    if self.inline_solve:
                        t_start, t_end, results = self._solve_batch(
                            problems, trace_ids)
                    else:
                        t_start, t_end, results = \
                            await loop.run_in_executor(
                                self._executor,
                                partial(self._solve_batch, problems,
                                        trace_ids))
                    for (r, _), res in zip(pairs, results):
                        # Adopt each result as the tenant's new warm
                        # state; the dispatcher is the cache's only
                        # writer, so this cannot interleave with
                        # another batch's consume.
                        if res.carry is not None:
                            # Store a PRIVATE copy as the matched
                            # "current": the result array belongs to
                            # the caller, and an in-place mutation over
                            # there must read as a cache miss, never
                            # as a still-valid warm match against a
                            # carry built from the unmutated plan.
                            self.carry_cache.store(
                                res.key, res.carry, res.assign.copy())
                        t_res = rec.now()
                        rec.observe("fleet.admission_latency_s",
                                    t_res - r.t_submit)
                        if not r.future.done():
                            r.future.set_result(res)
                        if r.timeline is not None:
                            r.timeline.mark("executor_queue", t_start)
                            r.timeline.mark("device", t_end)
                            r.timeline.mark("resolve", t_res)
                            r.timeline.record(
                                rec, tenant=res.key, warm=res.warm)
            except Exception as e:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
            if stop_seen:
                return
