"""Keyed warm-start carry store: the SolveCarry lifecycle, extracted.

``PlannerSession`` (plan/session.py) owned the whole warm-start
lifecycle inline — the carry/pending-carry pair, the dirty/dirty-post
masks, node-growth padding, invalidation, and the host-side capacity
precheck.  Fleet-scale planning (plan/fleet.py, plan/service.py) needs
that exact lifecycle *per tenant*: hundreds of independent indexes, each
carrying auction state between replans, sharing one byte-bounded store.
This module is that extraction.  ``PlannerSession`` is now a thin view
over a single-key :class:`CarryCache`; the plan service keys one shared
cache by tenant.

The lifecycle invariants are unchanged from the session (docs/DESIGN.md
"Incremental replanning"):

- a carry is valid only against the exact ``current`` assignment array
  it was built for.  Sessions enforce that by object identity (every
  adoption path replaces the array); the service — whose callers
  rebuild ``prev`` per request — checks by value (:meth:`CarryCache
  .consume` with ``match="equal"``).
- delta marks recorded while a proposal is pending land in the
  post-proposal mask: the pending solve did not absorb them, so a
  promote carries them forward instead of clearing them.
- node growth zero-pads the carries' [N]-shaped tables (fresh nodes
  hold nothing, so zero-fill keeps them exact) — BOTH the live carry
  and the pending one.
- eviction (the LRU byte budget) is always safe: a missing carry just
  means the next replan solves cold and rebuilds it, bit-identically.

Byte accounting covers the carry arrays themselves (prices + assign +
used, live and pending); the boolean dirty masks are kept even for
evicted keys — they are O(P) and the delta they record must survive the
carry's eviction (a cold solve absorbs them on the next promote).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Optional

import numpy as np

from ..core.encode import NPArray

if TYPE_CHECKING:  # annotation-only: keep jax imports lazy at runtime
    from .resident import EncodedState
    from .tensor import SolveCarry

__all__ = ["CarryCache", "CarryEntry", "EncodeCache", "pad_carry_nodes",
           "effective_dirty", "capacity_shrank"]


def pad_carry_nodes(carry: Optional["SolveCarry"],
                    n: int) -> Optional["SolveCarry"]:
    """Grow a carry's [N]-shaped tables to ``n`` nodes by zero-fill.

    Fresh nodes hold nothing, so zero columns keep the table exact; the
    prices vector is re-derived as the padded table's per-node sum (the
    same relationship :class:`plan.tensor.SolveCarry` documents).
    No-op (returns the carry unchanged) when already wide enough."""
    if carry is None:
        return None
    used = np.asarray(carry.used)
    if used.shape[1] >= n:
        return carry
    from .tensor import SolveCarry

    used = np.concatenate(
        [used, np.zeros((used.shape[0], n - used.shape[1]),
                        used.dtype)], axis=1)
    return SolveCarry(prices=used.sum(axis=0), assign=carry.assign,
                      used=used)


def effective_dirty(dirty: NPArray, current: NPArray,
                    constraints: "NPArray | tuple[int, ...]") -> NPArray:
    """The replan-time dirty mask: accumulated delta rows plus any
    partition with an unfilled constrained slot (it must bid).  Pure
    function of the mask, the live assignment and the per-state slot
    counts — the spelling PlannerSession and the fleet tier share."""
    d = dirty.copy()
    r = current.shape[2] if current.ndim == 3 else 0
    for si, c in enumerate(constraints):
        k = min(int(c), r)
        if k > 0:
            d |= (current[:, si, :k] < 0).any(axis=1)
    return d


def capacity_shrank(
    used: NPArray,  # [S, N] the carry's per-state per-node fill
    current: NPArray,  # [P, S, R] the assignment the carry matches
    partition_weights: NPArray,  # [P]
    node_weights: NPArray,  # [N]
    valid_node: NPArray,  # [N]
    constraints: "NPArray | tuple[int, ...]",  # [S]
    dirty: NPArray,  # [P] effective dirty mask
    shards: int = 1,
) -> bool:
    """True when some node's clean-row held weight exceeds its new
    per-state capacity rail — the pin pass would then trim (displace)
    holders OUTSIDE the dirty mask, so a warm repair cannot be accepted
    and the cold solve should run directly (skipping the wasted repair
    sweep).  O(N + dirty) host work off the carry.

    Grants the same quantization allowance as the device-side
    acceptance check (plan/tensor.py _warm_repair): a converged
    fixpoint legitimately overshoots the ceil'd rail by up to one
    max-weight partition per shard (the auction's first-bidder
    progress rule) and replans unchanged, so flagging that steady
    state would silently demote every replan of such a session to
    cold.  A mis-grant only costs a wasted repair sweep — the
    in-graph ripple check still falls back when the trim actually
    displaces clean holders."""
    used = np.asarray(used)
    pw = np.asarray(partition_weights)
    nw = np.asarray(node_weights)
    total_w = float(pw.sum())
    cap_w = np.where(
        np.asarray(valid_node) & (nw >= 0),
        np.maximum(nw, 1.0), 0.0).astype(np.float64)
    share = cap_w / max(cap_w.sum(), 1.0)
    r = current.shape[2]
    any_dirty = bool(dirty.any())
    allowance = shards * (float(pw.max()) if pw.size else 0.0)
    for si, c in enumerate(constraints):
        k = int(c)
        if k <= 0:
            continue
        held = used[si].astype(np.float64).copy()
        if any_dirty:
            # Dirty rows re-bid regardless; their held weight cannot
            # pin, so it does not count against the rail.
            ids = current[dirty, si, :].ravel()
            w = np.repeat(pw[dirty], r)
            m = ids >= 0
            np.subtract.at(held, ids[m], w[m])
        cap = np.ceil(k * total_w * share)
        if (held > cap + allowance + 1e-6).any():
            return True
    return False


class CarryEntry:
    """One key's warm-start state.  Attribute-for-attribute the state
    PlannerSession used to hold inline:

    - ``carry``/``current``: the live SolveCarry and the assignment
      array it matches (validity is identity against ``current`` for
      sessions, value equality for the service).
    - ``pending``: the carry of an un-adopted proposal, promoted by
      :meth:`CarryCache.promote`.
    - ``dirty``/``dirty_post``: delta marks; ``dirty_post`` holds marks
      recorded while a proposal was pending.
    """

    __slots__ = ("carry", "current", "pending", "dirty", "dirty_post",
                 "_tick")

    def __init__(self, partitions: int) -> None:
        self.carry: Optional["SolveCarry"] = None
        self.current: Optional[NPArray] = None
        self.pending: Optional["SolveCarry"] = None
        self.dirty = np.zeros(partitions, bool)
        self.dirty_post = np.zeros(partitions, bool)
        self._tick = 0

    def nbytes(self) -> int:
        total = 0
        for c in (self.carry, self.pending):
            if c is not None:
                for arr in (c.prices, c.assign, c.used):
                    total += int(np.asarray(arr).nbytes)
        return total


class CarryCache:
    """Keyed store of warm-start carries with an LRU byte budget.

    One entry per key (a tenant, or a session's private slot).  Every
    accessor bumps the key's recency; whenever the summed carry bytes
    exceed ``max_bytes``, least-recently-used keys lose their carries
    (:meth:`CarryEntry.nbytes` drops to zero) until the budget holds —
    the masks and the entry itself survive, so the delta bookkeeping
    stays correct and the next replan simply solves cold.

    ``max_entries`` bounds the KEY COUNT: beyond it, whole
    least-recently-used entries are dropped (masks included).  Without
    it a service with churning tenant keys would grow one mask-bearing
    entry per distinct key forever.  Dropping an entry is as safe as
    eviction — the key's next replan is a cold start, which absorbs
    any delta the dropped masks recorded.

    Evictions are NEVER silent: every one counts
    ``fleet.carry_evictions{reason=...}`` (``bytes`` — byte-budget LRU,
    ``entries`` — key-count LRU drop, ``shape`` — an entry reset
    because its problem was re-shaped) on ``recorder`` (the process
    recorder by default) and accumulates in :attr:`evictions` /
    :meth:`stats`, so a fleet's cold solves are attributable to the
    cache pressure that caused them instead of reading as unexplained
    warm-path misses (docs/FLEET.md "Carry-cache tuning").

    Single-task discipline (analysis/race_lint.py SHARED_STATE): every
    method is synchronous and mutates under one event-loop window; the
    plan service serializes all cache writes on its dispatcher task,
    and sessions are single-owner by construction.
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 max_entries: Optional[int] = None,
                 recorder: "Optional[Any]" = None) -> None:
        if max_bytes is not None and max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        if max_entries is not None and max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}")
        self.max_bytes = max_bytes
        self.max_entries = max_entries
        self._rec = recorder
        self._entries: dict[str, CarryEntry] = {}
        self._clock = 0
        # Running byte total, adjusted by _adjust around every carry
        # mutation: nbytes() must be O(1), not a sweep over every entry
        # (store() runs once per tenant per batch on the dispatcher's
        # event-loop thread).
        self._bytes = 0
        # Eviction counts by reason (the stats() twin of the
        # fleet.carry_evictions labeled counter).
        self.evictions: dict[str, int] = {}

    # -- bookkeeping ---------------------------------------------------------

    def _touch(self, e: CarryEntry) -> None:
        self._clock += 1
        e._tick = self._clock

    def _note_eviction(self, reason: str) -> None:
        """One eviction's accounting (sync window): the labeled
        ``fleet.carry_evictions`` counter plus the stats() dict, so the
        cold solve this eviction will cost is attributable."""
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        rec = self._rec
        if rec is None:
            from ..obs import get_recorder

            rec = get_recorder()
        rec.count(f'fleet.carry_evictions{{reason="{reason}"}}')

    def stats(self) -> dict[str, object]:
        """Cache-pressure snapshot: live entry/byte load against the
        budgets, plus cumulative evictions by reason."""
        return {
            "entries": len(self._entries),
            "bytes": self.nbytes(),
            "max_bytes": self.max_bytes,
            "max_entries": self.max_entries,
            "evictions": dict(self.evictions),
        }

    class _Adjust:
        """Context manager bracketing one entry's carry mutation: the
        entry's byte delta folds into the cache's running total."""

        __slots__ = ("cache", "entry", "before")

        def __init__(self, cache: "CarryCache", e: CarryEntry) -> None:
            self.cache = cache
            self.entry = e

        def __enter__(self) -> None:
            self.before = self.entry.nbytes()

        def __exit__(self, *exc: object) -> None:
            self.cache._bytes += self.entry.nbytes() - self.before

    def _adjust(self, e: CarryEntry) -> "CarryCache._Adjust":
        return CarryCache._Adjust(self, e)

    def entry(self, key: str, partitions: int) -> CarryEntry:
        """The key's entry, created (empty, mask length ``partitions``)
        on first use.  An existing entry whose mask length no longer
        matches ``partitions`` is reset — the problem was re-shaped, so
        any carried state is stale by construction."""
        e = self._entries.get(key)
        if e is None or e.dirty.shape[0] != partitions:
            if e is not None:  # shape reset drops the old carries
                self._bytes -= e.nbytes()
                if e.carry is not None or e.pending is not None:
                    self._note_eviction("shape")
            e = CarryEntry(partitions)
            self._entries[key] = e
            # Entry creation is the growth edge: enforce the key-count
            # bound here too, so consume-only key churn cannot outgrow
            # it between stores.  Touch FIRST — the new entry must
            # carry the highest tick so the LRU drop takes an old key,
            # never the one just created.
            self._touch(e)
            self._enforce_budget()
        else:
            self._touch(e)
        return e

    def peek(self, key: str) -> Optional[CarryEntry]:
        """The key's entry without creating one (no recency bump)."""
        return self._entries.get(key)

    def keys(self) -> list[str]:
        return list(self._entries)

    def nbytes(self) -> int:
        """Summed carry bytes across every entry (the budgeted mass);
        O(1) — maintained incrementally around every mutation (the
        recount twin below is the test oracle for that invariant)."""
        return self._bytes

    def _recount(self) -> int:
        """The O(entries) ground truth nbytes() must always equal."""
        return sum(e.nbytes() for e in self._entries.values())

    def _enforce_budget(self) -> None:
        if self.max_entries is not None and \
                len(self._entries) > self.max_entries:
            # Whole-entry LRU drop (masks included): churned-away
            # tenant keys must not accumulate forever.
            excess = len(self._entries) - self.max_entries
            for key in sorted(self._entries,
                              key=lambda k: self._entries[k]._tick
                              )[:excess]:
                e = self._entries[key]
                self._bytes -= e.nbytes()
                del self._entries[key]
                if e.carry is not None or e.pending is not None:
                    # Count only drops that cost a cold solve (the
                    # counter's contract); an already-empty entry loses
                    # nothing but its masks, which a cold start absorbs
                    # anyway — same guard as the shape-reset path.
                    self._note_eviction("entries")
        if self.max_bytes is None:
            return
        total = self.nbytes()
        if total <= self.max_bytes:
            return
        # Oldest first; the just-touched key has the highest tick and is
        # evicted last — but a single carry larger than the whole budget
        # still goes (the budget is a hard cap, not advisory).
        for key in sorted(self._entries,
                          key=lambda k: self._entries[k]._tick):
            e = self._entries[key]
            freed = e.nbytes()
            if freed == 0:
                continue
            e.carry = None
            e.current = None
            e.pending = None
            self._bytes -= freed
            total -= freed
            self._note_eviction("bytes")
            if total <= self.max_bytes:
                return

    # -- the lifecycle -------------------------------------------------------

    def invalidate(self, key: str) -> None:
        """Drop the key's warm-start state: the next replan solves cold.
        Masks clear too — a cold start absorbs every recorded delta."""
        e = self._entries.get(key)
        if e is None:
            return
        self._touch(e)
        with self._adjust(e):
            e.carry = None
            e.current = None
            e.pending = None
        e.dirty[:] = False
        e.dirty_post[:] = False

    def drop(self, key: str) -> None:
        """Forget the key entirely (entry included)."""
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes()

    def mark_dirty(self, key: str, mask: NPArray,
                   pending: bool) -> None:
        """Record delta marks.  With ``pending`` (a proposal is in
        flight) marks land in the post-proposal mask: the pending solve
        did not see this delta, so promote() must carry them forward
        instead of clearing them with the absorbed ones."""
        e = self._entries.get(key)
        if e is None:
            e = self.entry(key, mask.shape[0])
        self._touch(e)
        if pending:
            e.dirty_post |= mask
        else:
            e.dirty |= mask

    def drop_carry_keep_dirty(self, key: str) -> None:
        """Invalidate the live carry only: the masks and pending carry
        survive.  Used when ``current`` is replaced wholesale (R-growth
        padding) — the carry no longer matches any live array, but the
        recorded deltas still describe real cluster changes."""
        e = self._entries.get(key)
        if e is None:
            return
        self._touch(e)
        with self._adjust(e):
            e.carry = None
            e.current = None

    def pad_nodes(self, key: str, n: int) -> None:
        """Zero-pad BOTH carries' [N]-shaped tables after node growth
        (a delta can land between replan() and promote(), and promote
        will adopt the pending carry into the grown problem)."""
        e = self._entries.get(key)
        if e is None:
            return
        self._touch(e)
        with self._adjust(e):
            e.carry = pad_carry_nodes(e.carry, n)
            e.pending = pad_carry_nodes(e.pending, n)
        self._enforce_budget()

    def consume(
        self, key: str, current: NPArray, match: str = "identity",
    ) -> tuple[Optional["SolveCarry"], NPArray]:
        """Take the key's carry for a replan attempt, merging the
        post-proposal marks into the dirty mask (this solve absorbs
        every delta recorded so far).

        Returns ``(carry, dirty)``; carry is None on a miss.  The carry
        is CONSUMED either way — its device buffers may be donated into
        the repair, so the caller must replace it via store_pending +
        promote (or the entry stays cold).  ``match`` selects validity:
        ``"identity"`` (sessions: current IS the array the carry was
        built against) or ``"equal"`` (the service: callers rebuild
        prev per request, so compare by value)."""
        if match not in ("identity", "equal"):
            raise ValueError(f"unknown match mode: {match!r}")
        e = self.entry(key, current.shape[0])
        e.dirty |= e.dirty_post
        e.dirty_post[:] = False
        carry, cur = e.carry, e.current
        with self._adjust(e):
            e.carry = None
            e.current = None
        dirty = e.dirty
        if carry is None or cur is None:
            return None, dirty
        if match == "identity":
            ok = cur is current
        else:
            ok = cur.shape == current.shape and \
                bool(np.array_equal(cur, current))
        return (carry, dirty) if ok else (None, dirty)

    def store_pending(self, key: str,
                      carry: Optional["SolveCarry"]) -> None:
        """Hold a just-solved proposal's carry until promote()."""
        e = self._entries.get(key)
        if e is None:
            return
        self._touch(e)
        with self._adjust(e):
            e.pending = carry
        self._enforce_budget()

    def promote(self, key: str, current: NPArray) -> None:
        """Adopt the pending carry as the live warm-start state for
        ``current`` (the caller just adopted the proposal) and retire
        the absorbed delta marks; post-proposal marks roll forward."""
        e = self._entries.get(key)
        if e is None:
            return
        self._touch(e)
        with self._adjust(e):
            e.carry = e.pending
            e.current = current if e.pending is not None else None
            e.pending = None
        e.dirty = e.dirty_post
        e.dirty_post = np.zeros_like(e.dirty)
        self._enforce_budget()

    def store(self, key: str, carry: "SolveCarry",
              current: NPArray) -> None:
        """Adopt ``carry`` directly as the live state for ``current``
        (the service's one-shot path: solve + adopt in one step), with
        clean masks — the solve absorbed everything."""
        e = self.entry(key, current.shape[0])
        with self._adjust(e):
            e.carry = carry
            e.current = current
            e.pending = None
        e.dirty[:] = False
        e.dirty_post[:] = False
        self._enforce_budget()


class EncodeCache:
    """Keyed LRU store of per-tenant resident encode state
    (:class:`plan.resident.EncodedState`) — the encode-layer sibling of
    :class:`CarryCache`, sharing its contracts:

    - **eviction is always safe**: a dropped state just means the
      tenant's next converge cycle runs a full ``encode_problem`` and
      rebuilds it, bit-identically (cold is the single-problem encode
      on current inputs).  ``max_entries`` bounds the key count,
      ``max_bytes`` the summed resident array bytes; whole states are
      dropped least-recently-used first.
    - **evictions are never silent**: every drop counts
      ``fleet.encode_evictions{reason=bytes|entries}``, and every
      protocol demotion the planner requests
      (:meth:`invalidate`) counts
      ``fleet.encode_demotions{reason=...}`` — so a fleet's cold
      re-encodes are exactly attributable: in steady state,
      ``fleet.encode_cold == first encodes + demotions + evictions``
      (the bench ``fleet_loop`` stage gates that identity).

    Shared-state discipline (analysis/race_lint.py ``SHARED_STATE``):
    the cache is shared by N tenant control-loop tasks, but every
    method is synchronous (one no-await event-loop window) and each KEY
    has a single writer — its own tenant's task.  A planner holds its
    state object across its solve await, so a concurrent eviction of
    that key only drops the cache's reference; the planner's ``put``
    re-inserts it and re-enforces the budget.
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 max_entries: Optional[int] = None,
                 recorder: "Optional[Any]" = None) -> None:
        if max_bytes is not None and max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        if max_entries is not None and max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}")
        self.max_bytes = max_bytes
        self.max_entries = max_entries
        self._rec = recorder
        self._entries: "dict[str, EncodedState]" = {}
        self._ticks: dict[str, int] = {}
        self._clock = 0
        self.evictions: dict[str, int] = {}
        self.demotions: dict[str, int] = {}

    def _count(self, name: str, book: dict[str, int],
               reason: str) -> None:
        book[reason] = book.get(reason, 0) + 1
        rec = self._rec
        if rec is None:
            from ..obs import get_recorder

            rec = get_recorder()
        rec.count(f'{name}{{reason="{reason}"}}')

    def _touch(self, key: str) -> None:
        self._clock += 1
        self._ticks[key] = self._clock

    def get(self, key: str) -> "Optional[EncodedState]":
        st = self._entries.get(key)
        if st is not None:
            self._touch(key)
        return st

    def put(self, key: str, state: "EncodedState") -> None:
        self._entries[key] = state
        self._touch(key)
        self._enforce_budget()

    def invalidate(self, key: str, reason: str) -> None:
        """Drop one key's state on a protocol demotion (divergence /
        statics swap / node-list drift / shape drift): the next cycle
        re-encodes cold.  Counted once per live state dropped —
        ``fleet.encode_demotions{reason=}`` — so every later cold
        encode is attributable."""
        if self._entries.pop(key, None) is not None:
            self._ticks.pop(key, None)
            self._count("fleet.encode_demotions", self.demotions,
                        reason)

    def drop(self, key: str) -> None:
        """Forget a key silently (tenant teardown — not a demotion)."""
        self._entries.pop(key, None)
        self._ticks.pop(key, None)

    def keys(self) -> list[str]:
        return list(self._entries)

    def nbytes(self) -> int:
        return sum(st.nbytes() for st in self._entries.values())

    def stats(self) -> dict[str, object]:
        return {
            "entries": len(self._entries),
            "bytes": self.nbytes(),
            "max_bytes": self.max_bytes,
            "max_entries": self.max_entries,
            "evictions": dict(self.evictions),
            "demotions": dict(self.demotions),
        }

    def _enforce_budget(self) -> None:
        if self.max_entries is not None and \
                len(self._entries) > self.max_entries:
            excess = len(self._entries) - self.max_entries
            for key in sorted(self._entries,
                              key=lambda k: self._ticks[k])[:excess]:
                del self._entries[key]
                self._ticks.pop(key, None)
                self._count("fleet.encode_evictions", self.evictions,
                            "entries")
        if self.max_bytes is None:
            return
        total = self.nbytes()
        if total <= self.max_bytes:
            return
        for key in sorted(self._entries,
                          key=lambda k: self._ticks[k]):
            freed = self._entries[key].nbytes()
            del self._entries[key]
            self._ticks.pop(key, None)
            self._count("fleet.encode_evictions", self.evictions,
                        "bytes")
            total -= freed
            if total <= self.max_bytes:
                return
