"""Batched cost-tensor planner — the TPU backend.

Where the reference runs a sequential greedy loop over partitions
(reference plan.go:253-303, O(S*P*(S*N + N log N)) on one core), this
backend scores ALL partitions against ALL nodes at once and assigns each
state/replica slot in fully-vectorized auction rounds:

  score[P, N] = (holders_of_state[N] + 0.001 * fill[N] / P) / node_weight
              + negative-weight boost       (plan.go:675-684 semantics)
              - stickiness * held_previously (plan.go:654-662)
              + tiered hierarchy-rule penalty (api.go:76-105 semantics)
              + INF * forbidden              (same-partition exclusivity,
                                              removed nodes)

Assignment per slot runs capacity-constrained proposal rounds: every
unassigned partition bids on its best open node; each node accepts bidders
in most-urgent-first order (urgency = regret margin between best and
second-best) up to its remaining weighted capacity; accepted bids update
the counts that score the next round.  A deterministic per-(partition, node)
tie-break jitter — far below any real score term — spreads equal-score bids
across equally-good nodes, so a wave of identical partitions fills every
node in one round instead of herding onto the argmin.  A final force step
ignores capacity so constraint satisfaction never degrades below the greedy
planner's (shortfalls become warnings, exactly like plan.go:231-235).

Everything is jit-compiled with static (S, R, rules) structure: the slot
loops unroll at trace time, the auction is a lax.while_loop, and the only
cross-partition dependencies are per-node sums — which is what makes the
partition axis shardable across a TPU mesh (see blance_tpu.parallel).

Set axis_name to run under shard_map with the partition axis sharded:
per-node bid totals are then psum'd over the mesh so capacity and counts
stay globally consistent while scores stay local.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.encode import (
    DenseProblem,
    NPArray,
    decode_assignment,
    encode_problem,
)
from ..core.types import PartitionMap, PartitionModel, PlanOptions
from ..obs import device as _device
from ..obs import get_recorder, phase_span
from ..ops.reduce2 import (
    min2_argmin_reference,
    pallas_available,
    priced_min2_argmin,
)
from ..ops.score_fused import (
    fused_score_min2,
    jitter_hash,
    pack_score_inputs,
    score_at_columns,
)
from ..ops.sparse2 import sparse_min2_reference, sparse_priced_min2

__all__ = ["plan_next_map_tpu", "plan_pipeline", "solve_dense",
           "solve_dense_converged", "solve_converged_resilient",
           "solve_dense_warm", "SolveCarry", "carry_from_assignment",
           "check_assignment", "maybe_validate",
           "solve_sparse", "solve_sparse_warm", "DenseScoreMemoryError",
           "projected_score_bytes", "set_dense_score_budget",
           "check_dense_memory", "sparse_rules_supported",
           "resolve_sparse_impl"]

# Static solver-entry shapes (see plan/session.py where both are built
# from the EncodedProblem): per-state slot counts, and per-state tuples
# of (include_level, exclude_level) hierarchy-rule pairs.  The _hier_*
# helpers below take ONE state's pair tuple (StateRules); every solve
# entry takes the full per-state Rules.
Constraints = tuple[int, ...]
StateRules = tuple[tuple[int, int], ...]
Rules = tuple[StateRules, ...]

_INF = 1.0e9  # hard-forbidden
_RULE_MISS = 1.0e6  # satisfies no hierarchy rule (uniform => flat fallback)
_RULE_TIER = 1.0e4  # penalty step per rule index (earlier rules win)
# SCALE ASSUMPTION (round-5 advisor finding): tier equality is decided by
# BAND tests — "same tier" means the raw score sits within _RULE_TIER/2 of
# the row's unpriced minimum (see rule_ok/soft_ok in _assign_slot and the
# pin pass's floor test).  That is only sound while every within-tier
# score term stays well below the band: the seeded per-node fill term
# (≈ sum(constraints) * total_weight / total_node_weight for balanced
# prevs, or max(seed_fill/node_weight) for skewed ones), stickiness, and
# the negative-weight boost.  At extreme P/N ratios (≳2k unit-weight
# partitions per node per slot) the fill term alone crosses the band and
# nodes stop being tier-comparable — placements would silently
# misclassify tiers.  _check_tier_band_scale below asserts the headroom
# at every host-side solve entry so such problems fail loudly instead.
_TIER_BAND_HEADROOM = 0.45  # max allowed within-tier mass, in tiers
# Passed-check memo for _check_tier_band_scale: (array id + shape +
# statics) -> weight fingerprint.  See the function for the safety
# argument; bounded at 256 entries.
_tier_scale_memo: dict[tuple[object, ...], object] = {}
_MAX_AUCTION_ROUNDS = 16
# Bid-spreading jitter: above the advisory fill factor (0.001/P) by design,
# below every decision-bearing term (stickiness >= 1.5 typical, rule tiers
# 1e4, price >= 1/node-weight per accepted unit).
_JITTER = 1.0e-5

# Score-engine default for plan_next_map_tpu: "off" materializes the
# [P, N_l] score matrix per slot; "on" computes the score inside the
# Pallas reduction kernel (ops/score_fused.py) so the matrix never
# exists; "interpret" runs the fused kernel under the pallas interpreter
# (CPU testing).  Passed into the jit as a static arg, so flipping the
# default takes effect on the next call.  "auto" resolves per problem
# size at the plan_next_map_tpu boundary (resolve_fused_score): the
# matrix engine wins below the chip's memory ceiling (fewer kernel
# launches), the fused engine is the only thing that fits above it.
_FUSED_SCORE_DEFAULT = "auto"

# Working-set model for the matrix engine: ~5 live [P, N] f32 copies
# through an auction round (score build, priced copy, reduction temps).
# Calibrated on v5e: 100k x 10k measured an 18.9 GB program requirement
# = ~19 bytes/cell.
_MATRIX_BYTES_PER_CELL = 20
_HBM_BUDGET_FRACTION = 0.6


def set_fused_score_default(mode: str) -> None:
    """Select the score engine for subsequent plan_next_map_tpu calls."""
    global _FUSED_SCORE_DEFAULT
    if mode not in ("off", "on", "interpret", "auto"):
        raise ValueError(f"unknown fused-score mode: {mode!r}")
    _FUSED_SCORE_DEFAULT = mode


def _device_hbm_bytes() -> int:
    """Accelerator memory per chip; 16 GiB (v5e) when the runtime does
    not report a limit (e.g. CPU test meshes)."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except (RuntimeError, IndexError, AttributeError, TypeError,
            ValueError):
        # The documented "runtime does not report" shapes: backend init
        # failure, no devices, a backend without memory_stats, or a
        # stats dict with a non-numeric limit.  Anything else (bugs,
        # KeyboardInterrupt) propagates.
        pass
    return 16 * 2 ** 30


def resolve_fused_score(mode: str, p: int, n: int) -> str:
    """Resolve "auto" to a concrete engine for a [P, N]-sized problem.

    "auto" -> "on" (in-kernel score, O(P + N) traffic per round) when
    the matrix engine's [P, N] working set would exceed the chip's
    memory budget and the compiled Pallas path is available; "off"
    (materialized score matrix) otherwise.  Explicit modes pass
    through untouched.  Must run BEFORE jit: fused_score is a static
    argument of solve_dense / solve_dense_converged, and "auto" there
    is an error by design.
    """
    if mode != "auto":
        return mode
    from ..ops.reduce2 import pallas_available

    if not pallas_available():
        return "off"
    if p * n * _MATRIX_BYTES_PER_CELL > \
            _HBM_BUDGET_FRACTION * _device_hbm_bytes():
        return "on"
    return "off"


def resolve_default_fused_score(p: int, n: int) -> str:
    """The session-default engine mode, resolved for a [P, N] problem.
    The one spelling every entry point (plan_next_map_tpu,
    PlannerSession.replan, future callers) uses to turn the module
    default into a concrete jit-safe mode."""
    return resolve_fused_score(_FUSED_SCORE_DEFAULT, p, n)


# --- dense-memory guard ------------------------------------------------------
#
# The matrix engine's score sweep materializes ~_MATRIX_BYTES_PER_CELL
# bytes per [P, N] cell.  Past the accelerator budget XLA dies with an
# opaque allocator error deep in compile (or the CPU backend swaps the
# host to death); this guard turns that into a structured, actionable
# error at solve ENTRY, naming the projected footprint and the ways out
# (the sparse shortlist engine, the in-kernel fused engine, sharding).
# None = derive from the device (the same 60%-of-HBM ceiling the engine
# auto-selection uses); configurable for deployments with different
# headroom — and for tests.

_DENSE_GUARD_BUDGET: Optional[int] = None


def set_dense_score_budget(n_bytes: Optional[int]) -> None:
    """Override the dense-memory guard's byte budget (None = derive
    from the device again)."""
    global _DENSE_GUARD_BUDGET
    if n_bytes is not None and int(n_bytes) <= 0:
        raise ValueError(f"budget must be positive, got {n_bytes}")
    _DENSE_GUARD_BUDGET = None if n_bytes is None else int(n_bytes)


def dense_score_budget_bytes() -> int:
    """The byte budget the dense-memory guard enforces."""
    if _DENSE_GUARD_BUDGET is not None:
        return _DENSE_GUARD_BUDGET
    return int(_HBM_BUDGET_FRACTION * _device_hbm_bytes())


def projected_score_bytes(p: int, n: int) -> int:
    """Projected matrix-engine working set for a [P, N] problem (the
    score sweep's live [P, N] f32 copies, calibrated on v5e — see
    _MATRIX_BYTES_PER_CELL)."""
    return int(p) * int(n) * _MATRIX_BYTES_PER_CELL


class DenseScoreMemoryError(ValueError):
    """The dense matrix engine's projected [P, N] score footprint
    exceeds the memory budget.  Structured so callers can act on it:
    ``projected_bytes`` / ``budget_bytes`` / ``shape`` (P, S, N)."""

    def __init__(self, projected_bytes: int, budget_bytes: int,
                 shape: tuple[int, ...]):
        self.projected_bytes = int(projected_bytes)
        self.budget_bytes = int(budget_bytes)
        self.shape = tuple(shape)
        p, s, n = shape
        super().__init__(
            f"dense score sweep would materialize ~"
            f"{projected_bytes / 2**30:.1f} GiB of [P, N] intermediates "
            f"(P={p}, S={s}, N={n}, ~{_MATRIX_BYTES_PER_CELL} B/cell) — "
            f"over the {budget_bytes / 2**30:.1f} GiB budget; refusing "
            f"before XLA OOMs opaquely.  Ways out: the sparse shortlist "
            f"engine (PlanOptions(sparse=True) or plan.tensor."
            f"solve_sparse, K candidates/partition instead of N), a "
            f"smaller K if already sparse, the in-kernel fused engine "
            f"on TPU (set_fused_score_default('on')), sharding the "
            f"partition axis (parallel.sharded), or raising the budget "
            f"(plan.tensor.set_dense_score_budget)")


def check_dense_memory(p: int, s: int, n: int, engine: str) -> None:
    """Raise :class:`DenseScoreMemoryError` when the MATRIX engine
    (``engine == "off"``) is about to materialize a [P, N] score sweep
    past the budget.  The fused/sparse engines never materialize it and
    pass untouched."""
    if engine != "off":
        return
    projected = projected_score_bytes(p, n)
    budget = dense_score_budget_bytes()
    if projected > budget:
        raise DenseScoreMemoryError(projected, budget, (p, s, n))


class SolveCarry(NamedTuple):
    """Auction state carried across delta replans (the warm start).

    A converged solve is a fixpoint: replaying it against the same
    problem re-derives the same per-node fill (the quantity that prices
    the score's balance term) from scratch.  The carry keeps that state
    alive between replans so a delta replan seeds the solver instead of
    re-deriving it, and — more importantly — so the fixpoint loop's
    confirming sweep can be skipped when the repair provably stayed
    inside the delta (see :func:`solve_dense_warm`).

    ``used`` is the ground truth; ``prices`` is its per-node sum (the
    total fill vector the balance term divides), kept explicit so
    callers can run O(N) host prechecks (capacity-shrink detection)
    without touching the [S, N] table.

    Fields
    ------
    prices: [N] f32 — total per-node weighted fill at convergence.
    assign: [P, S, R] i32 — the converged assignment the carry matches.
        A carry is only valid against a ``prev`` equal to this array;
        sessions enforce that by identity (plan/session.py).
    used:   [S, N] f32 — per-state per-node accepted weight, built with
        the SAME scatter the solver's seed pass uses, so seeding from it
        is bitwise identical to recomputing from ``assign``.
    """

    prices: jnp.ndarray
    assign: jnp.ndarray
    used: jnp.ndarray


def _used_by_state(assign: jnp.ndarray, pweights: jnp.ndarray, n: int,
                   s: int, axis_name: Optional[str] = None) -> jnp.ndarray:
    """[S, N] per-state weighted fill — the carry's ``used`` table.

    One :func:`_scatter_counts` per state followed by a psum, exactly
    the ops (and op order) of solve_dense's seed pass, so a warm solve
    seeded from this table computes bit-identical totals."""
    return jnp.stack([
        _psum(_scatter_counts(assign[:, si, :], pweights, n), axis_name)
        for si in range(s)])


@jax.jit
def _carry_used_jit(assign: jnp.ndarray, pweights: jnp.ndarray,
                    nweights: jnp.ndarray) -> jnp.ndarray:
    """Single-device spelling of :func:`_used_by_state` (for building a
    carry from a host-side assignment, e.g. after a cold solve)."""
    return _used_by_state(
        assign, pweights, nweights.shape[0], assign.shape[1])


def carry_from_assignment(assign: jnp.ndarray, pweights: jnp.ndarray,
                          nweights: jnp.ndarray) -> SolveCarry:
    """Package a converged assignment as a :class:`SolveCarry`.

    Use after any cold solve whose output will seed future delta
    replans.  ``used`` comes from the same device scatter the solver's
    seed pass runs, so the next warm solve's totals match a cold
    recompute bit-for-bit."""
    assign = jnp.asarray(assign)
    used = _carry_used_jit(assign, jnp.asarray(pweights),
                           jnp.asarray(nweights))
    return SolveCarry(prices=jnp.sum(used, axis=0), assign=assign,
                      used=used)


def _drop_empty(ids: jnp.ndarray, n: int) -> jnp.ndarray:
    """Map empty (-1) ids to n so scatters with mode='drop' discard them.

    NB: JAX .at[] wraps negative indices like NumPy — a raw -1 would
    silently scatter onto the LAST node."""
    return jnp.where(ids >= 0, ids, n)


def _scatter_counts(ids: jnp.ndarray, weights: jnp.ndarray, n: int) -> jnp.ndarray:
    """Weighted histogram of node ids [P, R] -> [N]; -1 entries dropped."""
    flat = _drop_empty(ids.reshape(-1), n)
    w = jnp.broadcast_to(weights[:, None], ids.shape).reshape(-1)
    return jnp.zeros(n, jnp.float32).at[flat].add(w, mode="drop")




def _anchor_rule_sat(
    anchor: jnp.ndarray,  # [P] global node ids, -1 = absent
    cand_inc: jnp.ndarray,  # candidates' include-level gids, [P] or [1, N_l]
    cand_exc: jnp.ndarray,  # candidates' exclude-level gids, same shape
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    inc: int,
    exc: int,
) -> jnp.ndarray:
    """Rule gate for ONE anchor column: candidate satisfies (inc, exc)
    iff it shares the anchor's include-level ancestor and NOT its
    exclude-level ancestor; absent anchors satisfy everything; validity
    gates on the anchor side only.  THE single spelling of the gate —
    both the [P, N] penalty matrix and the [P] point evaluation go
    through here, so the semantics cannot drift apart."""
    aa = jnp.maximum(anchor, 0)
    sh = (anchor.shape[0],) + (1,) * (cand_inc.ndim - 1)
    inc_same = (gids[inc][aa].reshape(sh) == cand_inc) & \
        gid_valid[inc][aa].reshape(sh)
    exc_same = (gids[exc][aa].reshape(sh) == cand_exc) & \
        gid_valid[exc][aa].reshape(sh)
    return jnp.where((anchor >= 0).reshape(sh), inc_same & ~exc_same, True)


def _hier_penalty(
    anchors: jnp.ndarray,  # [P, A] GLOBAL node ids, -1 = absent anchor
    gids: jnp.ndarray,  # [L, N] full (anchor lookups are global)
    gid_valid: jnp.ndarray,  # [L, N] full
    rules: StateRules,  # ((include_level, exclude_level), ...)
    gids_cand: Optional[jnp.ndarray] = None,  # [L, N_l] candidate columns
) -> jnp.ndarray:
    """Tiered rule penalty [P, N] anchored on EVERY prior pick at once.

    The reference anchors each hierarchy pick on the primary *plus all
    nodes picked so far for the partition* (the intersection at
    plan.go:185-191,738-753), which is what makes two replicas under a
    rule like (include 2, exclude 1) land on two *different* racks — not
    merely racks different from the primary's.  A rule is satisfied by
    node n iff, for every present anchor a: n shares a's include-level
    ancestor and NOT a's exclude-level ancestor.  First-satisfied rule
    index sets the tier; satisfying none costs _RULE_MISS.  Unsatisfiable
    rules penalize every node equally, which leaves the argmin order
    flat — the reference's fall-back-to-flat-candidates behavior
    (plan.go:214-220).  A ~ 1 + constraints, so the anchor loop unrolls
    into a handful of [P, N] comparisons that XLA fuses into the score
    expression — no [P, N, A] tensor materializes.

    Under node-axis sharding, ``gids_cand`` holds only this shard's
    candidate columns (the output is [P, N_local]) while anchor lookups
    still index the full replicated tables; validity gates on the anchor
    side only, exactly like the replicated path."""
    if gids_cand is None:
        gids_cand = gids
    p, a_width = anchors.shape
    n_l = gids_cand.shape[1]
    any_anchor = jnp.any(anchors >= 0, axis=1)
    pen = jnp.full((p, n_l), _RULE_MISS, jnp.float32)
    for idx, (inc, exc) in enumerate(rules):
        sat = jnp.ones((p, n_l), jnp.bool_)
        for ai in range(a_width):
            sat &= _anchor_rule_sat(
                anchors[:, ai], gids_cand[inc][None, :],
                gids_cand[exc][None, :], gids, gid_valid, inc, exc)
        pen = jnp.where(sat, jnp.minimum(pen, idx * _RULE_TIER), pen)
    return jnp.where(any_anchor[:, None], pen, 0.0)


def _psum(x: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    return lax.psum(x, axis_name) if axis_name else x


def _hier_tier_at(
    anchors: jnp.ndarray,  # [P, A] global node ids, -1 absent
    node: jnp.ndarray,  # [P] or [P, K] global node ids
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    rules: StateRules,
) -> jnp.ndarray:
    """_hier_penalty evaluated at gathered columns — O(rows * cols) ops.

    ``node`` may be [P] (one column per row: phase B's waterfall probe)
    or [P, K] (the sparse shortlist's candidate block); the anchor axis
    broadcasts against any trailing shape, and the [P] spelling is
    bit-identical to what it always was."""
    any_anchor = jnp.any(anchors >= 0, axis=1)
    sh = (node.shape[0],) + (1,) * (node.ndim - 1)
    nd = jnp.clip(node, 0, gids.shape[1] - 1)
    pen = jnp.full(node.shape, _RULE_MISS, jnp.float32)
    for idx, (inc, exc) in enumerate(rules):
        sat = jnp.ones(node.shape, jnp.bool_)
        for ai in range(anchors.shape[1]):
            sat &= _anchor_rule_sat(
                anchors[:, ai], gids[inc][nd], gids[exc][nd],
                gids, gid_valid, inc, exc)
        pen = jnp.where(sat, jnp.minimum(pen, idx * _RULE_TIER), pen)
    return jnp.where(any_anchor.reshape(sh), pen, 0.0)


def _hier_floor_counts(
    anchors: jnp.ndarray,  # [P, A] global node ids, -1 absent
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    valid: jnp.ndarray,  # [N] full
    rules: StateRules,
    taken_stack: Optional[jnp.ndarray] = None,  # [P, T] GLOBAL node ids
    # the row's partition already occupies; those columns are +INF in
    # the score, so a taken-aware floor must not count them attainable
) -> jnp.ndarray:
    """Best attainable rule tier over valid nodes, by GROUP COUNTING.

    Equivalent to ``min over valid n of _hier_penalty[:, n]`` without
    materializing [P, N]: because each rule's exclude level is strictly
    finer than its include level (caller checks this statically), an
    exclude group lies inside exactly one include group, so the number
    of rule-satisfying valid nodes is
        count(valid in shared include group g)
        - sum over DISTINCT anchor exclude groups of count(valid in e).
    Everything is [N]-histograms plus [P] gathers.  Anchor-side validity
    gates exactly like _hier_penalty: an anchor with an invalid include
    gid makes the rule unsatisfiable; an invalid exclude gid excludes
    nothing.  Returns the floor PENALTY value ([P], 0.0 when no anchor),
    matching what _hier_penalty's row-min over valid columns yields —
    with one deliberate difference: when no valid node exists at all the
    matrix row-min is +_INF while this returns _RULE_MISS, and every
    comparison made against the floor treats those identically (a
    _RULE_MISS-tier pin passes either way)."""
    p, a_width = anchors.shape
    n = gids.shape[1]
    any_anchor = jnp.any(anchors >= 0, axis=1)
    floor = jnp.full(p, _RULE_MISS, jnp.float32)
    for idx, (inc, exc) in enumerate(rules):
        # Valid-node histograms per group at each level (group ids are
        # dense per level, < N; invalid slots route to the drop bucket).
        gi = jnp.where(valid, gids[inc], -1)
        ge = jnp.where(valid, gids[exc], -1)
        cnt_inc = jnp.zeros(n, jnp.float32).at[
            jnp.where(gi >= 0, gi, n)].add(1.0, mode="drop")
        cnt_exc = jnp.zeros(n, jnp.float32).at[
            jnp.where(ge >= 0, ge, n)].add(1.0, mode="drop")

        # Shared include group across present anchors (else unsatisfiable).
        g = jnp.full(p, -1, jnp.int32)
        ok = jnp.ones(p, jnp.bool_)
        for ai in range(a_width):
            a = anchors[:, ai]
            aa = jnp.maximum(a, 0)
            a_g = jnp.where(gid_valid[inc][aa], gids[inc][aa], -2)
            present = a >= 0
            ok &= jnp.where(present & (g >= 0), a_g == g, True)
            ok &= jnp.where(present & (g < 0), a_g >= 0, True)
            g = jnp.where(present & (g < 0), a_g, g)

        # Exclusion mass: distinct exclude groups among present anchors.
        excl = jnp.zeros(p, jnp.float32)
        e_seen = []
        for ai in range(a_width):
            a = anchors[:, ai]
            aa = jnp.maximum(a, 0)
            e = jnp.where((a >= 0) & gid_valid[exc][aa], gids[exc][aa], -1)
            dup = jnp.zeros(p, jnp.bool_)
            for prev_e in e_seen:
                dup |= (e == prev_e) & (e >= 0)
            excl += jnp.where(
                (e >= 0) & ~dup, cnt_exc[jnp.clip(e, 0, n - 1)], 0.0)
            e_seen.append(e)

        count = jnp.where(
            ok & (g >= 0), cnt_inc[jnp.clip(g, 0, n - 1)] - excl, 0.0)

        # Taken-aware: subtract the row's own occupied nodes still
        # standing in the include group but OUTSIDE every counted
        # exclude group (those inside were subtracted with their group).
        # Mirrors the audit's attainable_count (_count_hier_misses_fast)
        # including its dedup of repeated ids, so the floor agrees with
        # the matrix row-min over score columns the taken mask +INFs.
        if taken_stack is not None:
            t_seen = []
            for ti in range(taken_stack.shape[1]):
                u = taken_stack[:, ti]
                uu = jnp.clip(u, 0, n - 1)
                ok_u = (u >= 0) & valid[uu]
                in_g = ok_u & (gids[inc][uu] == g) & (g >= 0)
                in_excl = jnp.zeros(p, jnp.bool_)
                for e in e_seen:
                    in_excl |= (e >= 0) & (gids[exc][uu] == e)
                dup = jnp.zeros(p, jnp.bool_)
                for prev_u in t_seen:
                    dup |= (u == prev_u) & (u >= 0)
                count = count - jnp.where(in_g & ~in_excl & ~dup, 1.0, 0.0)
                t_seen.append(u)

        floor = jnp.where(count > 0,
                          jnp.minimum(floor, idx * _RULE_TIER), floor)
    return jnp.where(any_anchor, floor, 0.0)


# --- node-axis sharding helpers ------------------------------------------
#
# Under a 2-D mesh (parts x nodes) every [N] vector (counts, capacity,
# prices) stays REPLICATED along the node axis — at the north-star 10k
# nodes that's kilobytes — while the [P, N] score (and the masks fused
# into it) holds only local columns.  Membership/exclusivity live as
# [P, small] GLOBAL id columns compared against the local column window
# (_member_ids), so they need no collectives at all.  Acceptance/capacity
# logic runs as identical replicated math on every node shard; the only
# node-axis collectives are (a) combining per-row (min, argmin, second)
# stats and (b) fetching a matrix value at a remote column.


def _node_off(node_axis: Optional[str], n_l: int):
    """Global column offset of this node shard."""
    return lax.axis_index(node_axis) * n_l if node_axis else 0


def _node_slice(vec: jnp.ndarray, node_axis: Optional[str], n_l: int):
    """Local [.., N_l] slice of a node-replicated [.., N] array."""
    if not node_axis:
        return vec
    return lax.dynamic_slice_in_dim(
        vec, _node_off(node_axis, n_l), n_l, axis=vec.ndim - 1)


def _member_ids(ids: jnp.ndarray, cols: jnp.ndarray) -> jnp.ndarray:
    """[P, K] GLOBAL node ids x [N_l] global column ids -> [P, N_l]
    membership, as K unrolled broadcast-compares ORed together.

    Deliberately NOT a scatter: scatters materialize the [P, N_l] bool in
    HBM, while compares fuse into whatever elementwise consumer follows
    (the score build) — at the north-star scale that removes ~1 GB of
    write+read traffic per mask.  -1 ids never match (cols >= 0), and
    column ids are global, so the result is node-shard invariant by
    construction."""
    out = None
    for k in range(ids.shape[1]):
        m = ids[:, k][:, None] == cols[None, :]
        out = m if out is None else (out | m)
    if out is None:  # K == 0
        return jnp.zeros((ids.shape[0], cols.shape[0]), jnp.bool_)
    return out


def _in_id_list(node: jnp.ndarray, id_list: list[jnp.ndarray]) -> jnp.ndarray:
    """[P] node id -> [P] bool: held by any of the [P] id columns."""
    out = jnp.zeros(node.shape[0], jnp.bool_)
    for ids in id_list:
        out = out | ((node == ids) & (node >= 0))
    return out


def _gather_cols(
    mat: jnp.ndarray,  # [P, N_l]
    rows: jnp.ndarray,  # [P] row ids
    cols_global: jnp.ndarray,  # [P] GLOBAL column ids (>= 0)
    node_axis: Optional[str],
) -> jnp.ndarray:
    """mat[rows, cols] with global column ids: the owner shard supplies the
    value, a masked psum over the node axis delivers it everywhere."""
    n_l = mat.shape[1]
    loc = cols_global - _node_off(node_axis, n_l)
    ok = (loc >= 0) & (loc < n_l)
    vals = mat[rows, jnp.clip(loc, 0, n_l - 1)]
    if not node_axis:
        return vals
    return lax.psum(jnp.where(ok, vals, 0.0), node_axis)


def _row_min_global(mat: jnp.ndarray, node_axis: Optional[str]):
    """Per-row min over the full (sharded) column axis."""
    m = jnp.min(mat, axis=1)
    return lax.pmin(m, node_axis) if node_axis else m


def _combine_min2(
    best_l: jnp.ndarray,  # [P] local best (priced)
    choice_g: jnp.ndarray,  # [P] GLOBAL id of local argmin
    second_l: jnp.ndarray,  # [P] local second-best
    raw_l: jnp.ndarray,  # [P] unpriced score at the local argmin
    node_axis: Optional[str],
):
    """Merge per-shard (min, argmin, second, raw-at-min) into global stats.

    Global second = min(second of the winning shard, best of every other
    shard).  Ties in best break toward the lowest shard index = lowest
    global node id, preserving the replicated-node tie-break order."""
    if not node_axis:
        return best_l, choice_g, second_l, raw_l
    bests = lax.all_gather(best_l, node_axis)  # [ns, P]
    choices = lax.all_gather(choice_g, node_axis)
    seconds = lax.all_gather(second_l, node_axis)
    raws = lax.all_gather(raw_l, node_axis)
    ns = bests.shape[0]
    k_star = jnp.argmin(bests, axis=0)  # [P]

    def take(a):
        return jnp.take_along_axis(a, k_star[None, :], axis=0)[0]

    others = jnp.where(
        jnp.arange(ns)[:, None] == k_star[None, :], jnp.inf, bests)
    second = jnp.minimum(take(seconds), jnp.min(others, axis=0))
    return take(bests), take(choices), second, take(raws)


def _axis_size(axis_name: str):
    """``lax.axis_size`` appeared in newer JAX; ``psum(1, axis)`` is the
    long-standing equivalent on older pins (e.g. 0.4.x)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _shard_capacity(cap: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    """Split global per-node capacity into integral per-shard shares.

    Fractional caps + the first-bidder progress rule would overshoot, so
    each shard gets floor(cap/ns) with the remainder rotated by node index
    so no shard systematically holds the extras.
    """
    if not axis_name:
        return cap
    ns = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    base_cap = jnp.floor(cap / ns)
    rem = cap - base_cap * ns
    node_ids = jnp.arange(cap.shape[0], dtype=jnp.int32)
    extra = ((node_ids + idx) % ns) < rem.astype(jnp.int32)
    return base_cap + extra.astype(jnp.float32)


def _segment_accept(
    node_s: jnp.ndarray,  # [K] node ids, sorted so equal nodes are adjacent
    ok_s: jnp.ndarray,  # [K] participating entries
    w_s: jnp.ndarray,  # [K] weights (0 where not participating)
    cap_here: jnp.ndarray,  # [K] per-entry capacity budget (node's cap)
) -> jnp.ndarray:
    """Per-node prefix acceptance: keep entries while the running weight on
    their node fits ``cap_here``; the first entry per node always fits if
    the node has any capacity (the auction's progress rule).  The single
    capacity-acceptance idiom shared by the auction rounds and the
    warm-start pins — one accept rule, enforced identically in both."""
    csum = jnp.cumsum(w_s)
    ecs = csum - w_s  # exclusive prefix over ALL entries
    seg_start = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), node_s[1:] != node_s[:-1]])
    seg_base = lax.cummax(jnp.where(seg_start, ecs, -jnp.inf))
    before_me = ecs - seg_base  # weight of earlier entries on my node
    return ok_s & (
        (before_me + w_s <= cap_here) | (before_me == 0.0) & (cap_here > 0))


def _pin_prev_holders(
    prev_slot: jnp.ndarray,  # [P] node id or -1
    pin_ok: jnp.ndarray,  # [P] eligible to keep its previous node
    pweights: jnp.ndarray,  # [P]
    cap: jnp.ndarray,  # [N] GLOBAL capacity for this state
    slack: jnp.ndarray,  # [P] per-holder capacity tolerance (stickiness)
    axis_name: Optional[str],
    load_div: Optional[jnp.ndarray] = None,  # [N] node weight (>= 1) —
    # converts held weight into the score's count/weight units
    taken_stack: Optional[jnp.ndarray] = None,  # [P, T] GLOBAL node ids
    # this row's partition already occupies (other states / ordinals)
) -> jnp.ndarray:
    """Capacity-capped warm start: returns pinned[P] bool.

    The keep-ceiling per node is  max(fair-share quota,
    (least-loaded-open-node's score-load + stickiness) * node_weight) —
    the batch spelling of the reference's marginal rule (plan.go:654-662
    + the traced self-inclusive count: a holder keeps its node iff its
    node's load minus stickiness still beats the emptiest candidate).
    Consequences, each pinned by a test: a fresh node pulls load only
    from nodes more than ``stickiness`` above it (2 copies + 1 fresh
    node -> one moves); +-1 capacity-quantization fixpoints replan
    unchanged (ceil-cap overshoot sits inside the lmin+stickiness
    band); delta rebalances shed only the load above the band instead
    of trimming every over-quota node to its exact share (churn stays
    near the sequential oracle's).  Holders are kept in partition order
    (deterministic), except that holders barred from the emptiest node
    by same-partition exclusivity keep their place first (see trim).
    The first holder per node always stays (auction progress rule).
    Everything else goes to the auction.
    """
    p = prev_slot.shape[0]
    n = cap.shape[0]
    safe = _drop_empty(prev_slot, n)
    pin_w = jnp.where(pin_ok, pweights, 0.0)
    node_w_local = jnp.zeros(n, jnp.float32).at[safe].add(pin_w, mode="drop")
    # Load and over-capacity are GLOBAL questions — under shard_map each
    # shard holds an arbitrary subset of a node's holders, so the shard-
    # local weight says nothing about whether the node is full.
    node_w = _psum(node_w_local, axis_name)
    # Least-loaded OPEN node in score units (held weight / node weight);
    # the minimum runs over nodes that can accept load (cap > 0 —
    # removed nodes can't fake an empty target).  Anchors the marginal
    # keep-ceiling below.
    div = load_div if load_div is not None else jnp.ones(n, jnp.float32)
    load = node_w / div
    lmin = jnp.min(jnp.where(cap > 0, load, jnp.inf)) if n else jnp.inf

    # The trim quota must be shard-local (each shard admits only its
    # integral share of a node's capacity, remainder rotated — the same
    # split the auction uses) or every shard would admit up to the global
    # cap and overshoot by the shard count.
    cap_quota = _shard_capacity(cap, axis_name)

    def keep_all(_):
        # Common case (shrinking/steady cluster: caps only grew): every
        # eligible holder fits — no ordering pass needed.
        return pin_ok

    def trim(_):
        # Some node over-caps (cluster grew, its share shrank): keep
        # holders up to the marginal ceiling.  Within a node group, holders
        # whose partition already occupies the EMPTIEST open node are
        # kept FIRST: exclusivity bars their displaced copy from the one
        # node that needs load, so displacing them instead of a free
        # holder strands the deficit (seen on a 2-node + fresh-node
        # grow: both capacity-displaced primaries landed on the new
        # node, so the replica wave's partition-order trim displaced
        # exactly the two replicas that could not follow).  Ties keep
        # partition order (deterministic).
        if taken_stack is not None:
            deficit_node = jnp.argmin(jnp.where(cap > 0, load, jnp.inf))
            blocked = jnp.any(taken_stack == deficit_node, axis=1)
            perm1 = jnp.argsort((~blocked).astype(jnp.int32), stable=True)
        else:
            perm1 = jnp.arange(p)
        sort_node = jnp.where(pin_ok, prev_slot, n)
        perm2 = jnp.argsort(sort_node[perm1], stable=True)  # groups by node
        perm = perm1[perm2]
        node_s = sort_node[perm]
        ok_s = pin_ok[perm]
        w_s = jnp.where(ok_s, pweights[perm], 0.0)
        # Marginal keep-ceiling (docstring): fair-share quota, or the
        # emptiest open node's load plus the holder's stickiness in the
        # node's weight units — whichever is larger.  The lmin band is
        # divided by the shard count like the quota: it is a GLOBAL
        # allowance, and each shard orders only its own holders.
        ns = _axis_size(axis_name) if axis_name else 1
        nclip = jnp.clip(node_s, 0, n - 1)
        band = (lmin + slack[perm]) * div[nclip] / ns
        cap_here = jnp.maximum(cap_quota[nclip], band)
        keep_s = _segment_accept(node_s, ok_s, w_s, cap_here)
        return jnp.zeros(p, jnp.bool_).at[perm].set(keep_s)

    return lax.cond(jnp.any(node_w > cap), trim, keep_all, None)


def _sparse_score_cols(
    cols: jnp.ndarray,  # [M, K] GLOBAL node ids; -1 = pad (scores +_INF)
    rows: jnp.ndarray,  # [M] local row ids
    pbase,  # global partition index of local row 0 (jitter)
    *,
    total: jnp.ndarray,  # [N] full fill vector
    total_p: jnp.ndarray,
    w_div: jnp.ndarray,  # [N]
    neg_boost: jnp.ndarray,  # [N]
    valid: jnp.ndarray,  # [N] bool
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    stick_si: jnp.ndarray,  # [P]
    prev_slot: jnp.ndarray,  # [P] global ids
    prev_state: jnp.ndarray,  # [P, R]
    taken_ids: tuple[jnp.ndarray, ...],
    anchors: Optional[jnp.ndarray],  # [P, A] (rules only)
    rules: StateRules,
    jitter_scale: float,
) -> jnp.ndarray:
    """The MATRIX engine's score formula evaluated at gathered columns.

    This is the sparse path's score: term order mirrors run_auction's
    matrix build EXACTLY, so with a saturating shortlist (row r's
    columns = 0..N-1) the [P, N] result is bitwise the dense matrix —
    the foundation of the K = N bit-identity contract.  Pad columns
    (id -1) score +_INF like any forbidden node.  O(M * K) ops and
    HBM traffic; no [P, N] tensor exists."""
    n = w_div.shape[0]
    c = jnp.clip(cols, 0, n - 1)
    okc = cols >= 0
    st = stick_si[rows][:, None]
    score = 0.001 * total[c] / jnp.maximum(total_p, 1.0)
    score = score / w_div[c]
    # Same-ordinal alignment (matrix: -0.01 * _member_ids(prev_slot)).
    score = score - 0.01 * ((prev_slot[rows][:, None] == cols) & okc)
    nb = neg_boost[c]
    score = score + jnp.maximum(nb, jnp.where(nb > 0, st, 0.0))
    sticky = jnp.zeros(cols.shape, jnp.bool_)
    for r in range(prev_state.shape[1]):
        sticky = sticky | ((prev_state[rows, r][:, None] == cols) & okc)
    score = score - st * sticky
    if rules:
        score = score + _hier_tier_at(
            anchors[rows], c, gids, gid_valid, rules)
    taken = jnp.zeros(cols.shape, jnp.bool_)
    for tid in taken_ids:
        taken = taken | ((tid[rows][:, None] == cols) & okc)
    score = score + _INF * (taken | ~valid[c] | ~okc)
    pi = (pbase + rows)[:, None].astype(jnp.int32)
    return score + jitter_scale * jitter_hash(pi, c.astype(jnp.int32))


def _assign_slot(
    min2_fn,  # price_vec[N] -> (best, choice GLOBAL, second, raw-at-choice)
    score_at_fn,  # (rows[K], cols_global[K]) -> unpriced score values [K]
    p: int,
    pweights: jnp.ndarray,  # [P]
    cap: jnp.ndarray,  # [N] weighted capacity for this slot (global)
    price_scale: jnp.ndarray,  # [N] converts accepted weight into score units
    axis_name: Optional[str],
    init_assign: Optional[jnp.ndarray] = None,  # [P] warm-start (or -1)
    init_used: Optional[jnp.ndarray] = None,  # [N] weight behind the warm start
    node_axis: Optional[str] = None,
    topup_share: Optional[jnp.ndarray] = None,  # [N] per-node share for
    # capacity top-ups when rule-constrained demand exceeds the rail
    has_rules: bool = True,  # static: state carries hierarchy rules
    feasible_hint: Optional[jnp.ndarray] = None,  # [P] bool, required when
    # has_rules=False and topup_share is set: any allowed node exists
    allow: Optional[jnp.ndarray] = None,  # [P] bool — rows the caller
    # permits to take a slot here at all.  The sparse path gates rows
    # whose shortlist cannot reach the globally attainable rule tier:
    # they neither bid nor get forced, staying -1 for the per-row dense
    # fallback instead of silently accepting a worse-tier placement.
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Auction: returns (slot_assign[P] int32 GLOBAL node id or -1, used[N]).

    The score is reached ONLY through the two callables, so the caller
    chooses the engine: a materialized [P, N_l] matrix (min2_fn = the
    priced Pallas reduction or the XLA reference over it), or the fused
    in-kernel score (ops/score_fused.py) where the matrix never exists.
    Both must include the deterministic tie-break jitter and fold
    forbidden columns in as +_INF.

    Each round: bid on the best open node, accept most-urgent bidders up to
    remaining capacity (at least the first bidder per node, to guarantee
    progress), repeat.  Ends when everyone is assigned or nothing moved.
    ``init_assign``/``init_used`` seed the loop with pre-pinned placements
    (the warm start); pinned partitions never rebid.

    When a round accepts NOTHING while bidders remain — hierarchy rules can
    owe one rack far more copies than the global capacity rail allots it
    (e.g. every heavy node on one rack: the light racks then owe most
    replicas) — the rail is raised instead of abandoned: every node gains
    its ``topup_share`` of the remaining unassigned weight and the priced
    rounds continue.  This keeps per-node acceptance discipline for
    rule-constrained overflow, where the one-shot force step would herd
    stragglers onto the locally-cheapest node (measured within-rack
    replica spread 16..29 vs the greedy oracle's 20..21 on a weighted
    3-rack fuzz seed; with top-up both sit at ~1).

    Partition axis: entirely shard-local — the caller hands each shard its
    slice of capacity and psums the returned per-node usage afterwards, so
    shards may take different round counts.  Node axis: the callables see
    only this shard's columns while cap/price/used stay replicated [N];
    each round runs one all_gather (per-row min stats) inside min2_fn —
    everything else is identical replicated math on every node shard.
    """
    n = cap.shape[0]

    # Loop-invariant: phase B consults the unpriced per-row best to decide
    # whether a straggler still has rule-satisfying options.  Computed once
    # here (min2 at price 0) — XLA cannot hoist a [P, N] reduction out of
    # the while_loop body on its own.  Rule-LESS states have no tiers to
    # reason about (the boost term is a preference, not a constraint), so
    # their gates are structurally pass-through and this whole pass is
    # skipped; hard feasibility then comes from the caller's id-column
    # count (feasible_hint) instead of a row-min.
    if has_rules:
        raw_best_all, _, _, _ = min2_fn(jnp.zeros(n, jnp.float32))
        hard_feasible = raw_best_all < _INF / 2
    else:
        raw_best_all = None
        hard_feasible = feasible_hint
    if allow is not None and hard_feasible is not None:
        hard_feasible = hard_feasible & allow

    def round_body(carry):
        slot_assign, unassigned, rem_cap, used, _progress, it = carry

        # Price: weight already accepted this slot raises a node's cost as
        # if the counts term had updated, so bids keep spreading even
        # within one slot wave; closed nodes cost +_INF.
        # The fused (min, argmin, second-min) over score + price runs in
        # one HBM pass with the price row folded in VMEM via the Pallas
        # kernel on TPU (blance_tpu/ops/reduce2.py); the XLA spelling
        # (priced [P, N] materialization + 3 reductions) elsewhere.
        price_vec = used * price_scale + jnp.where(rem_cap > 0, 0.0, _INF)
        best, choice, second, raw_choice = min2_fn(price_vec)
        margin = jnp.clip(jnp.nan_to_num(second - best, posinf=10.0), 0.0, 10.0)

        # Rules-first gate (mirrors phase B's soft_ok): when every node
        # at the partition's best attainable rule TIER is priced closed
        # — common under shard_map, where each shard holds only 1/ns of
        # a node's capacity — the priced argmin falls through to a
        # worse-tier node.  Don't bid it: wait for top-up/force, which
        # prefer the best-tier nodes (rule conformance beats balance,
        # like the reference's hierarchy-pass-first ordering,
        # plan.go:174-226).  Tier equality is a band test against the
        # unpriced row-min: within-tier terms stay far below the
        # _RULE_TIER step.  Unattainable rules (row-min at _RULE_MISS)
        # fall back flat and accept any feasible node.
        rule_ok = ((raw_choice < raw_best_all + _RULE_TIER * 0.5)
                   | (raw_best_all >= _RULE_MISS / 2)) if has_rules else True
        active = unassigned & (best < _INF / 2) & rule_ok
        if allow is not None:
            active = active & allow

        # Sort bidders by (node, urgency desc) via two stable argsorts —
        # avoids packing into int64, which is x64-gated.  Inactive bidders
        # sort to the end.
        inv_margin = jnp.where(active, -margin, jnp.inf)
        sort_choice = jnp.where(active, choice, n)
        perm1 = jnp.argsort(inv_margin, stable=True)
        perm2 = jnp.argsort(sort_choice[perm1], stable=True)
        perm = perm1[perm2]

        choice_s = choice[perm]
        w_s = pweights[perm]
        active_s = active[perm]

        accept_s = _segment_accept(
            choice_s, active_s, jnp.where(active_s, w_s, 0.0),
            rem_cap[choice_s])

        accept = jnp.zeros(p, jnp.bool_).at[perm].set(accept_s)
        slot_assign = jnp.where(accept, choice, slot_assign)
        unassigned = unassigned & ~accept

        used_round = jnp.zeros(n, jnp.float32).at[choice].add(
            jnp.where(accept, pweights, 0.0))
        rem_cap = rem_cap - used_round
        used = used + used_round

        # Phase B — waterfall: stragglers rejected above would all rebid on
        # the single cheapest node next round (converging linearly), so
        # instead rank them by urgency and pour them into the remaining
        # capacity of nodes ordered by price.  Hard-forbidden matches and
        # rule-missing matches (when the partition still has rule-satisfying
        # options) are skipped and retry next round.
        price = used * price_scale
        node_order = jnp.argsort(price)
        # Clamp: the first-bidder progress rule can drive rem_cap negative
        # (oversize partition into a capacity remainder); cum_rem must stay
        # non-decreasing for searchsorted to be meaningful.
        rem_sorted = jnp.maximum(rem_cap, 0.0)[node_order]
        cum_rem = jnp.cumsum(rem_sorted)

        straggler = active & ~accept
        skey = jnp.where(straggler, -margin, jnp.inf)
        sperm = jnp.argsort(skey, stable=True)
        s_mask = straggler[sperm]
        s_w = jnp.where(s_mask, pweights[sperm], 0.0)
        s_excl = jnp.cumsum(s_w) - s_w
        pos = jnp.searchsorted(cum_rem, s_excl + 0.5 * s_w, side="right")
        in_range = pos < n
        choice2 = node_order[jnp.clip(pos, 0, n - 1)]

        raw2 = score_at_fn(sperm, choice2)
        hard_ok = raw2 < _INF / 2
        # Same tier-aware gate as phase A: the waterfall may only place a
        # partition at its best attainable tier — a capacity-ordered
        # target at a worse tier is skipped and retried next round (the
        # audit counts any tier downgrade as a hierarchy miss).
        soft_ok = ((raw2 < raw_best_all[sperm] + _RULE_TIER * 0.5)
                   | (raw_best_all[sperm] >= _RULE_MISS / 2)) \
            if has_rules else True
        accept2_s = s_mask & in_range & hard_ok & soft_ok

        accept2 = jnp.zeros(p, jnp.bool_).at[sperm].set(accept2_s)
        choice2_un = jnp.zeros(p, jnp.int32).at[sperm].set(choice2)
        slot_assign = jnp.where(accept2, choice2_un, slot_assign)
        unassigned = unassigned & ~accept2

        used2 = jnp.zeros(n, jnp.float32).at[choice2_un].add(
            jnp.where(accept2, pweights, 0.0))
        rem_cap = rem_cap - used2
        used = used + used2

        progress = jnp.any(accept | accept2)
        if topup_share is not None:
            # Stalled with FEASIBLE bidders left: raise the rail by each
            # node's share of their remaining weight and keep the priced
            # rounds going (see docstring).  Hard-infeasible stragglers
            # (no valid node at any price — raw_best_all >= _INF/2) must
            # not force extra rounds: only the force step can resolve
            # them, so without a feasible bidder the loop still exits on
            # the first stalled round.  Share-0 (invalid) nodes get no
            # top-up and stay closed.
            rem_w = jnp.sum(jnp.where(
                unassigned & hard_feasible, pweights, 0.0))
            stalled = ~progress & (rem_w > 0)
            topup = jnp.ceil(rem_w * topup_share)
            rem_cap = jnp.where(stalled, rem_cap + topup, rem_cap)
            progress = progress | (stalled & jnp.any(topup > 0))
        return (slot_assign, unassigned, rem_cap, used, progress, it + 1)

    def round_cond(carry):
        _, unassigned, _, _, progress, it = carry
        return jnp.any(unassigned) & progress & (it < _MAX_AUCTION_ROUNDS)

    if init_assign is None:
        init_assign = jnp.full(p, -1, jnp.int32)
    if init_used is None:
        init_used = jnp.zeros(n, jnp.float32)
    init = (
        init_assign,
        init_assign < 0,
        cap - init_used,
        init_used,
        jnp.array(True),
        jnp.array(0, jnp.int32),
    )
    for ax in (axis_name, node_axis):
        if not ax:
            continue
        # Freshly-created carries are axis-invariant until the (shard-local)
        # loop body makes them varying; mark them varying up front so carry
        # types agree.  Skip values that are already varying.  Pre-vma JAX
        # (the check_rep model: no pcast/pvary) has no varying-axes types
        # to reconcile, so there is nothing to mark.
        if hasattr(lax, "pcast"):
            _to_varying = lambda x: lax.pcast(x, (ax,), to="varying")
        elif hasattr(lax, "pvary"):
            _to_varying = lambda x: lax.pvary(x, (ax,))
        else:
            continue
        _typeof = jax.typeof if hasattr(jax, "typeof") else jax.core.get_aval

        def ensure_varying(x):
            vma = getattr(_typeof(x), "vma", frozenset())
            return x if ax in vma else _to_varying(x)
        init = tuple(ensure_varying(x) for x in init)
    slot_assign, unassigned, _rem, used, _, _ = lax.while_loop(
        round_cond, round_body, init)

    # Force step: remaining partitions take their best feasible node,
    # ignoring capacity (constraint satisfaction beats balance).  Price on
    # the GLOBAL usage (one [N] psum): each shard's force sees every
    # shard's accepted weight, or all shards would pile their stragglers
    # onto the same locally-cheapest node.  Skipped entirely (a full
    # [P, N] pass saved) when the rounds assigned everyone — the common
    # case.  The psum runs unconditionally; inside the branch only
    # node-axis collectives can occur, and ``unassigned`` is replicated
    # along the node axis, so every participant of those collectives
    # agrees on the branch.
    used_global = _psum(used, axis_name)

    def do_force(args):
        slot_assign, unassigned, used = args
        best, choice, _second, _raw = min2_fn(
            used_global * price_scale)
        feasible = best < _INF / 2
        forced = unassigned & feasible
        if allow is not None:
            forced = forced & allow
        slot_assign = jnp.where(forced, choice, slot_assign)
        used_forced = jnp.zeros(n, jnp.float32).at[choice].add(
            jnp.where(forced, pweights, 0.0))
        return slot_assign, used + used_forced

    def skip_force(args):
        slot_assign, _unassigned, used = args
        return slot_assign, used

    slot_assign, used = lax.cond(
        jnp.any(unassigned), do_force, skip_force,
        (slot_assign, unassigned, used))

    return slot_assign, used


def _solve_assign(
    prev: jnp.ndarray,  # [P, S, R] int32 (GLOBAL node ids)
    pweights: jnp.ndarray,  # [P] float32
    nweights: jnp.ndarray,  # [N] float32 (full, node-replicated)
    valid: jnp.ndarray,  # [N] bool (full)
    stickiness: jnp.ndarray,  # [P, S] float32
    gids: jnp.ndarray,  # [L, N] int32 (full)
    gid_valid: jnp.ndarray,  # [L, N] bool (full)
    constraints: Constraints,  # static, per-state slot counts
    rules: Rules,  # static, per-state tuple of (inc, exc) pairs
    axis_name: Optional[str] = None,  # static; set under shard_map
    node_axis: Optional[str] = None,  # static; second mesh axis over nodes
    node_shards: int = 1,  # static; size of the node axis (N must divide)
    fused_score: str = "off",  # static; "off" = materialized score matrix,
    # "on" = in-kernel score (ops/score_fused.py, TPU), "interpret" =
    # in-kernel via the pallas interpreter (CPU tests)
    carry_used: Optional[jnp.ndarray] = None,  # [S, N] warm-start seed:
    # per-state per-node fill from the previous converged solve
    # (SolveCarry.used).  MUST equal the scatter of ``prev`` (the session
    # invalidates the carry whenever prev drifts); seeding replaces the
    # S + 1 seed scatters with lookups, bit-identically.
    p_real: Optional[jnp.ndarray] = None,  # traced scalar: the GLOBAL
    # count of REAL partitions when the arrays carry inert padding rows
    # (shape bucketing).  Keeps the advisory fill factor's denominator —
    # the one place the partition COUNT (not weight) enters the score —
    # identical to the unpadded solve, so bucketing is bit-neutral.
    # Traced, not static: drifting real sizes inside one bucket must not
    # retrigger compilation.
    shortlist: Optional[jnp.ndarray] = None,  # [P, K] GLOBAL candidate
    # node ids per partition (-1 pads), ascending per row — the SPARSE
    # engine.  Scores are evaluated only at these columns ([P, S, K]
    # work per sweep) while fill/price/capacity stay full [S, N] width,
    # so acceptance and the audit contracts run against real global
    # state.  A saturating shortlist (row r = 0..N-1) is bit-identical
    # to the dense engines.
    sparse_impl: str = "xla",  # static: "xla" reference reduction,
    # "pallas" = the fused ops/sparse2.py kernel, "interpret" = that
    # kernel under the pallas interpreter (CPU tests)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One assignment sweep; returns (assign[P, S, R], exhausted[P]).

    ``exhausted`` is all-False on the dense engines; on the sparse
    engine it flags rows whose shortlist could not reach the globally
    attainable rule tier (or had no feasible candidate) for some slot —
    the rows the caller must re-place through the per-row dense
    fallback.

    With ``node_axis`` set (a 2-D parts x nodes mesh), every [P, N]
    intermediate — score, penalties, stickiness/taken masks — holds only
    this shard's N/node_shards columns, while [N] vectors (counts,
    capacity, prices) stay replicated along the node axis: at the
    north-star scale those are kilobytes and keeping them replicated makes
    all capacity/acceptance logic identical math on every node shard.
    Node ids in prev/assign are global throughout."""
    p, s, r_max = prev.shape
    n = nweights.shape[0]
    if fused_score not in ("off", "on", "interpret"):
        # "auto" must be resolved by resolve_fused_score BEFORE jit; a
        # silent passthrough here would select the compiled kernel on
        # hosts that can't run it.
        raise ValueError(f"unresolved fused-score mode: {fused_score!r}")
    if shortlist is not None:
        if node_axis:
            raise ValueError(
                "sparse solve does not support node-axis sharding: the "
                "[P, K] shortlist already bounds the column working set; "
                "shard the partition axis instead")
        if sparse_impl not in ("xla", "pallas", "interpret"):
            raise ValueError(f"unknown sparse_impl: {sparse_impl!r}")
        if not all(exc < inc for rl in rules for (inc, exc) in rl):
            # The shortlist-exhaustion gate needs the group-counting
            # attainability floor, which only exists for nesting rules
            # (exclude strictly finer than include — the tree shape).
            raise ValueError(
                "sparse solve requires nesting hierarchy rules "
                "(exclude_level < include_level for every rule); use the "
                "dense engines for exotic rule shapes")
    if constraints and max(constraints) > r_max:
        # JAX drops out-of-bounds scatter writes silently; without this the
        # slots beyond R would vanish while still consuming capacity.
        raise ValueError(
            f"prev slot depth R={r_max} < max constraints {max(constraints)}")
    if n % node_shards:
        raise ValueError(
            f"N={n} not divisible by node_shards={node_shards}; pad nodes")
    n_l = n // node_shards
    noff = _node_off(node_axis, n_l)
    valid_l = _node_slice(valid, node_axis, n_l)
    gids_l = _node_slice(gids, node_axis, n_l)

    if p_real is not None:
        total_p = jnp.asarray(p_real, jnp.float32)  # global: no psum
    else:
        total_p = _psum(jnp.array(p, jnp.float32), axis_name)
    total_w = _psum(jnp.sum(pweights), axis_name)

    w_div = jnp.where(nweights > 0, nweights, 1.0)
    neg_boost = jnp.where(nweights < 0, -nweights, 0.0)  # [N]

    # Jitter sits deliberately ABOVE the 0.001/P fill factor: the fill term
    # is an advisory nudge (as in the reference's 0.001 filled-factor,
    # plan.go:647-651), while real balance is owned by the capacity rail and
    # the in-slot price.  Letting jitter dominate the fill signal keeps
    # bids spread across near-equal nodes; herding bids by the fill
    # ordering fragments capacity and forces cap overflows (measured: slot
    # spread 12-20 vs 15-17 at 256x16).
    jitter_scale = jnp.float32(_JITTER)

    # Negative-weight (booster-steered) nodes get NO fair-share capacity:
    # the cbgt booster semantics make them last-resort targets (greedy
    # adds max(-w, stickiness) to their score, plan.go:675-684), so the
    # rail must not reserve a share for them — new load overflows onto
    # them only through the capacity-ignoring force step.  Their existing
    # sticky holders survive via pin slack when -w <= stickiness (the
    # same marginal rule the greedy applies).
    cap_w = jnp.where(valid & (nweights >= 0), jnp.maximum(nweights, 1.0),
                      0.0)
    cap_share = cap_w / jnp.maximum(jnp.sum(cap_w), 1.0)

    # Seed the total-fill factor from prev (plan.go:94).  Per-state counts
    # are NOT part of the batch score: every partition of a state reassigns
    # simultaneously, so the state's own counts are zero at wave start, and
    # carrying intra-wave counts across slots lets +-cap quantization noise
    # (several units) swamp the 1.5 stickiness bonus and cause churn.  The
    # capacity rail + in-slot price own balance instead.
    # A warm start reads the seed straight off the carry (which was built
    # with the same per-state scatters, in the same summation order, from
    # the same assignment) instead of re-scattering prev.
    if carry_used is not None:
        total = jnp.sum(carry_used, axis=0)
    else:
        total = jnp.sum(
            jnp.stack([_scatter_counts(prev[:, si, :], pweights, n)
                       for si in range(s)]), axis=0)
        total = _psum(total, axis_name)

    assign = jnp.full((p, s, r_max), -1, jnp.int32)
    # Sparse-engine escape hatch: rows whose shortlist could not serve
    # some slot (all-False on the dense engines, and on fully-pinned
    # slots — a pinned copy proved its tier through the pin pass).
    exhausted = jnp.zeros(p, jnp.bool_)
    # Nodes already holding this partition at an equal-or-higher priority
    # state in this pass (excludeHigherPriorityNodes, plan.go:146-156).
    # Kept as a LIST of [P] global-id columns, not a [P, N] bitmap: the
    # list stays kilobytes, membership tests become fusable compares (see
    # _member_ids), and global ids make every test node-shard invariant
    # with no psum gathers.
    taken_ids: list[jnp.ndarray] = []
    # Global column ids of this shard's node window (noff = 0 unsharded).
    cols_l = jnp.arange(n_l, dtype=jnp.int32) + noff

    top_anchor = prev[:, 0, 0]  # previous primary, until slot (0,0) assigns

    for si in range(s):
        k = constraints[si]
        if k <= 0:
            continue

        # All of this state's prev holders re-assign in this wave: remove
        # their seed contribution up front (the batch analog of the
        # per-partition decrement at plan.go:290-297).  Warm starts read
        # the per-state row off the carry (same psum-of-scatter, bitwise).
        if carry_used is not None:
            state_prev = carry_used[si]
        else:
            state_prev = _psum(_scatter_counts(prev[:, si, :], pweights, n),
                               axis_name)
        total = total - state_prev

        # Held this state before (fusable compares, no scatter).
        prev_state_ids = prev[:, si, :]  # [P, R]

        anchor = jnp.where(assign[:, 0, 0] >= 0, assign[:, 0, 0], top_anchor) \
            if si > 0 else top_anchor

        # Warm start, decided per STATE across all k ordinals: a previous
        # holder whose node survives, isn't taken by a higher-priority
        # state, and sits at the best attainable rule tier keeps its place
        # up to the node's state-level capacity — churn becomes structural,
        # not a price-dynamics accident (the batch analog of stickiness,
        # plan.go:654-662).  State-level, because ordinal packing within a
        # state is arbitrary (a node legitimately holds many slot-1 copies
        # if it holds few slot-0 copies); judging pins per slot would trim
        # balanced placements and break the replan fixpoint.
        kk = min(k, r_max)
        prev_k = prev[:, si, :kk]  # [P, kk]
        safe_k = jnp.clip(prev_k, 0, n - 1)
        taken_prev = jnp.stack(
            [_in_id_list(prev_k[:, j], taken_ids) for j in range(kk)],
            axis=1)
        # Booster-steered nodes: a holder stays only while the boost does
        # not exceed its stickiness (greedy: +max(-w, stick) - stick <= 0
        # keeps, > 0 pushes off, plan.go:675-684 + the cbgt booster).
        pin_ok_k = (prev_k >= 0) & valid[safe_k] & ~taken_prev & \
            (neg_boost[safe_k] <= stickiness[:, si][:, None])
        # An externally supplied prev map can repeat a node within one
        # state's row; only the first occurrence may pin, or both copies
        # would keep the same node — a duplicate the auction's exclusivity
        # mask can no longer prevent (the converged loop would then carry
        # it forever).  kk is small, so the pairwise check unrolls.
        for j in range(1, kk):
            dup = jnp.zeros(p, jnp.bool_)
            for i in range(j):
                dup |= (prev_k[:, j] == prev_k[:, i]) & (prev_k[:, j] >= 0)
            pin_ok_k = pin_ok_k.at[:, j].set(pin_ok_k[:, j] & ~dup)
        # Rule anchors for this state: column 0 is the primary anchor;
        # column 1+j is ordinal j's node once pinned/assigned.  Grown
        # ordinal-by-ordinal so every pick's penalty sees all prior picks
        # (reference plan.go:185-191) — this is what spreads replica pairs
        # across racks, not just replicas away from the primary.
        anchors = (jnp.full((p, 1 + k), -1, jnp.int32).at[:, 0].set(anchor)
                   if rules[si] else None)
        if rules[si]:
            # Pin eligibility, decided sequentially: a pin must sit at the
            # best attainable rule tier GIVEN the copies already kept
            # (primary + earlier ordinals' pin candidates) — the 1e4 tier
            # gap outweighs stickiness in the auction, and pinning must not
            # override that; nor may two surviving replicas stay co-racked.
            # Deliberately pre-capacity-trim: if the earlier pin is later
            # trimmed, a co-racked later ordinal loses its pin too — but the
            # anchors re-seed below drops the trimmed rack, and stickiness
            # steers the displaced copy back to its own node in the auction,
            # so the corner costs at most one extra converge pass, never a
            # rule violation.
            # Exclude groups nest inside include groups whenever the rule's
            # exclude level is strictly finer (the normal tree shape), and
            # then the attainable-tier floor reduces to group counting —
            # [P] gathers instead of a [P, N] penalty matrix + row-min.
            # Exotic rules (exc >= inc) keep the matrix path.
            counts_ok = all(exc < inc for (inc, exc) in rules[si])
            rows1 = jnp.arange(p)
            for j in range(kk):
                if counts_ok:
                    floor_j = _hier_floor_counts(
                        anchors[:, :1 + j], gids, gid_valid, valid,
                        rules[si])
                    hier_at_prev = _hier_tier_at(
                        anchors[:, :1 + j], safe_k[:, j], gids, gid_valid,
                        rules[si])
                else:
                    hier_j = _hier_penalty(
                        anchors[:, :1 + j], gids, gid_valid, rules[si],
                        gids_cand=gids_l)
                    floor_j = _row_min_global(
                        jnp.where(valid_l[None, :], hier_j, _INF), node_axis)
                    hier_at_prev = _gather_cols(
                        hier_j, rows1, safe_k[:, j], node_axis)
                ok_j = pin_ok_k[:, j] & (
                    hier_at_prev < floor_j + _RULE_TIER * 0.5)
                pin_ok_k = pin_ok_k.at[:, j].set(ok_j)
                anchors = anchors.at[:, 1 + j].set(
                    jnp.where(ok_j, prev_k[:, j], -1))
        state_cap = jnp.ceil(k * total_w * cap_share)
        pins_flat = _pin_prev_holders(
            prev_k.reshape(-1),
            pin_ok_k.reshape(-1),
            jnp.repeat(pweights, kk),
            state_cap,
            jnp.repeat(stickiness[:, si], kk),
            axis_name,
            load_div=w_div,
            taken_stack=(jnp.repeat(jnp.stack(taken_ids, axis=1), kk, axis=0)
                         if taken_ids else None),
        )
        pins = pins_flat.reshape(p, kk)
        # Same-partition exclusivity: later ordinals' pins must be visible
        # to earlier ordinals' auctions, or a displaced slot-0 copy could
        # land on the node slot-1 keeps pinned.  Each pin column is later
        # OVERWRITTEN by its ordinal's slot assignment (a superset: the
        # slot result keeps every pin), so the list stays one column per
        # slot instead of two.
        pin_base = len(taken_ids)
        for j in range(kk):
            taken_ids.append(jnp.where(pins[:, j], prev_k[:, j], -1))
        if rules[si]:
            # Re-seed anchors from the capacity-trimmed pins: a trimmed pin
            # must not keep excluding its rack from the auction, while a
            # surviving pin must exclude its rack from EVERY ordinal's
            # auction (including earlier ones — a displaced slot-0 copy may
            # not land in the rack slot-1 keeps pinned).
            anchors = jnp.full((p, 1 + k), -1, jnp.int32).at[:, 0].set(anchor)
            for j in range(kk):
                anchors = anchors.at[:, 1 + j].set(
                    jnp.where(pins[:, j], prev_k[:, j], -1))

        for ri in range(k):
            # This ordinal's share of the state-level pins; only displaced
            # or over-capacity copies enter the auction below.
            if ri < kk:
                init_assign = jnp.where(pins[:, ri], prev[:, si, ri], -1)
            else:
                init_assign = jnp.full(p, -1, jnp.int32)
            pin_used = jnp.zeros(n, jnp.float32).at[
                _drop_empty(init_assign, n)].add(
                jnp.where(init_assign >= 0, pweights, 0.0), mode="drop")

            all_pinned = jnp.all(init_assign >= 0)
            if axis_name:
                all_pinned = lax.psum(
                    (~all_pinned).astype(jnp.int32), axis_name) == 0

            def run_auction(_, *, ri=ri, anchors=anchors,
                            taken_ids=tuple(taken_ids)):
                """Score + auction + force for this slot — the expensive
                path, skipped entirely when every copy pinned (converged
                passes of solve_dense_converged land here for every slot,
                so the confirming pass never touches a [P, N] tensor).
                Two engines behind _assign_slot's callables: the default
                MATRIX path builds score[P, N_l] from fusable compares
                (scatter-free — the compares fuse into the elementwise
                build) and reduces it with the priced Pallas kernel; the
                FUSED path (ops/score_fused.py) computes the score
                in-kernel from the same id columns, so the matrix never
                exists and every round's HBM traffic is O(P + N)."""
                total_l = _node_slice(total, node_axis, n_l)
                w_div_l = _node_slice(w_div, node_axis, n_l)
                neg_boost_l = _node_slice(neg_boost, node_axis, n_l)
                stick_si = stickiness[:, si]
                prev_slot = prev[:, si, ri] if ri < r_max else \
                    jnp.full(p, -1, jnp.int32)
                pbase = lax.axis_index(axis_name) * p if axis_name else 0
                anchors_k = anchors if rules[si] else \
                    jnp.full((p, 1), -1, jnp.int32)

                if shortlist is not None:
                    # SPARSE engine: evaluate the matrix formula only at
                    # the [P, K] shortlist columns; fill/price/capacity
                    # stay full [S, N] width.  min2 reduces the gathered
                    # block (fused kernel on TPU); phase B's waterfall
                    # probes return +INF outside the row's shortlist, so
                    # stragglers never leak past their candidate set.
                    cand = shortlist
                    cand_c = jnp.clip(cand, 0, n - 1)
                    rows_p = jnp.arange(p)
                    score_pk = _sparse_score_cols(
                        cand, rows_p, pbase, total=total, total_p=total_p,
                        w_div=w_div, neg_boost=neg_boost, valid=valid,
                        gids=gids, gid_valid=gid_valid, stick_si=stick_si,
                        prev_slot=prev_slot, prev_state=prev_state_ids,
                        taken_ids=taken_ids, anchors=anchors_k,
                        rules=rules[si], jitter_scale=float(_JITTER))

                    def min2_fn(price_vec, *, score_pk=score_pk,
                                cand=cand, cand_c=cand_c):
                        price_pk = price_vec[cand_c]
                        if sparse_impl == "xla":
                            b, kidx, s2, raw = sparse_min2_reference(
                                score_pk, price_pk)
                        else:
                            b, kidx, s2, raw = sparse_priced_min2(
                                score_pk, price_pk,
                                interpret=(sparse_impl == "interpret"))
                        choice = jnp.maximum(jnp.take_along_axis(
                            cand, kidx[:, None], axis=1)[:, 0], 0)
                        return b, choice, s2, raw

                    def score_at_fn(rows, cols_global, *, cand=cand):
                        vals = _sparse_score_cols(
                            cols_global[:, None], rows, pbase,
                            total=total, total_p=total_p, w_div=w_div,
                            neg_boost=neg_boost, valid=valid, gids=gids,
                            gid_valid=gid_valid, stick_si=stick_si,
                            prev_slot=prev_slot,
                            prev_state=prev_state_ids,
                            taken_ids=taken_ids, anchors=anchors_k,
                            rules=rules[si],
                            jitter_scale=float(_JITTER))[:, 0]
                        in_sl = jnp.any(
                            cand[rows] == cols_global[:, None], axis=1)
                        return jnp.where(in_sl, vals, _INF)
                elif fused_score != "off":
                    si_pack = pack_score_inputs(
                        total_l=total_l, total_p=total_p, w_div_l=w_div_l,
                        neg_boost_l=neg_boost_l, valid_l=valid_l,
                        stickiness_si=stick_si, prev_slot=prev_slot,
                        prev_state=prev_state_ids,
                        taken_ids=list(taken_ids), anchors=anchors_k,
                        gids_l=gids_l, gid_valid=gid_valid, gids=gids,
                        rules=rules[si])

                    vma = tuple(a for a in (axis_name, node_axis) if a)

                    def min2_fn(price_vec):
                        price_l = _node_slice(price_vec, node_axis, n_l)
                        b, cl, s2, raw = fused_score_min2(
                            price_l, si_pack, pbase, noff,
                            nrules=len(rules[si]),
                            jitter_scale=float(_JITTER),
                            interpret=(fused_score == "interpret"),
                            vma=vma)
                        return _combine_min2(
                            b, cl + noff, s2, raw, node_axis)

                    base_full = (0.001 * total
                                 / jnp.maximum(total_p, 1.0)) / w_div

                    def score_at_fn(rows, cols_global):
                        return score_at_columns(
                            rows, cols_global, base_full=base_full,
                            neg_boost_full=neg_boost, valid_full=valid,
                            gids=gids, gid_valid=gid_valid,
                            anchors=anchors_k, rules=rules[si],
                            prev_slot=prev_slot,
                            prev_state=prev_state_ids,
                            taken_ids=taken_ids, stick=stick_si,
                            jitter_scale=float(_JITTER), pbase=pbase)
                else:
                    balance = 0.001 * total_l[None, :] / \
                        jnp.maximum(total_p, 1.0)
                    score = balance / w_div_l[None, :]
                    # Same-ordinal alignment: slot ri mildly prefers prev
                    # slot ri's node (above jitter, below every real
                    # term), so sticky bids don't scramble ordinals and
                    # leftovers stay spread.
                    score = score - 0.01 * _member_ids(
                        prev_slot[:, None], cols_l)
                    score = score + jnp.maximum(
                        neg_boost_l[None, :],
                        jnp.where(neg_boost_l[None, :] > 0,
                                  stick_si[:, None], 0.0))
                    score = score - stick_si[:, None] * _member_ids(
                        prev_state_ids, cols_l)
                    # Per-slot rule penalty: anchored on the primary,
                    # every pinned ordinal, and every slot already
                    # assigned this state — so consecutive replicas
                    # spread across exclusion groups.
                    if rules[si]:
                        score = score + _hier_penalty(
                            anchors, gids, gid_valid, rules[si],
                            gids_cand=gids_l)
                    taken = _member_ids(
                        jnp.stack(taken_ids, axis=1), cols_l) if taken_ids \
                        else jnp.zeros((p, n_l), jnp.bool_)
                    score = score + _INF * (taken | ~valid_l[None, :])
                    # Deterministic tie-break jitter (Weyl hash of GLOBAL
                    # (partition, node) — shard-local indices would make
                    # every shard bid on the same jitter-preferred
                    # columns in lockstep, and break node-shard-count
                    # invariance).
                    pi = (pbase + jnp.arange(p))[:, None].astype(jnp.int32)
                    ni = cols_l[None, :].astype(jnp.int32)
                    score = score + jitter_scale * jitter_hash(pi, ni)

                    def min2_fn(price_vec):
                        price_l = _node_slice(price_vec, node_axis, n_l)
                        if pallas_available():
                            b_l, c_l, s_l = priced_min2_argmin(
                                score, price_l)
                        else:
                            b_l, c_l, s_l = min2_argmin_reference(
                                score + price_l[None, :])
                        raw_l = jnp.take_along_axis(
                            score, c_l[:, None], axis=1)[:, 0]
                        return _combine_min2(
                            b_l, c_l + noff, s_l, raw_l, node_axis)

                    def score_at_fn(rows, cols_global):
                        return _gather_cols(
                            score, rows, cols_global, node_axis)

                if rules[si]:
                    feasible_hint = None
                else:
                    # Rule-less hard feasibility without a [P, N] row-min:
                    # the taken ids are distinct per partition (exclusivity
                    # invariant), so an allowed node exists iff the count
                    # of taken VALID nodes is below the valid-node total.
                    n_valid_total = jnp.sum(valid.astype(jnp.int32))
                    tkn = jnp.zeros(p, jnp.int32)
                    for tid in taken_ids:
                        tkn += ((tid >= 0)
                                & valid[jnp.clip(tid, 0, n - 1)]
                                ).astype(jnp.int32)
                    feasible_hint = tkn < n_valid_total

                allow = None
                exh_slot = jnp.zeros(p, jnp.bool_)
                if shortlist is not None:
                    # Shortlist adequacy, judged against GLOBAL state: a
                    # row may take this slot only when its shortlist
                    # best reaches the globally attainable rule tier
                    # (group-counting floor, taken-aware — [P] ops, no
                    # [P, N] row-min) or, rule-less, offers any feasible
                    # candidate while one exists anywhere.  Inadequate
                    # rows sit out the whole slot (no bid, no force) and
                    # are flagged for the per-row dense fallback; at a
                    # saturating K the shortlist best IS the global
                    # best, so the gate passes exactly when the dense
                    # engines would have placed the row.
                    raw_best_sl = jnp.min(score_pk, axis=1)
                    if rules[si]:
                        floor_sl = _hier_floor_counts(
                            anchors, gids, gid_valid, valid, rules[si],
                            taken_stack=(jnp.stack(taken_ids, axis=1)
                                         if taken_ids else None))
                        allow = raw_best_sl < floor_sl + _RULE_TIER * 0.5
                    else:
                        sl_feas = raw_best_sl < _INF / 2
                        allow = sl_feas | ~feasible_hint
                        # Top-up must weigh shortlist-feasible rows, not
                        # globally-feasible ones the gate excluded.
                        feasible_hint = sl_feas
                    exh_slot = (init_assign < 0) & ~allow

                # Exact ceil capacity: the binding rail that yields tight
                # balance; exclusivity stragglers rebid under the in-slot
                # price and, in the worst case, the force step places them.
                cap = _shard_capacity(
                    jnp.ceil(total_w * cap_share), axis_name)
                slot_assign, used = _assign_slot(
                    min2_fn, score_at_fn, p, pweights, cap, 1.0 / w_div,
                    axis_name, init_assign=init_assign, init_used=pin_used,
                    node_axis=node_axis, topup_share=cap_share,
                    has_rules=bool(rules[si]), feasible_hint=feasible_hint,
                    allow=allow)
                return slot_assign, used, exh_slot

            def keep_pins(_):
                return init_assign, pin_used, jnp.zeros(p, jnp.bool_)

            # NB: no collectives run inside either branch (_assign_slot is
            # shard-local by design), so a cond on the globally-agreed
            # all_pinned flag is safe under shard_map.
            slot_assign, used, exh_slot = lax.cond(
                all_pinned, keep_pins, run_auction, None)
            exhausted = exhausted | exh_slot
            used = _psum(used, axis_name)  # global per-node accepted weight

            assign = assign.at[:, si, ri].set(slot_assign)
            total = total + used
            if ri < kk:
                taken_ids[pin_base + ri] = slot_assign  # supersedes the pin
            else:
                taken_ids.append(slot_assign)
            if rules[si]:
                anchors = anchors.at[:, 1 + ri].set(slot_assign)

    return assign, exhausted


_SOLVE_STATICS = ("constraints", "rules", "axis_name", "node_axis",
                  "node_shards", "fused_score")


@partial(jax.jit, static_argnames=_SOLVE_STATICS)
def solve_dense(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    carry_used: Optional[jnp.ndarray] = None,
    p_real: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Solve the whole placement problem on device; returns assign[P, S, R].

    The jitted dense spelling of :func:`_solve_assign` (see its
    docstring for the full parameter/sharding contract); the sparse
    engine enters through :func:`solve_sparse` instead."""
    return _solve_assign(
        prev, pweights, nweights, valid, stickiness, gids, gid_valid,
        constraints, rules, axis_name, node_axis, node_shards,
        fused_score, carry_used=carry_used, p_real=p_real)[0]


@partial(jax.jit, static_argnames=("constraints", "rules", "axis_name",
                                   "max_iterations", "node_axis",
                                   "node_shards", "fused_score",
                                   "trace_sweeps"))
def _solve_dense_converged_impl(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    max_iterations: int = 10,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    carry_used: Optional[jnp.ndarray] = None,
    p_real: Optional[jnp.ndarray] = None,
    trace_sweeps: bool = False,
) -> tuple[jnp.ndarray, ...]:
    """Jitted fixpoint body; returns (assign, sweeps-executed).

    ``carry_used`` seeds the FIRST sweep only — like cluster deltas
    (plan.go:49-55), the carry describes the state the loop starts from;
    later sweeps re-derive their seed from their own input.

    ``trace_sweeps`` (static) additionally accumulates each sweep's
    accepted-bid fraction — the share of REAL partitions whose
    assignment that sweep changed — in-graph, returning
    (assign, sweeps, fracs[max_iterations]) so the device observatory
    (obs/device.py) can export a convergence track without per-sweep
    host round-trips.  Off (the default) the trace and outputs are
    byte-identical to before the flag existed."""
    def solve(x, cu=None):
        return solve_dense(x, pweights, nweights, valid, stickiness,
                           gids, gid_valid, constraints, rules, axis_name,
                           node_axis, node_shards, fused_score,
                           carry_used=cu, p_real=p_real)

    first = solve(prev, carry_used)

    if not trace_sweeps:
        def cond(carry):
            out, prev_i, it = carry
            changed = jnp.any(out != prev_i)
            if axis_name:
                changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
            return changed & (it < max_iterations)

        def body(carry):
            out, _prev, it = carry
            return solve(out), out, it + 1

        out, _, it = lax.while_loop(cond, body, (first, prev, jnp.array(1)))
        return out, it

    # Traced variant: same fixpoint, plus a [max_iterations] accumulator
    # of per-sweep changed-row fractions.  The denominator is the REAL
    # partition count (p_real under bucketing — pad rows are inert and
    # never change), psum'd across partition shards like every other
    # global count.
    if p_real is not None:
        denom = jnp.maximum(jnp.asarray(p_real, jnp.float32), 1.0)
    else:
        denom = jnp.maximum(
            _psum(jnp.array(prev.shape[0], jnp.float32), axis_name), 1.0)

    def frac(a, b):
        changed = jnp.any(a != b, axis=(1, 2))
        total = jnp.sum(changed.astype(jnp.float32))
        return _psum(total, axis_name) / denom

    fracs0 = jnp.zeros(max_iterations, jnp.float32) \
        .at[0].set(frac(first, prev))

    def cond_t(carry):
        out, prev_i, it, _f = carry
        changed = jnp.any(out != prev_i)
        if axis_name:
            changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
        return changed & (it < max_iterations)

    def body_t(carry):
        out, _prev, it, f = carry
        new = solve(out)
        return new, out, it + 1, f.at[it].set(frac(new, out))

    out, _, it, fracs = lax.while_loop(
        cond_t, body_t, (first, prev, jnp.array(1), fracs0))
    return out, it, fracs


def _record_sweeps(sweeps: object) -> None:
    """Publish a converged solve's pass count to the obs Recorder.

    Silently skipped when ``sweeps`` is a tracer (solve_dense_converged
    runs under shard_map / an outer jit: there is no concrete value at
    trace time, and a host callback would be the wrong cost to pay)."""
    if isinstance(sweeps, jax.core.Tracer):
        return
    try:
        n = int(sweeps)
    except (TypeError, ValueError):
        # A non-scalar/non-numeric sweeps value (an exotic tracer the
        # isinstance above missed, an aborted transfer) — recording is
        # best-effort, correctness errors propagate elsewhere.
        return
    rec = get_recorder()
    rec.count("plan.solve.calls")
    rec.count("plan.solve.sweeps", n)
    rec.observe("plan.solve.sweeps", n)
    rec.set_attr("sweeps", n)


def _check_tier_band_scale(prev, pweights, nweights, valid, stickiness,
                           constraints, rules) -> None:
    """Assert the tier-equality band's scale assumption (see _RULE_TIER).

    Estimates the largest within-tier score mass a node can carry —
    the per-node fill term at its capacity rail AND at the prev map's
    seeded skew, plus max stickiness and max negative-weight boost —
    and raises ValueError when it eats into the _RULE_TIER/2 band
    (headroom _TIER_BAND_HEADROOM).  Rule-less problems never consult
    the band and are exempt.  Host-side only: silently skipped under a
    jit/shard_map trace (the host entry already checked concrete
    values).  Cost: one vectorized bincount over prev (a few ms at
    the 100k-partition north star), memoized per (prev identity,
    weight/stickiness fingerprint) so the steady-state warm-replan loop
    — which passes the SAME adopted ``current`` array replan after
    replan — pays it once, not per solve."""
    if not any(rl for rl in rules):
        return
    from jax import core as _jax_core

    args = (prev, pweights, nweights, valid, stickiness)
    if any(isinstance(a, _jax_core.Tracer) for a in args):
        return
    prev_in = prev
    prev = np.asarray(prev)
    pw = np.asarray(pweights, np.float64)
    nw = np.asarray(nweights, np.float64)
    valid = np.asarray(valid, bool)
    stick = np.asarray(stickiness, np.float64)
    n = nw.shape[0]
    if prev.size == 0 or n == 0:
        return
    # Memo key: array identity + cheap O(P+N) fingerprint.  The
    # fingerprint (not identity alone) guards against id() reuse after
    # gc and against in-place weight edits; a stale hit can only skip a
    # re-check of an already-validated shape, never corrupt a solve.
    key = (id(prev_in), prev.shape, n, tuple(constraints),
           tuple(tuple(r) for r in rules))
    fingerprint = (float(pw.sum()), float(stick.max()) if stick.size else 0.0,
                   float(nw.min()), float(nw.max()), int(valid.sum()))
    if _tier_scale_memo.get(key) == fingerprint:
        return
    total_w = float(pw.sum())
    cap_w = np.where(valid & (nw >= 0), np.maximum(nw, 1.0), 0.0)
    w_div = np.where(nw > 0, nw, 1.0)
    # Balanced ceiling: every constrained slot's capacity rail lands
    # ~K * total_w * share on a node; dividing by the node's weight
    # cancels the share for uniform shares.
    k_total = float(sum(max(int(c), 0) for c in constraints))
    rail_term = k_total * total_w / max(float(cap_w.sum()), 1.0)
    # Skewed seed: the prev map's actual per-node weighted fill
    # (bincount, not add.at — vectorized, so the guard stays a few ms
    # even at 100k partitions and never taxes the warm replan path).
    ids = prev.reshape(prev.shape[0], -1)
    w_rep = np.broadcast_to(pw[:, None], ids.shape)
    m = ids >= 0
    fill = np.bincount(ids[m].ravel(), weights=w_rep[m].ravel(),
                       minlength=n)[:n]
    seed_term = float((fill / w_div).max()) if n else 0.0
    bound = max(rail_term, seed_term)
    bound += float(stick.max()) if stick.size else 0.0
    bound += float(np.maximum(-nw, 0.0).max())
    if bound >= _TIER_BAND_HEADROOM * _RULE_TIER:
        raise ValueError(
            f"hierarchy tier band overflow: within-tier score mass "
            f"~{bound:.0f} >= {_TIER_BAND_HEADROOM:.2f} * _RULE_TIER "
            f"({_RULE_TIER:.0f}) — at this partitions-per-node scale "
            f"(P={prev.shape[0]}, usable N={int(cap_w.nonzero()[0].size)}, "
            f"slots={k_total:.0f}) the band test that separates hierarchy "
            f"tiers would misclassify rule conformance.  Add nodes, split "
            f"the problem, or raise _RULE_TIER in concert with "
            f"_RULE_MISS/_INF (blance_tpu/plan/tensor.py)")
    if len(_tier_scale_memo) >= 256:  # bound a long-lived process's memo
        _tier_scale_memo.clear()
    _tier_scale_memo[key] = fingerprint


def solve_dense_converged(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    max_iterations: int = 10,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    record: bool = True,
    carry_used: Optional[jnp.ndarray] = None,
    return_carry: bool = False,
    p_real: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """solve_dense iterated to a fixpoint (reference plan.go:23-58).

    The reference replans on its own output until stable (≤ 10 passes,
    "usually 1 or 2"): the first pass does the work, later passes converge
    because the warm-start pins hold everything the capacity rail accepts.
    A converged pass short-circuits the auction (every copy pins), so the
    confirming iteration costs a fraction of the first.  Like the
    reference, cluster deltas apply only to the first pass — subsequent
    passes re-balance on the stable node set (plan.go:49-55; removed nodes
    hold nothing after pass 1, so a constant valid mask is equivalent).

    The executed pass count surfaces as the ``plan.solve.sweeps``
    counter/histogram on the obs Recorder (the loop itself is fused into
    one device program, so per-sweep host spans cannot exist).  Reading it
    costs one scalar device-to-host sync; ``record=False`` skips that —
    for micro-timed loops where an extra host round-trip would perturb
    the measurement (under jit/shard_map tracing it is skipped anyway).

    ``carry_used`` (SolveCarry.used matching ``prev``) seeds the first
    sweep's fill totals bit-identically instead of re-scattering them;
    ``return_carry`` additionally packages the converged output as a
    :class:`SolveCarry` for the next delta replan — returns
    (assign, carry) instead of assign.  (Not usable under an outer
    jit/shard_map trace; the sharded entry point builds its carry
    host-side instead.)
    """
    _check_tier_band_scale(prev, pweights, nweights, valid, stickiness,
                           constraints, rules)
    # An enclosing dispatch site's entry scope (the bucketed plan path)
    # owns BOTH instruments — compile attribution is first-wins anyway,
    # and the cost gauges must agree with it, or the documented
    # device.flops{entry="solve_dense.bucketed"} series would never
    # exist while "cold" silently absorbed bucketed-shape classes.
    ent = _device.ambient_entry() or (
        "solve_dense.carry" if carry_used is not None
        else "solve_dense.cold")
    # Device observatory (obs/device.py), all opt-in: the sweep trace
    # compiles a sibling program with the convergence accumulator, and
    # cost analysis AOT-compiles the dispatched program once per
    # (entry, shape).  Both are host-side only — under an outer
    # jit/shard_map trace the args are tracers and everything below is
    # skipped, so the sharded dispatch keeps owning its own scope.
    concrete = not isinstance(prev, jax.core.Tracer)
    want_trace = (record and concrete and
                  _device.sweep_trace_enabled())
    if concrete:
        # Lower the ACTUAL dispatched unit — the converged fixpoint
        # program, not one solve_dense sweep — so the gauge's unit
        # ("FLOPs per dispatch") is consistent with the fleet/warm
        # entries, which also publish their real dispatched programs.
        _device.maybe_publish_cost(
            ent, f"{prev.shape[0]}x{nweights.shape[0]}",
            _solve_dense_converged_impl,
            prev, pweights, nweights, valid, stickiness, gids, gid_valid,
            constraints, rules, axis_name, max_iterations, node_axis,
            node_shards, fused_score, carry_used, p_real)
    rec = get_recorder()
    t0 = rec.now()
    with _device.entry(ent):
        res = _solve_dense_converged_impl(
            prev, pweights, nweights, valid, stickiness, gids, gid_valid,
            constraints, rules, axis_name, max_iterations, node_axis,
            node_shards, fused_score, carry_used, p_real,
            trace_sweeps=want_trace)
    out, sweeps = res[0], res[1]
    if record:
        _record_sweeps(sweeps)
    if want_trace:
        _device.record_sweep_trace(rec, t0, rec.now(), int(sweeps),
                                   np.asarray(res[2]))
    if return_carry:
        return out, carry_from_assignment(out, pweights, nweights)
    return out


def _warm_repair(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    dirty: jnp.ndarray,  # [P] bool — partitions the delta may move
    carry_used: jnp.ndarray,  # [S, N] SolveCarry.used matching prev
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE carry-seeded repair sweep + in-graph acceptance flags.

    The repair sweep is ``solve_dense`` itself (same trace, totals seeded
    bit-identically from the carry), so its output equals a cold solve's
    first sweep exactly; only re-bidding partitions — dirty rows, plus
    anything the pin pass displaces — do any auction work, while
    untouched rows keep their pinned placement.  What a warm replan
    SKIPS is the fixpoint loop's confirming sweep(s), and that skip is
    only sound when the repair provably stayed inside the delta.  Two
    device-side checks decide, without host round-trips per condition:

    - ripple: any row OUTSIDE the dirty mask changed — the delta leaked
      (capacity trim displaced clean holders, a tier floor shifted); a
      second sweep could move more, so the caller must cold-solve.
    - fresh over-capacity: a node's new fill exceeds its state rail by
      more than the quantization allowance AND exceeds its previous
      fill — a sign the repair force-packed displaced copies where a
      confirming sweep would re-balance them.  The allowance is one
      max-weight partition per shard: the auction's first-bidder
      progress rule legitimately overshoots the ceil'd rail by up to
      that much, and such fixpoints replan unchanged (the overshoot
      sits inside the pin pass's lmin+stickiness band — see
      _pin_prev_holders), so flagging them would demote every
      steady-state sharded replan to cold.  Rails the PREVIOUS solution
      already exceeded (rule-constrained overflow the top-up
      deliberately grants) don't trip this either.

    Returns (assign, new_used[S, N], ok) where ``ok`` (scalar bool,
    globally agreed under shard_map) means "accept this as converged".
    """
    p, s, _ = prev.shape
    n = nweights.shape[0]
    out = solve_dense(prev, pweights, nweights, valid, stickiness, gids,
                      gid_valid, constraints, rules, axis_name, node_axis,
                      node_shards, fused_score, carry_used=carry_used,
                      p_real=p_real)
    new_used = _used_by_state(out, pweights, n, s, axis_name)
    ok = _repair_ok(prev, out, new_used, carry_used, dirty, pweights,
                    nweights, valid, constraints, axis_name)
    return out, new_used, ok


def _repair_ok(prev, out, new_used, carry_used, dirty, pweights, nweights,
               valid, constraints, axis_name):
    """The warm repair's acceptance gates (ripple + fresh over-capacity;
    see :func:`_warm_repair`'s docstring) — extracted so the sparse
    repair judges itself with the identical device-side checks."""
    p = prev.shape[0]
    rippled = jnp.any((out != prev) & ~dirty[:, None, None])
    if axis_name:
        rippled = lax.psum(rippled.astype(jnp.int32), axis_name) > 0

    total_w = _psum(jnp.sum(pweights), axis_name)
    cap_w = jnp.where(valid & (nweights >= 0), jnp.maximum(nweights, 1.0),
                      0.0)
    cap_share = cap_w / jnp.maximum(jnp.sum(cap_w), 1.0)
    ns = _axis_size(axis_name) if axis_name else 1
    max_w = jnp.max(pweights) if p else jnp.float32(0.0)
    if axis_name:
        max_w = lax.pmax(max_w, axis_name)
    allowance = ns * max_w  # first-bidder quantization, one per shard
    overcap = jnp.array(False)
    for si, k in enumerate(constraints):
        if k <= 0:
            continue
        rail = jnp.ceil(k * total_w * cap_share)
        overcap |= jnp.any((new_used[si] > rail + allowance)
                           & (new_used[si] > carry_used[si]))
    return ~rippled & ~overcap


_WARM_STATICS = ("constraints", "rules", "axis_name", "node_axis",
                 "node_shards", "fused_score")
_warm_repair_jit = partial(jax.jit, static_argnames=_WARM_STATICS)(
    _warm_repair)
# Donating prev + carry_used lets XLA alias them into the outputs (same
# shapes/dtypes), so a steady-state warm replan reuses the previous
# carry's buffers instead of allocating: the carry is single-use by
# contract (sessions drop theirs after every attempt).  CPU buffers are
# not donatable (dispatch would warn every call), so the plain jit backs
# host runs and tests.
_warm_repair_donating = jax.jit(
    _warm_repair, static_argnames=_WARM_STATICS,
    donate_argnames=("prev", "carry_used"))


def solve_dense_warm(
    prev, pweights, nweights, valid, stickiness, gids, gid_valid,
    constraints, rules, *, dirty, carry: SolveCarry,
    fused_score: str = "off", record: bool = True,
    donate: Optional[bool] = None, p_real=None,
) -> tuple[Optional[NPArray], Optional[SolveCarry]]:
    """Warm delta replan: repair sweep from the carry, or decline.

    Returns (assign, next_carry) when the repair is accepted as
    converged — one sweep instead of the cold fixpoint's two-plus — or
    (None, None) when the delta leaked outside the dirty mask and the
    caller must run the cold path (:func:`solve_converged_resilient`).
    The carry is CONSUMED either way (its device buffers may be donated
    into the repair); callers must replace it with ``next_carry`` or the
    cold solve's rebuilt carry, never reuse it.

    obs: records ``plan.solve.dirty_fraction`` (histogram), a
    ``plan.solve.warm_fallback`` counter on decline, the executed sweep
    in ``plan.solve.sweeps``, and a ``warm`` span attribute on
    acceptance.  ``plan.solve.carry_hit`` is deliberately NOT counted
    here: the caller may still reject the accepted repair (the
    session's audit gate), and a hit must mean the replan really did
    cost one sweep end-to-end — callers count it once their own gates
    pass.
    """
    rec = get_recorder()
    _check_tier_band_scale(prev, pweights, nweights, valid, stickiness,
                           constraints, rules)
    check_dense_memory(np.asarray(prev).shape[0], np.asarray(prev).shape[1],
                       np.asarray(nweights).shape[-1], fused_score)
    dirty_np = np.asarray(dirty)
    if record:
        rec.observe("plan.solve.dirty_fraction",
                    float(dirty_np.mean()) if dirty_np.size else 0.0)
    if donate is None:
        donate = jax.default_backend() != "cpu"
    impl = _warm_repair_donating if donate else _warm_repair_jit
    dev_args = (
        jnp.asarray(prev), jnp.asarray(pweights), jnp.asarray(nweights),
        jnp.asarray(valid), jnp.asarray(stickiness), jnp.asarray(gids),
        jnp.asarray(gid_valid), jnp.asarray(dirty_np),
        jnp.asarray(carry.used))
    # Cost gauges BEFORE the dispatch: with donation on, the live call
    # consumes its operand buffers and a later lower() could not touch
    # them.  Memoized per (entry, shape) — steady state pays nothing.
    _device.maybe_publish_cost(
        "solve_dense.warm",
        f"{dev_args[0].shape[0]}x{dev_args[2].shape[0]}", _warm_repair_jit,
        *dev_args, constraints=constraints, rules=rules,
        fused_score=fused_score, p_real=p_real)
    with rec.span("plan.solve.attempt", warm=True,
                  engine={"off": "matrix", "on": "fused",
                          "interpret": "fused-interpret"}[fused_score]), \
            _device.entry("solve_dense.warm"):
        out, new_used, ok = impl(
            *dev_args, constraints=constraints, rules=rules,
            fused_score=fused_score, p_real=p_real)
        accepted = bool(ok)
    if not accepted:
        if record:
            rec.count("plan.solve.warm_fallback")
            rec.count("plan.solve.sweeps", 1)  # the executed repair pass
        return None, None
    if record:
        _record_sweeps(1)
        rec.set_attr("warm", True)
    return np.asarray(out), SolveCarry(
        prices=jnp.sum(new_used, axis=0), assign=out, used=new_used)


# --- sparse shortlist solve --------------------------------------------------
#
# ROADMAP item 2: the dense score sweep is f32 [P, N] per slot — 1M
# partitions x 10k nodes is a ~40 GB intermediate no fusing fixes.  The
# sparse engine scores only a [P, K] candidate shortlist (K << N,
# derived statically in core/shortlist.py from stickiness + hierarchy
# groups + weights) while the fill/price/capacity tables stay full
# [S, N] width, so acceptance, tie-breaks and the audit contracts are
# evaluated against real global state.  Rows whose shortlist cannot
# reach the globally attainable rule tier (or has no feasible candidate)
# are flagged in-graph and re-placed by a per-row dense fallback on the
# host — the observable escape hatch (plan.sparse.* counters) that makes
# audit contracts hold for ANY shortlist.  A saturating K = N shortlist
# is bit-identical to the dense matrix engine, cold and warm.


def sparse_rules_supported(rules: Rules) -> bool:
    """True when the sparse engine can solve these rules (every
    exclude level strictly finer than its include level — the nesting
    tree shape the group-counting attainability floor requires)."""
    from ..core.shortlist import shortlist_rules_nest

    return shortlist_rules_nest(rules)


def resolve_sparse_impl(impl: Optional[str]) -> str:
    """None -> the fused ops/sparse2.py kernel on TPU, the XLA
    reference elsewhere; explicit modes pass through validated."""
    if impl is None:
        return "pallas" if pallas_available() else "xla"
    if impl not in ("xla", "pallas", "interpret"):
        raise ValueError(f"unknown sparse_impl: {impl!r}")
    return impl


_SPARSE_STATICS = ("constraints", "rules", "axis_name", "max_iterations",
                   "sparse_impl")


@partial(jax.jit, static_argnames=_SPARSE_STATICS)
def _solve_sparse_converged_impl(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    shortlist: jnp.ndarray,  # [P, K] ascending candidate ids, -1 pads
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    max_iterations: int = 10,
    sparse_impl: str = "xla",
    carry_used: Optional[jnp.ndarray] = None,
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, ...]:
    """Jitted sparse fixpoint; returns (assign, sweeps, exhausted[P]).

    The same converged loop as ``_solve_dense_converged_impl`` (carry
    seeds the FIRST sweep only), over the shortlist engine.  The
    exhaustion flags are the LAST executed sweep's — rows still
    unservable at the fixpoint, which the host fallback re-places."""
    def solve(x, cu=None):
        return _solve_assign(
            x, pweights, nweights, valid, stickiness, gids, gid_valid,
            constraints, rules, axis_name, None, 1, "off",
            carry_used=cu, p_real=p_real, shortlist=shortlist,
            sparse_impl=sparse_impl)

    first, exh0 = solve(prev, carry_used)

    def cond(carry):
        out, prev_i, it, _exh = carry
        changed = jnp.any(out != prev_i)
        if axis_name:
            changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
        return changed & (it < max_iterations)

    def body(carry):
        out, _prev, it, _exh = carry
        new, exh = solve(out)
        return new, out, it + 1, exh

    out, _, it, exh = lax.while_loop(
        cond, body, (first, prev, jnp.array(1), exh0))
    return out, it, exh


def _warm_repair_sparse(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    shortlist: jnp.ndarray,
    dirty: jnp.ndarray,
    carry_used: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    sparse_impl: str = "xla",
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, ...]:
    """ONE carry-seeded sparse repair sweep; returns
    (assign, new_used[S, N], ok, exhausted[P]) with the exact
    acceptance gates of :func:`_warm_repair` (shared ``_repair_ok``),
    so ``PlannerSession``/``CarryCache`` semantics carry over
    unchanged.  Exhausted rows come back -1 and, being changed rows,
    are only acceptable when the dirty mask covers them — the caller
    then routes them through the per-row dense fallback."""
    p, s, _ = prev.shape
    n = nweights.shape[0]
    out, exh = _solve_assign(
        prev, pweights, nweights, valid, stickiness, gids, gid_valid,
        constraints, rules, axis_name, None, 1, "off",
        carry_used=carry_used, p_real=p_real, shortlist=shortlist,
        sparse_impl=sparse_impl)
    new_used = _used_by_state(out, pweights, n, s, axis_name)
    ok = _repair_ok(prev, out, new_used, carry_used, dirty, pweights,
                    nweights, valid, constraints, axis_name)
    return out, new_used, ok, exh


_WARM_SPARSE_STATICS = ("constraints", "rules", "axis_name", "sparse_impl")
_warm_repair_sparse_jit = partial(
    jax.jit, static_argnames=_WARM_SPARSE_STATICS)(_warm_repair_sparse)
# Same donation contract as _warm_repair_donating: the carry is
# single-use and prev aliases into the same-shaped assign output.
_warm_repair_sparse_donating = jax.jit(
    _warm_repair_sparse, static_argnames=_WARM_SPARSE_STATICS,
    donate_argnames=("prev", "carry_used"))


def _sparse_fallback_rows(
    assign: NPArray,  # [P, S, R] the sparse result (NOT mutated)
    rows: NPArray,  # indices of exhausted rows
    prev: NPArray,
    pweights: NPArray,
    nweights: NPArray,
    valid: NPArray,
    stickiness: NPArray,
    gids: NPArray,
    gid_valid: NPArray,
    constraints: Constraints,
    rules: Rules,
) -> NPArray:
    """Per-row DENSE fallback for shortlist-exhausted partitions.

    Discards the flagged rows' sparse placements entirely and re-places
    every slot in order against the full node axis — anchors, taken-set
    and rule tiers evaluated exactly as the audit judges them, priced by
    the real global fill so the handful of fallback rows spread instead
    of herding.  Host numpy over a [B, N] block (B = exhausted rows,
    rare by design): the whole point of the flag is that only these
    rows ever pay dense cost.  Returns a patched copy."""
    assign = np.array(np.asarray(assign), copy=True)
    rows = np.asarray(rows)
    P, S, R = assign.shape
    nw = np.asarray(nweights, np.float32)
    n = nw.shape[0]
    if rows.size == 0 or n == 0:
        return assign
    pw = np.asarray(pweights, np.float32)
    valid = np.asarray(valid, bool)
    gids = np.asarray(gids)
    gid_valid = np.asarray(gid_valid)
    w_div = np.where(nw > 0, nw, 1.0)
    neg_boost = np.maximum(-nw, 0.0)

    kept = assign.copy()
    kept[rows] = -1
    used_s = np.zeros((S, n), np.float32)
    for si in range(S):
        ids = kept[:, si, :]
        m = ids >= 0
        if m.any():
            w_rep = np.broadcast_to(pw[:, None], ids.shape)
            used_s[si] = np.bincount(
                ids[m].ravel(), weights=w_rep[m].ravel(),
                minlength=n)[:n].astype(np.float32)
    total = used_s.sum(axis=0)

    B = rows.size
    prev_b = np.asarray(prev)[rows]
    stick_b = np.asarray(stickiness, np.float32)[rows]
    pw_b = pw[rows]
    top_anchor = prev_b[:, 0, 0]
    new_rows = np.full((B, S, R), -1, np.int32)
    taken: list[NPArray] = []
    ar = np.arange(B)
    for si in range(S):
        kcon = int(constraints[si])
        if kcon <= 0:
            continue
        rules_si = list(rules[si]) if si < len(rules) else []
        if rules_si:
            base = top_anchor if si == 0 else np.where(
                new_rows[:, 0, 0] >= 0, new_rows[:, 0, 0], top_anchor)
            anchors = [base]
        for ri in range(min(kcon, R)):
            score = (0.001 * total[None, :] / max(float(P), 1.0)) \
                / w_div[None, :]
            prev_slot = prev_b[:, si, ri] if ri < prev_b.shape[2] \
                else np.full(B, -1, np.int32)
            align = np.zeros((B, n), bool)
            hold = prev_slot >= 0
            align[ar[hold], prev_slot[hold]] = True
            score = score - 0.01 * align
            score = score + np.maximum(
                neg_boost[None, :],
                np.where(neg_boost[None, :] > 0,
                         stick_b[:, si][:, None], 0.0))
            sticky = np.zeros((B, n), bool)
            for r in range(prev_b.shape[2]):
                ps = prev_b[:, si, r]
                hold = ps >= 0
                sticky[ar[hold], ps[hold]] = True
            score = score - stick_b[:, si][:, None] * sticky
            if rules_si:
                pen = np.full((B, n), _RULE_MISS, np.float32)
                for idx, (inc, exc) in enumerate(rules_si):
                    sat = np.ones((B, n), bool)
                    for a in anchors:
                        aa = np.clip(a, 0, n - 1)
                        inc_same = (gids[inc][aa][:, None]
                                    == gids[inc][None, :]) \
                            & gid_valid[inc][aa][:, None]
                        exc_same = (gids[exc][aa][:, None]
                                    == gids[exc][None, :]) \
                            & gid_valid[exc][aa][:, None]
                        sat &= np.where((a >= 0)[:, None],
                                        inc_same & ~exc_same, True)
                    pen = np.where(sat, np.minimum(pen, idx * _RULE_TIER),
                                   pen)
                any_anchor = np.zeros(B, bool)
                for a in anchors:
                    any_anchor |= a >= 0
                score = score + np.where(any_anchor[:, None], pen, 0.0)
            tk = np.zeros((B, n), bool)
            for t in taken:
                held = t >= 0
                tk[ar[held], t[held]] = True
            score = score + _INF * (tk | ~valid[None, :])
            # Price by the state's live global fill so concurrent
            # fallback rows spread (the force step's pricing idiom).
            score = score + used_s[si][None, :] / w_div[None, :]
            choice = np.argmin(score, axis=1).astype(np.int32)
            feas = score[ar, choice] < _INF / 2
            pick = np.where(feas, choice, -1).astype(np.int32)
            new_rows[:, si, ri] = pick
            placed = pick[feas]
            np.add.at(used_s[si], placed, pw_b[feas])
            np.add.at(total, placed, pw_b[feas])
            taken.append(pick)
            if rules_si:
                anchors.append(pick)
    assign[rows] = new_rows
    return assign


def _apply_sparse_fallback(
    assign: NPArray, exhausted: NPArray, prev, pweights, nweights,
    valid, stickiness, gids, gid_valid, constraints, rules,
    record: bool = True,
) -> tuple[NPArray, int]:
    """Route flagged rows through the dense fallback; returns
    (patched assign, rows re-placed).  Publishes the
    ``plan.sparse.shortlist_exhausted`` / ``dense_fallback_rows``
    counters so the escape hatch is observable."""
    rows = np.nonzero(np.asarray(exhausted))[0]
    if rows.size == 0:
        return np.asarray(assign), 0
    rec = get_recorder()
    if record:
        rec.count("plan.sparse.shortlist_exhausted", int(rows.size))
    patched = _sparse_fallback_rows(
        assign, rows, np.asarray(prev), pweights, nweights, valid,
        stickiness, gids, gid_valid, constraints, rules)
    replaced = int(np.any(
        patched[rows] != np.asarray(assign)[rows], axis=(1, 2)).sum())
    if record and replaced:
        rec.count("plan.sparse.dense_fallback_rows", replaced)
    return patched, replaced


def _build_or_adopt_shortlist(
    prev, pweights, nweights, valid, gids, gid_valid, constraints, rules,
    shortlist, k, record: bool,
):
    """The host entries' shared shortlist step: adopt a caller-built
    [P, K] table or derive one (timed as plan.sparse.shortlist_build_s),
    and publish the k_effective gauge."""
    from ..core.shortlist import auto_shortlist_k, build_shortlist

    rec = get_recorder()
    if shortlist is None:
        n = np.asarray(nweights).shape[-1]
        kk = int(k) if k is not None \
            else auto_shortlist_k(n, constraints, rules)
        t0 = rec.now()
        shortlist = build_shortlist(
            prev, pweights, nweights, valid, gids, gid_valid,
            constraints, rules, kk)
        if record:
            rec.observe("plan.sparse.shortlist_build_s", rec.now() - t0)
    shortlist = jnp.asarray(shortlist)
    if record:
        rec.set_gauge("plan.sparse.k_effective",
                      float(shortlist.shape[1] if shortlist.ndim == 2
                            else 0))
    return shortlist


def solve_sparse(
    prev, pweights, nweights, valid, stickiness, gids, gid_valid,
    constraints, rules, *, shortlist=None, k: Optional[int] = None,
    max_iterations: int = 10, record: bool = True, carry_used=None,
    return_carry: bool = False, p_real=None,
    sparse_impl: Optional[str] = None,
):
    """Sparse converged solve: shortlist -> [P, S, K] auction ->
    per-row dense fallback for exhausted rows.  The sparse sibling of
    :func:`solve_dense_converged` — same positional contract, returns
    the assignment as numpy (plus the rebuilt :class:`SolveCarry` with
    ``return_carry``).

    ``shortlist`` adopts a caller-built [P, K] table; otherwise one is
    derived (``k`` columns, auto-sized when None — see
    core/shortlist.py).  A saturating K >= N is bit-identical to the
    dense matrix engine (map, warnings and moves), the pinned contract
    that keeps the two paths from drifting.  ``carry_used`` seeds the
    first sweep exactly like the dense loop, so warm sessions ride it
    unchanged.
    """
    constraints = tuple(int(c) for c in constraints)
    rules = tuple(tuple(r) for r in rules)
    if not sparse_rules_supported(rules):
        raise ValueError(
            "sparse solve requires nesting hierarchy rules "
            "(exclude_level < include_level); use the dense engines")
    _check_tier_band_scale(prev, pweights, nweights, valid, stickiness,
                           constraints, rules)
    impl = resolve_sparse_impl(sparse_impl)
    rec = get_recorder()
    ent = _device.ambient_entry() or (
        "sparse.carry" if carry_used is not None else "sparse.cold")
    # The entry scope opens before the shortlist step: the cold entry
    # owns TWO programs (the jitted builder + the converged fixpoint),
    # and the retrace budget (analysis/retrace.py) is sized for both —
    # a builder retrace must land in THIS bucket, not "other".
    with _device.entry(ent):
        shortlist = _build_or_adopt_shortlist(
            prev, pweights, nweights, valid, gids, gid_valid,
            constraints, rules, shortlist, k, record)
        with rec.span("plan.solve.attempt", engine="sparse"):
            out, sweeps, exh = _solve_sparse_converged_impl(
                jnp.asarray(prev), jnp.asarray(pweights),
                jnp.asarray(nweights), jnp.asarray(valid),
                jnp.asarray(stickiness), jnp.asarray(gids),
                jnp.asarray(gid_valid), shortlist,
                constraints=constraints, rules=rules,
                max_iterations=max(int(max_iterations), 1),
                sparse_impl=impl, carry_used=carry_used, p_real=p_real)
            out_np = np.asarray(out)
            exh_np = np.asarray(exh)
    if record:
        _record_sweeps(sweeps)
    out_np, _replaced = _apply_sparse_fallback(
        out_np, exh_np, prev, pweights, nweights, valid, stickiness,
        gids, gid_valid, constraints, rules, record=record)
    if return_carry:
        return out_np, carry_from_assignment(
            jnp.asarray(out_np), jnp.asarray(pweights),
            jnp.asarray(nweights))
    return out_np


def solve_sparse_warm(
    prev, pweights, nweights, valid, stickiness, gids, gid_valid,
    constraints, rules, *, dirty, carry: SolveCarry, shortlist=None,
    k: Optional[int] = None, record: bool = True,
    donate: Optional[bool] = None, p_real=None,
    sparse_impl: Optional[str] = None,
) -> tuple[Optional[NPArray], Optional[SolveCarry]]:
    """Warm delta replan on the sparse engine: one carry-seeded repair
    sweep over the shortlist, or decline — the exact
    :func:`solve_dense_warm` contract ((None, None) on decline, carry
    consumed either way, same obs counters), so sessions and the
    CarryCache ride the sparse path unchanged.  Exhausted rows in an
    ACCEPTED repair go through the per-row dense fallback and the
    returned carry is rebuilt from the patched assignment."""
    constraints = tuple(int(c) for c in constraints)
    rules = tuple(tuple(r) for r in rules)
    if not sparse_rules_supported(rules):
        raise ValueError(
            "sparse solve requires nesting hierarchy rules "
            "(exclude_level < include_level); use the dense engines")
    rec = get_recorder()
    _check_tier_band_scale(prev, pweights, nweights, valid, stickiness,
                           constraints, rules)
    impl = resolve_sparse_impl(sparse_impl)
    dirty_np = np.asarray(dirty)
    if record:
        rec.observe("plan.solve.dirty_fraction",
                    float(dirty_np.mean()) if dirty_np.size else 0.0)
    if donate is None:
        donate = jax.default_backend() != "cpu"
    impl_fn = _warm_repair_sparse_donating if donate \
        else _warm_repair_sparse_jit
    # The donating dispatch consumes prev's device buffer (aliased into
    # the repair output), but the exhaustion fallback below still needs
    # the pre-repair placement — snapshot it host-side first.
    prev_fb = np.asarray(prev) if donate else prev
    with _device.entry("sparse.warm"):
        shortlist = _build_or_adopt_shortlist(
            prev, pweights, nweights, valid, gids, gid_valid,
            constraints, rules, shortlist, k, record)
        with rec.span("plan.solve.attempt", warm=True, engine="sparse"):
            out, new_used, ok, exh = impl_fn(
                jnp.asarray(prev), jnp.asarray(pweights),
                jnp.asarray(nweights), jnp.asarray(valid),
                jnp.asarray(stickiness), jnp.asarray(gids),
                jnp.asarray(gid_valid), shortlist,
                jnp.asarray(dirty_np), jnp.asarray(carry.used),
                constraints=constraints, rules=rules, sparse_impl=impl,
                p_real=p_real)
            accepted = bool(ok)
    if not accepted:
        if record:
            rec.count("plan.solve.warm_fallback")
            rec.count("plan.solve.sweeps", 1)  # the executed repair pass
        return None, None
    if record:
        _record_sweeps(1)
        rec.set_attr("warm", True)
    out_np = np.asarray(out)
    patched, replaced = _apply_sparse_fallback(
        out_np, np.asarray(exh), prev_fb, pweights, nweights, valid,
        stickiness, gids, gid_valid, constraints, rules, record=record)
    if replaced:
        return patched, carry_from_assignment(
            jnp.asarray(patched), jnp.asarray(pweights),
            jnp.asarray(nweights))
    return patched, SolveCarry(
        prices=jnp.sum(new_used, axis=0), assign=out, used=new_used)


# --- fused single-dispatch plan pipeline ------------------------------------
#
# ROADMAP item 3: at the north star the device solve is ~1/3 of
# end-to-end wall-clock — host encode/decode and the separate move-diff
# dispatch own the rest.  These impls chain solve -> on-device move diff
# -> on-device decode pack into ONE jitted program, so a plan round trip
# pays one dispatch and no intermediate host transfer: the solver output
# feeds the diff and the pack as device values inside the same trace.
# Buffer donation (prev, and the warm path's carry table) lets XLA alias
# the inputs into the same-shaped outputs (assign/packed are prev-shaped,
# new_used is carry_used-shaped), so the steady-state replan loop
# allocates no fresh [P, S, R] buffers.  Host work shrinks to the
# id->name materialization (decode_assignment's gather + NextMoves
# lists), which is irreducibly string-typed.


def _pipeline_cold_impl(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    max_iterations: int = 10,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    favor_min_nodes: bool = False,
    carry_used: Optional[jnp.ndarray] = None,
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, ...]:
    """Cold pipeline body: converged solve + diff(prev, out) + pack.

    Returns (assign, sweeps, prices, used, d_nodes, d_states, d_ops,
    packed, counts).  ``prices``/``used`` are the next SolveCarry's
    tables, computed with the carry builder's exact ops so the packaged
    carry is bitwise what carry_from_assignment would build —  without
    a second dispatch.  The solve is the UNCHANGED converged fixpoint
    trace, so ``assign`` is bit-identical to the staged path's.
    """
    from ..core.encode import pack_assignment_core
    from ..moves.batch import diff_assignments

    out, sweeps = _solve_dense_converged_impl(
        prev, pweights, nweights, valid, stickiness, gids, gid_valid,
        constraints, rules, axis_name, max_iterations, node_axis,
        node_shards, fused_score, carry_used, p_real)
    used = _used_by_state(out, pweights, nweights.shape[0], prev.shape[1],
                          axis_name)
    prices = jnp.sum(used, axis=0)
    d_nodes, d_states, d_ops = diff_assignments(
        prev, out, favor_min_nodes=favor_min_nodes)
    packed, counts = pack_assignment_core(out)
    return (out, sweeps, prices, used, d_nodes, d_states, d_ops,
            packed, counts)


def _pipeline_warm_impl(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    dirty: jnp.ndarray,
    carry_used: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    node_axis: Optional[str] = None,
    node_shards: int = 1,
    fused_score: str = "off",
    favor_min_nodes: bool = False,
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, ...]:
    """Warm pipeline body: one carry-seeded repair sweep (_warm_repair,
    acceptance flags included) + diff + pack in the same program.

    Returns (assign, prices, used, ok, d_nodes, d_states, d_ops,
    packed, counts); ``ok`` False means the repair leaked and the
    caller must run the cold pipeline — the diff/pack work is then
    wasted, which is fine: declines are the rare path by design."""
    from ..core.encode import pack_assignment_core
    from ..moves.batch import diff_assignments

    out, new_used, ok = _warm_repair(
        prev, pweights, nweights, valid, stickiness, gids, gid_valid,
        dirty, carry_used, constraints, rules, axis_name, node_axis,
        node_shards, fused_score, p_real)
    prices = jnp.sum(new_used, axis=0)
    d_nodes, d_states, d_ops = diff_assignments(
        prev, out, favor_min_nodes=favor_min_nodes)
    packed, counts = pack_assignment_core(out)
    return (out, prices, new_used, ok, d_nodes, d_states, d_ops,
            packed, counts)


def _pipeline_sparse_cold_impl(
    prev: jnp.ndarray,
    pweights: jnp.ndarray,
    nweights: jnp.ndarray,
    valid: jnp.ndarray,
    stickiness: jnp.ndarray,
    gids: jnp.ndarray,
    gid_valid: jnp.ndarray,
    constraints: Constraints,
    rules: Rules,
    axis_name: Optional[str] = None,
    max_iterations: int = 10,
    shortlist_k: int = 16,
    sparse_impl: str = "xla",
    favor_min_nodes: bool = False,
    carry_used: Optional[jnp.ndarray] = None,
    p_real: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, ...]:
    """Sparse pipeline body: shortlist build -> sparse converged solve
    -> diff(prev, out) -> pack, ONE traced program — the sparse variant
    of :func:`_pipeline_cold_impl` (donation preserved: prev aliases
    into assign/packed).  Returns the cold pipeline tuple plus the
    exhaustion flags; the dispatcher re-places flagged rows host-side
    and re-derives diff/pack for them (rare by design)."""
    from ..core.encode import pack_assignment_core
    from ..core.shortlist import build_shortlist_core
    from ..moves.batch import diff_assignments

    shortlist = build_shortlist_core(
        prev, pweights, nweights, valid, gids, gid_valid, constraints,
        rules, shortlist_k)
    out, sweeps, exh = _solve_sparse_converged_impl(
        prev, pweights, nweights, valid, stickiness, gids, gid_valid,
        shortlist, constraints=constraints, rules=rules,
        axis_name=axis_name, max_iterations=max_iterations,
        sparse_impl=sparse_impl, carry_used=carry_used, p_real=p_real)
    used = _used_by_state(out, pweights, nweights.shape[0], prev.shape[1],
                          axis_name)
    prices = jnp.sum(used, axis=0)
    d_nodes, d_states, d_ops = diff_assignments(
        prev, out, favor_min_nodes=favor_min_nodes)
    packed, counts = pack_assignment_core(out)
    return (out, sweeps, prices, used, d_nodes, d_states, d_ops,
            packed, counts, exh)


_PIPE_COLD_STATICS = ("constraints", "rules", "axis_name",
                      "max_iterations", "node_axis", "node_shards",
                      "fused_score", "favor_min_nodes")
_PIPE_SPARSE_STATICS = ("constraints", "rules", "axis_name",
                        "max_iterations", "shortlist_k", "sparse_impl",
                        "favor_min_nodes")
_PIPE_WARM_STATICS = ("constraints", "rules", "axis_name", "node_axis",
                      "node_shards", "fused_score", "favor_min_nodes")

_pipeline_cold_jit = partial(
    jax.jit, static_argnames=_PIPE_COLD_STATICS)(_pipeline_cold_impl)
# Donation: prev aliases into the same-shaped assign/packed outputs; the
# warm path additionally donates the consumed carry table (single-use by
# contract — sessions replace theirs after every attempt).  Donation is
# supported on every backend under the pinned jax (tests assert the
# donated buffers really are invalidated), so there is no CPU split like
# _warm_repair_donating's.
_pipeline_cold_donating = jax.jit(
    _pipeline_cold_impl, static_argnames=_PIPE_COLD_STATICS,
    donate_argnames=("prev",))
_pipeline_warm_jit = partial(
    jax.jit, static_argnames=_PIPE_WARM_STATICS)(_pipeline_warm_impl)
_pipeline_warm_donating = jax.jit(
    _pipeline_warm_impl, static_argnames=_PIPE_WARM_STATICS,
    donate_argnames=("prev", "carry_used"))
_pipeline_sparse_jit = partial(
    jax.jit, static_argnames=_PIPE_SPARSE_STATICS)(
    _pipeline_sparse_cold_impl)
_pipeline_sparse_donating = jax.jit(
    _pipeline_sparse_cold_impl, static_argnames=_PIPE_SPARSE_STATICS,
    donate_argnames=("prev",))


def _seeded_beg_map(prev_map: PartitionMap,
                    partitions_to_assign: PartitionMap) -> PartitionMap:
    """The beginning state the planner actually diffs against: prev_map
    entries where present, partitions_to_assign seeds elsewhere — the
    same ``prev_map.get(p) or partitions_to_assign[p]`` rule
    encode_problem fills prev[P, S, R] with."""
    return {name: (prev_map.get(name) or partitions_to_assign[name])
            for name in partitions_to_assign}


def plan_pipeline(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: Optional[PlanOptions] = None,
    timer=None,
    *,
    favor_min_nodes: bool = False,
    want_moves: bool = True,
):
    """plan_next_map_tpu + the move diff in ONE device dispatch.

    Returns (next_map, warnings, moves): the map and warnings are
    bit-identical to ``plan_next_map_tpu``'s, and ``moves`` matches
    ``moves.batch.calc_all_moves(seeded_beg, next_map, model,
    favor_min_nodes)`` (the per-partition ordered op lists the
    orchestrator consumes), where seeded_beg resolves missing prev
    entries from partitions_to_assign exactly like the encoder.  The
    encode stays host (string interning), then encode->solve->diff->
    decode-pack run as one jitted, buffer-donated program — no
    intermediate host transfer between solve and diff, and decode's
    host share is only the id->name gather.

    Caveat shared with PlannerSession.moves(): partitions whose
    beginning state holds one node in several states diff through the
    dense one-state-per-node encoding (calc_all_moves's irregular-host
    fallback does not apply); the solver's own outputs never do that.

    Engine/runtime failures degrade to the staged path
    (plan_next_map_tpu + device diff), counted as
    ``plan.pipeline.fallback`` — the pipeline is a fast path, never a
    new failure mode.

    ``want_moves=False`` skips the host move materialization (and the
    fallback paths' diff entirely), returning ``{}`` as the third
    element — for callers that only want the map riding the fused
    dispatch (plan_next_map's ``fused_pipeline`` option)."""
    from ..moves.batch import calc_all_moves
    from ..utils.trace import PhaseTimer

    opts = opts or PlanOptions()
    timer = timer if timer is not None else PhaseTimer()
    rec = get_recorder()
    if not _tpu_supported(opts):
        # Exact-path fallback keeps custom placement hooks; the move
        # diff still runs on device against the dense maps.
        next_map, warnings = plan_next_map_tpu(
            prev_map, partitions_to_assign, nodes_all, nodes_to_remove,
            nodes_to_add, model, opts, timer=timer)
        moves = calc_all_moves(
            _seeded_beg_map(prev_map, partitions_to_assign), next_map,
            model, favor_min_nodes) if want_moves else {}
        return next_map, warnings, moves
    del nodes_to_add

    with rec.span("plan.pipeline", partitions=len(partitions_to_assign),
                  nodes=len(nodes_all)):
        rec.count("plan.pipeline.calls")
        with phase_span("plan.encode", timer=timer):
            problem = encode_problem(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, model, opts)
        if problem.P == 0 or problem.N == 0 or problem.S == 0:
            next_map, warnings = decode_assignment(
                problem,
                np.full((problem.P, problem.S, max(problem.R, 1)), -1,
                        np.int32),
                partitions_to_assign, nodes_to_remove)
            return next_map, warnings, {n: [] for n in problem.partitions}

        rules = tuple(
            tuple(problem.rules.get(si, ())) for si in range(problem.S))
        constraints = tuple(int(c) for c in problem.constraints)

        prev_a = problem.prev
        pw_a = problem.partition_weights
        nw_a = problem.node_weights
        valid_a = problem.valid_node
        stick_a = problem.stickiness
        gids_a = problem.gids
        gv_a = problem.gid_valid
        solve_p, solve_n = problem.P, problem.N
        if opts.shape_bucketing:
            from ..core.encode import bucket_size, pad_problem_arrays

            solve_p = bucket_size(problem.P)
            solve_n = bucket_size(problem.N)
            (prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a) = \
                pad_problem_arrays(prev_a, pw_a, nw_a, valid_a, stick_a,
                                   gids_a, gv_a, solve_p, solve_n)
        _check_tier_band_scale(prev_a, pw_a, nw_a, valid_a, stick_a,
                               constraints, rules)
        mode = resolve_default_fused_score(solve_p, solve_n)
        use_sparse = _sparse_selected(opts, solve_p, problem.S, solve_n,
                                      rules)
        if not use_sparse:
            check_dense_memory(solve_p, problem.S, solve_n, mode)

        try:
            if use_sparse:
                res = _dispatch_pipeline_sparse(
                    prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a,
                    constraints, rules,
                    max_iterations=max(int(opts.max_iterations), 1),
                    shortlist_k=_opts_shortlist_k(
                        opts, solve_n, constraints, rules),
                    sparse_impl=resolve_sparse_impl(None),
                    favor_min_nodes=favor_min_nodes,
                    entry="sparse.pipeline", timer=timer,
                    p_real=(jax.device_put(np.float32(problem.P))
                            if opts.shape_bucketing else None))
            else:
                res = _dispatch_pipeline_cold(
                    prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a,
                    constraints, rules,
                    max_iterations=max(int(opts.max_iterations), 1),
                    fused_score=mode,
                    allow_fallback=_FUSED_SCORE_DEFAULT == "auto",
                    favor_min_nodes=favor_min_nodes,
                    entry=("solve_dense.bucketed" if opts.shape_bucketing
                           else "pipeline.cold"),
                    timer=timer,
                    p_real=(jax.device_put(np.float32(problem.P))
                            if opts.shape_bucketing else None))
        except (ValueError, TypeError):
            raise  # deterministic input errors: same on the staged path
        except Exception as e:
            import warnings as _warnings

            first = (str(e).splitlines() or [""])[0][:200]
            _warnings.warn(
                f"blance_tpu plan_pipeline: fused dispatch failed "
                f"({type(e).__name__}: {first}); degrading to the staged "
                f"path", UserWarning, stacklevel=2)
            rec.count("plan.pipeline.fallback")
            next_map, warnings = plan_next_map_tpu(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, None, model, opts, timer=timer)
            moves = calc_all_moves(
                _seeded_beg_map(prev_map, partitions_to_assign),
                next_map, model, favor_min_nodes) if want_moves else {}
            return next_map, warnings, moves

        assign, _sweeps, _carry, (d_nodes, d_states, d_ops), \
            (packed, counts) = res
        assign = assign[:problem.P]
        maybe_validate(problem, assign, opts.validate_assignment,
                       "plan_pipeline")
        with phase_span("plan.decode", timer=timer):
            next_map, warnings = decode_assignment(
                problem, assign, partitions_to_assign, nodes_to_remove,
                packed=packed[:problem.P], counts=counts[:problem.P])
        if not want_moves:
            return next_map, warnings, {}
        with phase_span("plan.pipeline.materialize", timer=timer):
            from ..moves.batch import moves_from_arrays

            moves = moves_from_arrays(
                problem.partitions, problem.states, problem.nodes,
                d_nodes[:problem.P], d_states[:problem.P],
                d_ops[:problem.P])
        return next_map, warnings, moves


def _dispatch_pipeline_cold(
    prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a,
    constraints: Constraints, rules: Rules, *, max_iterations: int,
    fused_score: str, allow_fallback: bool, favor_min_nodes: bool,
    entry: str, timer=None, carry_used=None, p_real=None, donate=True,
):
    """One cold pipeline dispatch with the engine-failure degradation of
    solve_converged_resilient (retry once on the opposite engine when
    the mode came from "auto").  Returns (assign_np, sweeps,
    SolveCarry, (d_nodes, d_states, d_ops) np, (packed, counts) np) —
    everything off-device exactly once, at the end."""
    import warnings as _warnings

    rec = get_recorder()

    def run(m: str):
        impl = _pipeline_cold_donating if donate else _pipeline_cold_jit
        dev_prev = jnp.asarray(prev_a)
        t0 = rec.now()
        with phase_span("plan.pipeline.dispatch", timer=timer,
                        engine=m), \
                _device.entry(entry):
            out = impl(
                dev_prev, jnp.asarray(pw_a), jnp.asarray(nw_a),
                jnp.asarray(valid_a), jnp.asarray(stick_a),
                jnp.asarray(gids_a), jnp.asarray(gv_a),
                constraints, rules, max_iterations=max_iterations,
                fused_score=m, favor_min_nodes=favor_min_nodes,
                carry_used=carry_used, p_real=p_real)
            (assign, sweeps, prices, used, d_nodes, d_states, d_ops,
             packed, counts) = out
            # One boundary crossing for the whole pipeline: everything
            # below is host-side materialization.
            assign_np = np.asarray(assign)
        rec.observe("plan.pipeline.dispatch_s", rec.now() - t0)
        _record_sweeps(sweeps)
        carry = SolveCarry(prices=prices, assign=assign, used=used)
        return (assign_np, sweeps, carry,
                (np.asarray(d_nodes), np.asarray(d_states),
                 np.asarray(d_ops)),
                (np.asarray(packed), np.asarray(counts)))

    try:
        return run(fused_score)
    except (ValueError, TypeError):
        raise
    except Exception as e:
        alt = {"off": "on", "on": "off"}.get(fused_score)
        if not allow_fallback or alt is None or \
                (alt == "on" and not pallas_available()):
            raise
        first = (str(e).splitlines() or [""])[0][:200]
        _warnings.warn(
            f"blance_tpu plan_pipeline: score engine {fused_score!r} "
            f"failed to compile/run ({type(e).__name__}: {first}); "
            f"retrying with {alt!r}", UserWarning, stacklevel=3)
        rec.count("plan.engine_fallback")
        if timer is not None:
            timer.annotate("engine_fallback", f"-> {alt}")
        return run(alt)


def _sparse_selected(opts: PlanOptions, p: int, s: int, n: int,
                     rules: Rules) -> bool:
    """Route a plan through the sparse shortlist engine?

    ``opts.sparse`` True/False forces it (True + non-nesting rules is
    an error); None = auto — sparse exactly when the dense matrix
    engine's projected [P, N] footprint exceeds the memory budget and
    the rules nest, i.e. when dense would be refused (CPU hosts) or
    forced into the fused engine's O(P*N) compute (TPU)."""
    sel = getattr(opts, "sparse", None)
    if sel is False:
        return False
    nest = sparse_rules_supported(rules)
    if sel:
        if not nest:
            raise ValueError(
                "PlanOptions(sparse=True) requires nesting hierarchy "
                "rules (exclude_level < include_level for every rule)")
        return True
    return nest and projected_score_bytes(p, n) > \
        dense_score_budget_bytes()


def _opts_shortlist_k(opts: PlanOptions, n: int, constraints: Constraints,
                      rules: Rules) -> int:
    """PlanOptions.sparse_k, or the auto-derived K."""
    from ..core.shortlist import auto_shortlist_k

    k = getattr(opts, "sparse_k", None)
    if k is not None:
        if int(k) < 1:
            raise ValueError(f"PlanOptions.sparse_k must be >= 1, got {k}")
        return min(int(k), max(n, 1))
    return auto_shortlist_k(n, constraints, rules)


def _dispatch_pipeline_sparse(
    prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a,
    constraints: Constraints, rules: Rules, *, max_iterations: int,
    shortlist_k: int, sparse_impl: str, favor_min_nodes: bool,
    entry: str, timer=None, carry_used=None, p_real=None, donate=True,
):
    """One sparse pipeline dispatch (shortlist -> sparse solve -> diff
    -> pack in one program).  Returns the `_dispatch_pipeline_cold`
    tuple; exhausted rows are re-placed by the host fallback and their
    diff/pack re-derived in one small extra dispatch (the rare path)."""
    rec = get_recorder()
    impl = _pipeline_sparse_donating if donate else _pipeline_sparse_jit
    # The donating dispatch aliases prev's buffer into the outputs, but
    # the exhaustion fallback and its re-diff below still need the
    # pre-solve placement — snapshot it host-side first (zero-copy for
    # the numpy arrays the plan/session paths pass).
    prev_fb = np.asarray(prev_a) if donate else prev_a
    t0 = rec.now()
    with phase_span("plan.pipeline.dispatch", timer=timer,
                    engine="sparse"), \
            _device.entry(entry):
        (assign, sweeps, prices, used, d_nodes, d_states, d_ops,
         packed, counts, exh) = impl(
            jnp.asarray(prev_a), jnp.asarray(pw_a), jnp.asarray(nw_a),
            jnp.asarray(valid_a), jnp.asarray(stick_a),
            jnp.asarray(gids_a), jnp.asarray(gv_a),
            constraints, rules, max_iterations=max_iterations,
            shortlist_k=shortlist_k, sparse_impl=sparse_impl,
            favor_min_nodes=favor_min_nodes, carry_used=carry_used,
            p_real=p_real)
        # One boundary crossing for the whole pipeline (plus the
        # exhaustion flags, which gate the host escape hatch).
        assign_np = np.asarray(assign)
        exh_np = np.asarray(exh)
    rec.observe("plan.pipeline.dispatch_s", rec.now() - t0)
    rec.set_gauge("plan.sparse.k_effective", float(shortlist_k))
    _record_sweeps(sweeps)
    patched, replaced = _apply_sparse_fallback(
        assign_np, exh_np, prev_fb, pw_a, nw_a, valid_a, stick_a,
        gids_a, gv_a, constraints, rules)
    if replaced:
        # The fused diff/pack ran before the host fallback patched the
        # flagged rows: re-derive both against the final assignment and
        # rebuild the carry from it (one small extra dispatch on the
        # escape-hatch path only).
        from ..core.encode import pack_assignment
        from ..moves.batch import diff_assignments

        assign_np = patched
        dev_assign = jnp.asarray(assign_np)
        d_nodes, d_states, d_ops = diff_assignments(
            jnp.asarray(prev_fb), dev_assign,
            favor_min_nodes=favor_min_nodes)
        packed, counts = pack_assignment(dev_assign)
        carry = carry_from_assignment(
            dev_assign, jnp.asarray(pw_a), jnp.asarray(nw_a))
    else:
        carry = SolveCarry(prices=prices, assign=assign, used=used)
    return (assign_np, sweeps, carry,
            (np.asarray(d_nodes), np.asarray(d_states),
             np.asarray(d_ops)),
            (np.asarray(packed), np.asarray(counts)))


def solve_converged_resilient(
    prev, pweights, nweights, valid, stickiness, gids, gid_valid,
    constraints, rules, *, max_iterations: int, mode: str,
    allow_fallback: bool, context: str, timer=None,
    carry_used=None, return_carry: bool = False, p_real=None,
):
    """solve_dense_converged with engine-failure degradation.

    The auto-selected engine is a prediction from a working-set model
    (_MATRIX_BYTES_PER_CELL / _HBM_BUDGET_FRACTION, calibrated on one
    chip generation); when the prediction is wrong the matrix engine can
    die in compile (HBM over-subscription) — or, more rarely, a fused
    kernel can hit a Mosaic lowering gap on a new toolchain.  With
    ``allow_fallback`` (set iff the mode came from "auto", never for an
    explicit user choice) a failed engine retries once on the opposite
    one, surfacing the switch as a UserWarning and on the timer's
    annotations — so production callers degrade exactly like bench.py
    does, instead of erroring.  Returns (assignment, engine-that-ran),
    plus the rebuilt :class:`SolveCarry` when ``return_carry`` is set.
    ``carry_used`` seeds the first sweep (see solve_dense_converged).
    """
    import warnings as _warnings

    rec = get_recorder()

    def run(m: str) -> NPArray:
        # Structured refusal instead of an opaque XLA OOM when the
        # matrix engine's projected [P, N] working set is over budget
        # (checked per attempt: an auto-fallback onto the matrix engine
        # must not sneak past the guard either).
        check_dense_memory(prev.shape[0], prev.shape[1],
                           np.asarray(nweights).shape[-1], m)
        # np.asarray inside the guarded region: async dispatch can defer
        # a runtime failure to the first host read.
        with rec.span("plan.solve.attempt", engine=m):
            return np.asarray(solve_dense_converged(
                prev, pweights, nweights, valid, stickiness, gids,
                gid_valid, constraints, rules,
                max_iterations=max_iterations, fused_score=m,
                carry_used=carry_used, p_real=p_real))

    try:
        out = run(mode)
    except (ValueError, TypeError):
        # Deterministic input/validation errors fail identically on every
        # engine — retrying would double the failure and surface the
        # wrong traceback.  The fallback is for the documented runtime
        # cases only (HBM over-subscription, Mosaic lowering gaps).
        raise
    except Exception as e:
        alt = {"off": "on", "on": "off"}.get(mode)
        if not allow_fallback or alt is None or \
                (alt == "on" and not pallas_available()):
            raise
        first = (str(e).splitlines() or [""])[0][:200]
        _warnings.warn(
            f"blance_tpu {context}: score engine {mode!r} failed to "
            f"compile/run ({type(e).__name__}: {first}); retrying with "
            f"{alt!r}", UserWarning, stacklevel=3)
        rec.count("plan.engine_fallback")
        out = run(alt)
        mode = alt
        # timer.annotate forwards to rec.set_attr (PhaseTimer is a shim
        # over the Recorder), so write directly only when there is no
        # timer — never both.
        if timer is not None:
            timer.annotate("engine_fallback", f"-> {alt}")
        else:
            rec.set_attr("engine_fallback", f"-> {alt}")
    engine = {"off": "matrix", "on": "fused",
              "interpret": "fused-interpret"}[mode]
    if timer is not None:
        timer.annotate("engine", engine)
    else:
        rec.set_attr("engine", engine)
    if return_carry:
        return out, mode, carry_from_assignment(out, pweights, nweights)
    return out, mode


def _anchor_sat_np(
    anchor: NPArray,  # [P] node ids, -1 = absent
    gids: NPArray,  # [L, N]
    gid_valid: NPArray,  # [L, N]
    rules: list[tuple[int, int]],
) -> NPArray:
    """Per-rule satisfaction [n_rules, P, N] for ONE anchor column: does
    node n share the anchor's include-level ancestor and NOT its
    exclude-level ancestor?  Absent anchors satisfy everything.  Validity
    gates on the anchor side only, exactly like the device _hier_penalty."""
    p = anchor.shape[0]
    n = gids.shape[1]
    aa = np.clip(anchor, 0, n - 1)
    present = (anchor >= 0)[:, None]
    out = np.ones((len(rules), p, n), bool)
    for idx, (inc, exc) in enumerate(rules):
        inc_same = (gids[inc][aa][:, None] == gids[inc][None, :]) & \
            gid_valid[inc][aa][:, None]
        exc_same = (gids[exc][aa][:, None] == gids[exc][None, :]) & \
            gid_valid[exc][aa][:, None]
        out[idx] = np.where(present, inc_same & ~exc_same, True)
    return out


# Partition-block size for the matrix-path hierarchy audit: bounds its
# peak numpy temporaries to [n_rules, _HIER_CHUNK, N] regardless of P.
_HIER_CHUNK = 4096


def _audit_rules_nest(problem: DenseProblem) -> bool:
    """True when every rule's exclude level is strictly finer than its
    include level — the tree shape under which an exclude group lies
    inside exactly one include group, so attainability reduces to group
    counting (the same precondition _hier_floor_counts relies on in the
    solver)."""
    return all(exc < inc
               for si in range(problem.S)
               for (inc, exc) in (problem.rules.get(si) or []))


def _count_hier_misses_fast(
    problem: DenseProblem, assign: NPArray
) -> int:
    """Group-counting hierarchy audit: O(P·S·R·rules + N·L) host math.

    Semantically identical to the matrix path (_count_hier_misses_block)
    when every rule nests (_audit_rules_nest) — pinned by
    tests/test_tensor.py's parity fuzz.  Instead of materializing
    per-anchor satisfaction over all N candidates, the attainable tier
    comes from counting: with the exclude level strictly finer than the
    include level, the number of rule-satisfying open candidates is

        count(valid nodes in the anchors' shared include group)
        - sum over DISTINCT anchor exclude groups of count(valid in e)
        - count(already-used nodes in the include group but in none of
          those exclude groups)

    — [N]-bincounts (one per hierarchy level, shared across rules) plus
    [P] gathers.  The achieved tier is a point evaluation at the judged
    node.  This is what makes the audit affordable at the north-star
    scale, so validation defaults ON at every size (maybe_validate);
    the reference's equivalent property surfaces as warnings
    (plan.go:231-235).
    """
    P, S, R = assign.shape
    N = problem.N
    gids, gid_valid = problem.gids, problem.gid_valid
    valid = problem.valid_node
    if not any(problem.rules.get(si) for si in range(S)):
        return 0

    # Valid-node histogram per hierarchy level.  Ancestor PRESENCE is
    # gid_valid, not the gid's sign: encode interns orphans into a shared
    # ""-group with a real dense id and gid_valid=False (encode.py:
    # level_group_ids + find_ancestor), while synthetic/test problems may
    # spell absence as gid -1 — gate on gid_valid and drop negatives so
    # both representations count identically.
    cnt = np.zeros((gids.shape[0], N), np.int64)
    for lv in range(gids.shape[0]):
        g = gids[lv][valid & gid_valid[lv]]
        g = g[g >= 0]
        cnt[lv] = np.bincount(g, minlength=N)

    # Joint histograms per rule: nodes of an exclude group that also hold
    # a PRESENT include-level ancestor.  A node can sit in a real exclude
    # group while its coarser ancestor is missing (e.g. a rack with no
    # zone parent): such a node is never in the shared include group, so
    # subtracting the full exclude-group count would over-subtract it.
    # Present ancestors are tree-consistent (same exclude group + present
    # include ancestor => same include group), so this joint count is
    # exactly |e ∩ g| for every e counted under g.
    cnt_pair: dict[tuple[int, int], NPArray] = {}
    for si in range(S):
        for (inc, exc) in (problem.rules.get(si) or []):
            if (inc, exc) in cnt_pair:
                continue
            sel = valid & gid_valid[exc] & gid_valid[inc] & \
                (gids[exc] >= 0) & (gids[inc] >= 0)
            cnt_pair[(inc, exc)] = np.bincount(
                gids[exc][sel], minlength=N)

    top_anchor = problem.prev[:, 0, 0]
    misses = 0
    used_ids: list[NPArray] = []  # [P] global node ids, -1 = none

    def point_sat(anchors, node, inc, exc):
        """[P] bool: does ``node`` satisfy (inc, exc) for every present
        anchor?  Validity gates on the anchor side only, exactly like
        _anchor_sat_np / the device _anchor_rule_sat."""
        nd = np.clip(node, 0, N - 1)
        out = np.ones(P, bool)
        for a in anchors:
            aa = np.clip(a, 0, N - 1)
            inc_same = (gids[inc][aa] == gids[inc][nd]) & gid_valid[inc][aa]
            exc_same = (gids[exc][aa] == gids[exc][nd]) & gid_valid[exc][aa]
            out &= np.where(a >= 0, inc_same & ~exc_same, True)
        return out

    def attainable_count(anchors, inc, exc):
        """[P] count of rule-satisfying candidates among valid & unused
        nodes, by group counting (see docstring)."""
        # Shared include group across present anchors (else unsatisfiable).
        g = np.full(P, -1, np.int64)
        ok = np.ones(P, bool)
        for a in anchors:
            aa = np.clip(a, 0, N - 1)
            a_g = np.where(gid_valid[inc][aa], gids[inc][aa], -2)
            present = a >= 0
            ok &= np.where(present & (g >= 0), a_g == g, True)
            ok &= np.where(present & (g < 0), a_g >= 0, True)
            g = np.where(present & (g < 0), a_g, g)
        gc = np.clip(g, 0, N - 1)
        count = cnt[inc][gc].astype(np.int64)

        # Subtract distinct anchor exclude groups (each nested inside the
        # shared include group, so each subtracts its full valid count).
        e_seen: list[NPArray] = []
        for a in anchors:
            aa = np.clip(a, 0, N - 1)
            e = np.where((a >= 0) & gid_valid[exc][aa], gids[exc][aa], -1)
            dup = np.zeros(P, bool)
            for prev_e in e_seen:
                dup |= (e == prev_e) & (e >= 0)
            count -= np.where((e >= 0) & ~dup,
                              cnt_pair[(inc, exc)][np.clip(e, 0, N - 1)], 0)
            e_seen.append(e)

        # Subtract already-used nodes still standing in the include group:
        # used nodes inside a counted exclude group are subtracted above
        # already, so only those OUTSIDE every counted group go here.
        for u in used_ids:
            uu = np.clip(u, 0, N - 1)
            in_g = (u >= 0) & valid[uu] & (gids[inc][uu] == g)
            in_excl = np.zeros(P, bool)
            for e in e_seen:
                in_excl |= (e >= 0) & (gids[exc][uu] == e)
            count -= (in_g & ~in_excl).astype(np.int64)
        return np.where(ok & (g >= 0), count, 0)

    for si in range(S):
        rules_si = problem.rules.get(si) or []
        big = len(rules_si)
        if rules_si:
            base = top_anchor if si == 0 else np.where(
                assign[:, 0, 0] >= 0, assign[:, 0, 0], top_anchor)
            anchors: list[NPArray] = [base]
            any_anchor = base >= 0
        for j in range(R):
            node_j = assign[:, si, j]
            has = node_j >= 0
            if rules_si and has.any():
                achieved = np.full(P, big, np.int64)
                attainable = np.full(P, big, np.int64)
                for idx in reversed(range(big)):
                    inc, exc = rules_si[idx]
                    achieved = np.where(
                        point_sat(anchors, node_j, inc, exc), idx, achieved)
                    attainable = np.where(
                        attainable_count(anchors, inc, exc) > 0,
                        idx, attainable)
                misses += int((has & any_anchor
                               & (achieved > attainable)).sum())
            if rules_si:
                anchors.append(node_j)
                any_anchor = any_anchor | has
            # Cross-state exclusivity: every pick occupies its node for
            # the whole partition.  Deduplicate (a malformed assignment
            # can repeat a node; the matrix path's bool [P, N] ``used``
            # dedups structurally, and duplicates are already counted by
            # check_assignment separately).
            dup = np.zeros(P, bool)
            for u in used_ids:
                dup |= (node_j == u) & has
            used_ids.append(np.where(has & ~dup, node_j, -1))
    return misses


def _count_hier_misses(problem: DenseProblem, assign: NPArray) -> int:
    """Feasible-tier hierarchy misses: a copy counts when it sits at a
    WORSE rule tier than some still-open valid node could have achieved
    given the same anchors (the solver's prefix anchoring, reference
    plan.go:185-191): state 0 anchors on the PREVIOUS primary (the
    solver's top_anchor — never on the node being judged), later states
    on the assigned primary plus the state's earlier picks.
    Unsatisfiable rules never count: when no candidate reaches a better
    tier, the flat fallback is correct behavior (plan.go:214-220).

    Two implementations, same contract: the group-counting fast path
    (O(P + N·L), _count_hier_misses_fast) whenever every rule's exclude
    level is strictly finer than its include level — the common tree
    shape — and the exhaustive [P, N] matrix path otherwise, run in
    P-blocks of _HIER_CHUNK so peak memory stays flat in P (at the
    north-star 100k x 10k that is ~40 MB of bool temporaries per rule,
    not ~1 GB)."""
    if _audit_rules_nest(problem):
        return _count_hier_misses_fast(problem, assign)
    P = assign.shape[0]
    total = 0
    for lo in range(0, P, _HIER_CHUNK):
        hi = min(lo + _HIER_CHUNK, P)
        total += _count_hier_misses_block(
            problem, assign[lo:hi], problem.prev[lo:hi])
    return total


def _count_hier_misses_block(
    problem: DenseProblem, assign: NPArray, prev: NPArray
) -> int:
    """One partition block of _count_hier_misses; per-anchor rule
    satisfaction folds in incrementally — each rule-bearing state costs
    one [n_rules, B, N] table plus one AND per ordinal."""
    P, S, R = assign.shape
    N = problem.N
    if not any(problem.rules.get(si) for si in range(S)):
        return 0
    rows = np.arange(P)
    top_anchor = prev[:, 0, 0]
    misses = 0
    used = np.zeros((P, N), bool)  # nodes this partition already occupies
    for si in range(S):
        rules_si = problem.rules.get(si) or []
        if rules_si:
            big = len(rules_si)
            base = top_anchor if si == 0 else np.where(
                assign[:, 0, 0] >= 0, assign[:, 0, 0], top_anchor)
            sat = _anchor_sat_np(base, problem.gids, problem.gid_valid,
                                 rules_si)
            any_anchor = base >= 0
        for j in range(R):
            node_j = assign[:, si, j]
            has = node_j >= 0
            if rules_si and has.any():
                tier = np.full((P, N), big, np.int32)
                for idx in reversed(range(len(rules_si))):
                    tier = np.where(sat[idx], idx, tier)
                cand_ok = problem.valid_node[None, :] & ~used
                attainable = np.min(np.where(cand_ok, tier, big), axis=1)
                achieved = tier[rows, np.clip(node_j, 0, N - 1)]
                misses += int((has & any_anchor
                               & (achieved > attainable)).sum())
            if rules_si:
                # This pick anchors the state's later ordinals.
                sat &= _anchor_sat_np(node_j, problem.gids,
                                      problem.gid_valid, rules_si)
                any_anchor = any_anchor | has
            used[rows, np.clip(node_j, 0, N - 1)] |= has
    return misses


def check_assignment(
    problem: DenseProblem, assign: NPArray
) -> dict[str, int]:
    """Constraint checker — the '0 violations' gate for the TPU backend.

    Counts (a) slot shortfalls beyond what an honest solver could fill,
    (b) same-partition node duplicates across states/slots, (c) assignments
    to removed nodes, (d) feasible-tier hierarchy-rule misses — copies
    placed at a worse rule tier than an open valid node could achieve
    (unmeetable rules degrade softly to the flat fallback and do NOT
    count, like the reference's warnings, plan.go:214-235).

    Pure numpy.  With nesting rules (every exclude level strictly finer
    than its include level — the common tree shape) the hierarchy audit
    runs by group counting in O(P + N·L), noise next to the solve at any
    size, so maybe_validate defaults it ON at every scale.  Exotic
    non-nesting rules fall back to the exhaustive [P, N] matrix audit
    (streamed in P-blocks: bounded memory, but O(P*N) time — tens of
    seconds at 100k x 10k), which stays behind the auto-validation
    ceiling unless explicitly requested.  See the
    ``validate_assignment`` wiring in plan_next_map_tpu /
    PlannerSession.replan."""
    assign = np.asarray(assign)
    P, S, R = assign.shape
    n_valid = int(problem.valid_node.sum())
    if P == 0:
        return {"duplicates": 0, "on_removed_nodes": 0,
                "unfilled_feasible_slots": 0, "hierarchy_misses": 0}

    def row_dups(rows: NPArray) -> NPArray:
        """Per row: count of valid entries equal to an earlier entry."""
        srt = np.sort(rows, axis=1)
        return ((srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] >= 0)).sum(axis=1)

    flat = assign.reshape(P, S * R)
    dup = int(row_dups(flat).sum())
    held = flat[flat >= 0]
    removed = int((~problem.valid_node[held]).sum())

    # Shortfall per (partition, state): want vs got, capped by what an
    # honest solver could still fill given the distinct nodes the
    # partition already occupies through this state (prefix-distinct).
    shortfall = 0
    got_ps = (assign >= 0).sum(axis=2)  # [P, S]
    for si in range(S):
        want = int(problem.constraints[si])
        if want <= 0:
            continue
        pre = assign[:, :si + 1, :].reshape(P, -1)
        distinct = (pre >= 0).sum(axis=1) - row_dups(pre)
        got = got_ps[:, si]
        achievable = np.minimum(want, np.maximum(n_valid - distinct + got, 0))
        shortfall += int(np.maximum(achievable - got, 0).sum())
    return {"duplicates": dup, "on_removed_nodes": removed,
            "unfilled_feasible_slots": shortfall,
            "hierarchy_misses": _count_hier_misses(problem, assign)}


# Auto-validation ceiling for the EXOTIC-rules path only: the exhaustive
# matrix audit is O(P*N) time, so above this many cells it needs an
# explicit opt-in.  Nesting rules (the common case) audit in O(P + N·L)
# and validate by default at every scale.
_VALIDATE_AUTO_CELLS = 1 << 22


def maybe_validate(
    problem: DenseProblem, assign: NPArray, validate: Optional[bool],
    context: str,
) -> Optional[dict[str, int]]:
    """Run check_assignment per the ``validate_assignment`` policy and
    surface violations as a UserWarning (reference analogue: constraint
    problems degrade to warnings, plan.go:231-235).  Returns the counts
    when the check ran, else None."""
    import warnings as _warnings

    if validate is None:
        validate = _audit_rules_nest(problem) or \
            problem.P * problem.N <= _VALIDATE_AUTO_CELLS
    if not validate:
        return None
    counts = check_assignment(problem, assign)
    if any(counts.values()):
        _warnings.warn(
            f"blance_tpu {context}: solver produced a constraint-violating "
            f"assignment: {counts}", UserWarning, stacklevel=3)
    return counts


def _tpu_supported(opts: PlanOptions) -> bool:
    """Can the batched solver honor these options' placement policy?

    The device score bakes in the default scoring formula plus the cbgt
    booster shape max(-weight, stickiness); an arbitrary Python
    ``node_scorer``/``node_sorter`` or a non-cbgt ``node_score_booster``
    cannot run inside the jitted computation (reference contract:
    plan.go:566-580,693-697).
    Negative node weights WITHOUT a booster are also unsupported: the
    reference ignores them entirely (plan.go:675-684 boosts only when the
    booster is set), while the device score would pin them."""
    if opts.node_scorer is not None or opts.node_sorter is not None:
        return False
    booster = opts.node_score_booster
    if booster is not None and \
            getattr(booster, "__blance_native__", None) != "cbgt":
        return False
    if booster is None and opts.node_weights and \
            any(w < 0 for w in opts.node_weights.values()):
        return False
    return True


def plan_next_map_tpu(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: Optional[PlanOptions] = None,
    timer=None,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """TPU-backed equivalent of plan_next_map_greedy: one global batched
    solve instead of a sequential pass.  Same inputs/outputs; nodes_to_add
    is implicit (fresh nodes simply have zero counts, which attracts load).
    ``timer`` (utils.trace.PhaseTimer) attributes wall-clock to
    encode / solve / decode when provided.

    Custom placement hooks the device score can't express fall back to the
    native/greedy exact path — a cbgt-style app keeps its policy even when
    ``backend="auto"`` routes a large problem here."""
    from ..utils.trace import PhaseTimer

    opts = opts or PlanOptions()
    timer = timer if timer is not None else PhaseTimer()
    if not _tpu_supported(opts):
        from .native import plan_next_map_native  # falls back to greedy

        # The exact path has no encode/solve/decode split; attribute it
        # all to "solve" so a caller's timer still sees the wall-clock.
        with phase_span("plan.solve", timer=timer,
                        engine="exact-fallback"):
            return plan_next_map_native(
                prev_map, partitions_to_assign, nodes_all,
                nodes_to_remove, nodes_to_add, model, opts)
    del nodes_to_add

    with phase_span("plan.encode", timer=timer):
        problem = encode_problem(
            prev_map, partitions_to_assign, nodes_all, nodes_to_remove,
            model, opts)
    if problem.P == 0 or problem.N == 0 or problem.S == 0:
        return decode_assignment(
            problem,
            np.full((problem.P, problem.S, max(problem.R, 1)), -1, np.int32),
            partitions_to_assign, nodes_to_remove)

    rules = tuple(
        tuple(problem.rules.get(si, ())) for si in range(problem.S))
    constraints = tuple(int(c) for c in problem.constraints)

    # Opt-in static-shape bucketing (PlanOptions.shape_bucketing): pad
    # P and N up to the next bucket so repeated pure-path calls against a
    # drifting cluster hit the jit cache instead of recompiling — keeping
    # shapes static is what makes repeated invocation cheap (GSPMD,
    # arXiv:2105.04663).  Pad partitions are weight-0 bidders (their
    # assignments are sliced off below) and pad nodes invalid
    # (valid=False => zero capacity, +INF score, gid_valid=False), the
    # same inert-padding contract parallel/sharded.py relies on, so the
    # real rows solve identically to the unpadded problem.
    prev_a = problem.prev
    pw_a = problem.partition_weights
    nw_a = problem.node_weights
    valid_a = problem.valid_node
    stick_a = problem.stickiness
    gids_a = problem.gids
    gv_a = problem.gid_valid
    solve_p, solve_n = problem.P, problem.N
    if opts.shape_bucketing:
        from ..core.encode import bucket_size, pad_problem_arrays

        solve_p = bucket_size(problem.P)
        solve_n = bucket_size(problem.N)
        (prev_a, pw_a, nw_a, valid_a, stick_a, gids_a, gv_a) = \
            pad_problem_arrays(prev_a, pw_a, nw_a, valid_a, stick_a,
                               gids_a, gv_a, solve_p, solve_n)

    # Observatory attribution: the bucketed pure path owns its compiles
    # as "solve_dense.bucketed" (first-wins, so the inner cold/carry
    # labels inside solve_dense_converged don't re-claim them); the
    # unbucketed path lets the inner labels stand.
    obs_entry = _device.entry("solve_dense.bucketed") \
        if opts.shape_bucketing else contextlib.nullcontext()
    use_sparse = _sparse_selected(opts, solve_p, problem.S, solve_n,
                                  rules)
    with phase_span("plan.solve", timer=timer,
                    partitions=problem.P, nodes=problem.N,
                    engine=("sparse" if use_sparse else None),
                    bucketed_shape=((solve_p, solve_n)
                                    if opts.shape_bucketing else None)), \
            obs_entry:
        if use_sparse:
            assign = solve_sparse(
                jnp.asarray(prev_a), jnp.asarray(pw_a),
                jnp.asarray(nw_a), jnp.asarray(valid_a),
                jnp.asarray(stick_a), jnp.asarray(gids_a),
                jnp.asarray(gv_a), constraints, rules,
                k=_opts_shortlist_k(opts, solve_n, constraints, rules),
                max_iterations=max(int(opts.max_iterations), 1),
                p_real=(jax.device_put(np.float32(problem.P))
                        if opts.shape_bucketing else None))
            if timer is not None:
                timer.annotate("engine", "sparse")
        else:
            assign, _engine = solve_converged_resilient(
                jnp.asarray(prev_a),
                jnp.asarray(pw_a),
                jnp.asarray(nw_a),
                jnp.asarray(valid_a),
                jnp.asarray(stick_a),
                jnp.asarray(gids_a),
                jnp.asarray(gv_a),
                constraints,
                rules,
                max_iterations=max(int(opts.max_iterations), 1),
                mode=resolve_default_fused_score(solve_p, solve_n),
                allow_fallback=_FUSED_SCORE_DEFAULT == "auto",
                context="plan_next_map_tpu",
                timer=timer,
                # Only under bucketing: p_real keeps the fill
                # denominator at the REAL partition count while sizes
                # drift within a bucket.  Unbucketed solves keep total_p
                # as a compile-time constant — a traced scalar changes
                # how XLA strength-reduces the fill division, and those
                # low bits flip jitter-level ties, perturbing the pinned
                # fuzz contract for zero benefit on the default path.
                # (This is also why bucketed output is
                # contract-equivalent to the unbucketed solve, not
                # bit-identical.)
                # device_put: the traced scalar must reach the device as
                # an EXPLICIT transfer (a bare np scalar operand rides
                # the eager convert primitive, which the tier-1
                # transfer-guard fixture in tests/conftest.py rejects as
                # an implicit sync).
                p_real=(jax.device_put(np.float32(problem.P))
                        if opts.shape_bucketing else None),
            )
    assign = assign[:problem.P]  # bucketing pad rows are not real work
    maybe_validate(problem, assign, opts.validate_assignment,
                   "plan_next_map_tpu")
    with phase_span("plan.decode", timer=timer):
        return decode_assignment(
            problem, assign, partitions_to_assign, nodes_to_remove)
