"""Native (C++) exact greedy backend — ctypes bindings + encode/decode.

Drives native/planner.cpp: the same algorithm as plan/greedy.py (and the
reference's plan.go:60-331) with the hot loop in C++ over dense ids.  The
results are bit-identical to the Python greedy planner — validated by
running the full golden test suites against this backend — at roughly
two orders of magnitude higher throughput, which makes it the honest CPU
baseline for the TPU solver.

Python owns: interning, the static partition sort key, count seeding, the
convergence loop, and warning synthesis.  C++ owns the per-state scoring
loop (including the per-state visit-order rebuild, which depends on
mutating assignments).

Falls back to the Python greedy transparently when a feature the native
core doesn't model is in play: custom node_scorer hooks, non-cbgt score
boosters, or partitions carrying states outside the model.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..core.encode import NPArray
from ..core.hierarchy import find_ancestor, parents_to_children
from ..utils.nativebuild import compile_cached
from ..core.setops import strings_intersect, strings_remove
from ..core.types import Partition, PartitionMap, PartitionModel, PlanOptions
from .greedy import (
    _partition_name_key,
    _partition_weight_key,
    count_state_nodes,
    plan_next_map_greedy,
    sort_state_names,
    sorted_by_partition_name,
)

__all__ = ["plan_next_map_native", "cbgt_node_score_booster", "native_available"]


def cbgt_node_score_booster(weight: int, stickiness: float) -> float:
    """The booster couchbase/cbgt installs (control_test.go:19-29); the
    native core implements exactly this form."""
    return max(float(-weight), stickiness)


# Any booster marked native-compatible (this attribute) maps onto the C++
# max(-w, stickiness) implementation.
cbgt_node_score_booster.__blance_native__ = "cbgt"

_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _build_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "_native_build")


def _source_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "native", "planner.cpp")


def _load_lib() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the native planner; None if unavailable."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    src = _source_path()
    so = os.path.join(_build_dir(), "_native_planner.so")
    if not compile_cached(src, so, ["g++", "-O3", "-shared", "-fPIC",
                                    "-std=c++17", "-o", so, src]):
        _LIB_FAILED = True
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        _LIB_FAILED = True
        return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.blance_plan_inner.restype = None
    lib.blance_plan_inner.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32,
        i32p, i32p, f64p, f64p, u8p, u8p, f64p,
        ctypes.c_int32, i32p, u8p, i32p, i32p, i32p,
        ctypes.c_uint8, ctypes.c_uint8,
        i32p, u8p, u8p, ctypes.c_uint8,
        i32p, f64p, i32p,
    ]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _load_lib() is not None


def _native_supported(
    partitions_to_assign: PartitionMap, model: PartitionModel, opts: PlanOptions
) -> bool:
    if opts.node_scorer is not None or opts.node_sorter is not None:
        return False
    booster = opts.node_score_booster
    if booster is not None and getattr(booster, "__blance_native__", None) != "cbgt":
        return False
    for p in partitions_to_assign.values():
        for s in p.nodes_by_state:
            if s not in model:
                return False  # unmodeled states need the Python data model
    return True


def _ptr(arr: NPArray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _plan_inner_native(
    lib: ctypes.CDLL,
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: list[str],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: PlanOptions,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """One inner pass through the C++ core (greedy._plan_next_map_inner)."""
    nodes = list(nodes_all)
    node_index = {n: i for i, n in enumerate(nodes)}
    # Ghost nodes: partitions may reference nodes outside nodes_all (a dead
    # node the caller dropped from the cluster list without removing it).
    # The greedy planner keeps them in rows and accounting — it only ever
    # *candidates* from nodes_all — so intern them as non-candidate ids.
    for pmap in (partitions_to_assign, prev_map):
        for partition in pmap.values():
            for ns in partition.nodes_by_state.values():
                for node in ns:
                    if node not in node_index:
                        node_index[node] = len(nodes)
                        nodes.append(node)
    n_candidates = len(nodes_all)
    states = sort_state_names(model)
    state_index = {s: i for i, s in enumerate(states)}
    partitions = sorted_by_partition_name(partitions_to_assign.keys())
    P, S, N = len(partitions), len(states), len(nodes)

    constraints = np.zeros(max(S, 1), np.int32)
    priority = np.zeros(max(S, 1), np.int32)
    for s, st in model.items():
        c = st.constraints
        if opts.model_state_constraints is not None:
            c = opts.model_state_constraints.get(s, c)
        constraints[state_index[s]] = c
        priority[state_index[s]] = st.priority

    if P == 0 or S == 0 or int(constraints.max(initial=0)) <= 0:
        # Nothing to assign: the greedy path handles the strip-only result.
        return plan_next_map_greedy(
            prev_map, partitions_to_assign, nodes_all,
            nodes_to_remove, nodes_to_add, model,
            _single_pass_opts(opts))

    removed = set(nodes_to_remove)

    r_max = int(constraints.max())
    present_states: list[set[str]] = []
    for pname in partitions:
        src = partitions_to_assign[pname]
        present_states.append(set(src.nodes_by_state.keys()))
        for s, ns in src.nodes_by_state.items():
            r_max = max(r_max, len(ns))

    assign = np.full((P, S, r_max), -1, np.int32)
    for pi, pname in enumerate(partitions):
        src = partitions_to_assign[pname]
        for s, ns in src.nodes_by_state.items():
            si = state_index[s]
            ri = 0
            for node in ns:
                if node in removed:
                    continue  # strip removed nodes (plan.go:84-88)
                if ri < r_max:
                    assign[pi, si, ri] = node_index[node]
                    ri += 1

    pweights = np.ones(P, np.float64)
    if opts.partition_weights:
        for pi, pname in enumerate(partitions):
            pweights[pi] = opts.partition_weights.get(pname, 1)

    nweights = np.ones(N, np.float64)
    nweight_set = np.zeros(N, np.uint8)
    if opts.node_weights:
        for ni, n in enumerate(nodes):
            if n in opts.node_weights:
                nweights[ni] = opts.node_weights[n]
                nweight_set[ni] = 1

    # Candidate mask: only nodes_all members that are not being removed are
    # ever newly chosen (nodesNext, plan.go:77); ghosts are row-only.
    valid = np.zeros(N, np.uint8)
    for ni, n in enumerate(nodes):
        if ni < n_candidates and n not in removed:
            valid[ni] = 1

    # Stickiness per (p, s) with the reference's resolution order
    # (plan.go:104-115 incl. the partition_weights gate).
    stickiness = np.full((P, S), 1.5, np.float64)
    pw, ss = opts.partition_weights, opts.state_stickiness
    ss_active = ss is not None and (
        pw is not None or opts.state_stickiness_standalone)
    for pi, pname in enumerate(partitions):
        if pw is not None and pname in pw:
            stickiness[pi, :] = float(pw[pname])
        elif ss_active:
            for si, s in enumerate(states):
                if s in ss:
                    stickiness[pi, si] = float(ss[s])

    # Hierarchy: globally interned ancestor ids per level, deep enough to
    # cover the whole tree (chain membership handles non-uniform depth).
    parents = opts.node_hierarchy or {}
    depth = 0
    for n in nodes:
        d, cur, seen = 0, n, set()
        while cur in parents and cur not in seen:
            seen.add(cur)
            cur = parents[cur]
            d += 1
        depth = max(depth, d)
    levels = depth + 1
    interned: dict[str, int] = {}

    def intern_anc(name: str) -> int:
        if name == "":
            return -1
        if name not in interned:
            interned[name] = len(interned)
        return interned[name]

    aid = np.full((levels, max(N, 1)), -1, np.int32)
    for level in range(levels):
        for ni, n in enumerate(nodes):
            aid[level, ni] = intern_anc(find_ancestor(n, parents, level))

    # find_leaves returns LEAVES only (plan.go:764-774): a listed node that
    # is itself a parent in the hierarchy can never be a hierarchy pick.
    children = parents_to_children(parents)
    is_leaf = np.ones(max(N, 1), np.uint8)
    for ni, n in enumerate(nodes):
        if children.get(n):
            is_leaf[ni] = 0

    rule_off = np.zeros(S + 1, np.int32)
    rule_inc: list[int] = []
    rule_exc: list[int] = []
    has_hierarchy = opts.hierarchy_rules is not None
    if has_hierarchy:
        for si, s in enumerate(states):
            for rule in (opts.hierarchy_rules or {}).get(s, []):
                rule_inc.append(rule.include_level)
                rule_exc.append(rule.exclude_level)
            rule_off[si + 1] = len(rule_inc)
    rule_inc_a = np.asarray(rule_inc or [0], np.int32)
    rule_exc_a = np.asarray(rule_exc or [0], np.int32)

    # Static partition rank: (heavier first, zero-padded-numeric name, name).
    def static_key(pname: str):
        w = 1
        if opts.partition_weights is not None:
            w = opts.partition_weights.get(pname, 1)
        return (_partition_weight_key(w), _partition_name_key(pname), pname)

    rank_order = sorted(range(P), key=lambda pi: static_key(partitions[pi]))
    static_rank = np.zeros(P, np.int32)
    for r, pi in enumerate(rank_order):
        static_rank[pi] = r

    # Category-0 flags: prev holders of state s on removed nodes
    # (plan.go:541-550).
    cat0 = np.zeros((S, P), np.uint8)
    if nodes_to_remove:
        for pi, pname in enumerate(partitions):
            last = prev_map.get(pname)
            if last is None:
                continue
            for si, s in enumerate(states):
                lpnbs = last.nodes_by_state.get(s)
                if lpnbs and strings_intersect(lpnbs, nodes_to_remove):
                    cat0[si, pi] = 1

    add_mask = np.zeros(max(N, 1), np.uint8)
    has_adds = nodes_to_add is not None
    if nodes_to_add:
        for n in nodes_to_add:
            ni = node_index.get(n)
            if ni is not None:
                add_mask[ni] = 1

    # Seed counts from prev_map (plan.go:94).
    counts = np.zeros((S, max(N, 1)), np.float64)
    for s, per_node in count_state_nodes(prev_map, opts.partition_weights).items():
        si = state_index.get(s)
        if si is None:
            continue
        for node, cnt in per_node.items():
            ni = node_index.get(node)
            if ni is not None:
                counts[si, ni] = cnt

    shortfall = np.zeros((P, S), np.int32)

    lib.blance_plan_inner(
        P, N, S, r_max, len(prev_map),
        _ptr(constraints, ctypes.c_int32), _ptr(priority, ctypes.c_int32),
        _ptr(pweights, ctypes.c_double), _ptr(nweights, ctypes.c_double),
        _ptr(nweight_set, ctypes.c_uint8), _ptr(valid, ctypes.c_uint8),
        _ptr(stickiness, ctypes.c_double),
        levels, _ptr(aid, ctypes.c_int32), _ptr(is_leaf, ctypes.c_uint8),
        _ptr(rule_off, ctypes.c_int32), _ptr(rule_inc_a, ctypes.c_int32),
        _ptr(rule_exc_a, ctypes.c_int32),
        1 if opts.node_score_booster is not None else 0,
        1 if has_hierarchy else 0,
        _ptr(static_rank, ctypes.c_int32), _ptr(cat0, ctypes.c_uint8),
        _ptr(add_mask, ctypes.c_uint8), 1 if has_adds else 0,
        _ptr(assign, ctypes.c_int32), _ptr(counts, ctypes.c_double),
        _ptr(shortfall, ctypes.c_int32),
    )

    # Decode: original state keys survive; assigned states always present.
    next_map: PartitionMap = {}
    warnings: dict[str, list[str]] = {}
    for pi, pname in enumerate(partitions):
        nbs: dict[str, list[str]] = {}
        for si, s in enumerate(states):
            assigned = int(constraints[si]) > 0
            if not assigned and s not in present_states[pi]:
                continue
            nbs[s] = [nodes[i] for i in assign[pi, si] if i >= 0]
            if shortfall[pi, si] > 0:
                warnings.setdefault(pname, []).append(
                    "could not meet constraints: %d, stateName: %s,"
                    " partitionName: %s" % (int(constraints[si]), s, pname))
        next_map[pname] = Partition(pname, nbs)
    return next_map, warnings


def _single_pass_opts(opts: PlanOptions) -> PlanOptions:
    import dataclasses
    return dataclasses.replace(opts, max_iterations=1)


def plan_next_map_native(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    nodes_to_add: Optional[list[str]],
    model: PartitionModel,
    opts: Optional[PlanOptions] = None,
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """Native-backed plan_next_map: bit-identical to the greedy backend.

    Runs the same convergence loop (plan.go:23-58) with each inner pass in
    C++.  Transparently falls back to the Python greedy when the native
    core can't model the request (custom hooks, unmodeled states) or the
    toolchain is unavailable.
    """
    opts = opts or PlanOptions()
    lib = _load_lib()
    if lib is None or not _native_supported(partitions_to_assign, model, opts):
        return plan_next_map_greedy(
            prev_map, partitions_to_assign, nodes_all,
            nodes_to_remove, nodes_to_add, model, opts)

    from ..core.types import copy_partition_map

    prev_map = copy_partition_map(prev_map)
    partitions_to_assign = copy_partition_map(partitions_to_assign)
    nodes_all = list(nodes_all)
    nodes_to_remove = list(nodes_to_remove) if nodes_to_remove is not None else []
    nta: Optional[list[str]] = (
        list(nodes_to_add) if nodes_to_add is not None else None)

    next_map: PartitionMap = {}
    warnings: dict[str, list[str]] = {}
    for _ in range(max(1, opts.max_iterations)):
        next_map, warnings = _plan_inner_native(
            lib, prev_map, partitions_to_assign, nodes_all,
            nodes_to_remove, nta, model, opts)
        if all(
            prev_map.get(p.name) is not None
            and p.nodes_by_state == prev_map[p.name].nodes_by_state
            for p in next_map.values()
        ):
            break
        for p in next_map.values():
            prev_map[p.name] = p
            partitions_to_assign[p.name] = p
        nodes_all = strings_remove(nodes_all, nodes_to_remove)
        nodes_to_remove = []
        nta = []
    return next_map, warnings
