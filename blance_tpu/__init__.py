"""blance_tpu — TPU-native partition assignment & rebalance orchestration.

A ground-up framework with the capabilities of couchbase/blance
(reference mounted at /root/reference): plan balanced partition->node
assignments under prioritized states, constraints, weights, stickiness and
rack/zone hierarchy rules; diff two maps into minimal ordered move sequences;
and orchestrate those moves with per-node concurrency limits, pluggable
prioritization, pause/resume/stop and streamed progress.

The planner's hot path is a batched (partitions x states x nodes) cost tensor
in JAX, sharded over the partition axis (see blance_tpu.plan.tensor and
blance_tpu.parallel); the exact sequential planner (blance_tpu.plan.greedy)
is the semantics oracle and small-problem backend.
"""

from .core.types import (
    HierarchyRule,
    HierarchyRules,
    Partition,
    PartitionMap,
    PartitionModel,
    PartitionModelState,
    PlanOptions,
    copy_partition_map,
    model,
    partition_map_from_json,
    partition_map_to_json,
)
from .core.setops import (
    strings_dedup,
    strings_intersect,
    strings_remove,
    strings_to_set,
)
from .moves.calc import NodeStateOp, calc_partition_moves
from .plan.api import plan_next_map, plan_next_map_legacy
from .plan.session import PlannerSession
from .rebalance import (
    RebalanceResult,
    RecoveryRound,
    load_partition_map,
    rebalance,
    rebalance_async,
    save_partition_map,
)
from .plan.greedy import (
    NodeScoreContext,
    count_state_nodes,
    default_node_score,
    flatten_nodes_by_state,
    plan_next_map_greedy,
    sort_state_names,
)

__version__ = "0.1.0"

__all__ = [
    "HierarchyRule",
    "HierarchyRules",
    "Partition",
    "PartitionMap",
    "PartitionModel",
    "PartitionModelState",
    "PlanOptions",
    "PlannerSession",
    "NodeScoreContext",
    "NodeStateOp",
    "calc_partition_moves",
    "copy_partition_map",
    "count_state_nodes",
    "default_node_score",
    "flatten_nodes_by_state",
    "model",
    "partition_map_from_json",
    "partition_map_to_json",
    "plan_next_map",
    "plan_next_map_greedy",
    "plan_next_map_legacy",
    "RebalanceResult",
    "RecoveryRound",
    "load_partition_map",
    "rebalance",
    "rebalance_async",
    "save_partition_map",
    "sort_state_names",
    "strings_dedup",
    "strings_intersect",
    "strings_remove",
    "strings_to_set",
]
