"""blance_tpu.orchestrate — asyncio rebalance control plane."""

from .csp import GET, PUT, Chan, ChanClosed, select
from .orchestrator import (
    MOVE_OP_WEIGHT,
    ErrorInterrupt,
    ErrorStopped,
    NextMoves,
    Orchestrator,
    OrchestratorOptions,
    OrchestratorProgress,
    PartitionMove,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)

__all__ = [
    "GET",
    "PUT",
    "Chan",
    "ChanClosed",
    "select",
    "MOVE_OP_WEIGHT",
    "ErrorInterrupt",
    "ErrorStopped",
    "NextMoves",
    "Orchestrator",
    "OrchestratorOptions",
    "OrchestratorProgress",
    "PartitionMove",
    "lowest_weight_partition_move_for_node",
    "orchestrate_moves",
]
