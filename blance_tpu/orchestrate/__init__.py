"""blance_tpu.orchestrate subpackage."""
