"""blance_tpu.orchestrate — asyncio rebalance control plane."""

from .csp import GET, PUT, Chan, ChanClosed, select
from .faults import FaultInjected, FaultPlan, NodeFaults
from .health import HALF_OPEN, HEALTHY, QUARANTINED, HealthTracker, NodeHealth
from .sched import (
    CriticalPathScheduler,
    LegacyWeightOrder,
    SchedulePlan,
    SchedulerPolicy,
)
from .orchestrator import (
    MOVE_OP_WEIGHT,
    ErrorInterrupt,
    ErrorStopped,
    MissingMoverError,
    MoveFailure,
    MoveTimeoutError,
    NextMoves,
    NodeQuarantinedError,
    Orchestrator,
    OrchestratorOptions,
    OrchestratorProgress,
    PartitionMove,
    lowest_weight_partition_move_for_node,
    orchestrate_moves,
)

__all__ = [
    "GET",
    "PUT",
    "Chan",
    "ChanClosed",
    "select",
    "FaultInjected",
    "FaultPlan",
    "NodeFaults",
    "HEALTHY",
    "QUARANTINED",
    "HALF_OPEN",
    "HealthTracker",
    "NodeHealth",
    "MOVE_OP_WEIGHT",
    "ErrorInterrupt",
    "ErrorStopped",
    "MissingMoverError",
    "MoveFailure",
    "MoveTimeoutError",
    "NextMoves",
    "NodeQuarantinedError",
    "Orchestrator",
    "OrchestratorOptions",
    "OrchestratorProgress",
    "PartitionMove",
    "lowest_weight_partition_move_for_node",
    "orchestrate_moves",
    "CriticalPathScheduler",
    "LegacyWeightOrder",
    "SchedulePlan",
    "SchedulerPolicy",
]
