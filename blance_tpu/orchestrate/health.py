"""Per-node health tracking: a circuit breaker for the orchestrator.

The reference orchestrator has no notion of node health — a node whose
assign callback keeps failing is fed moves forever (each one burning the
app's retry budget), and a dead node wedges the transition.  This module
adds the classic three-state breaker, per node:

    healthy ──(N consecutive failures)──> quarantined
    quarantined ──(probe_after_s elapsed)──> half-open
    half-open ──(probe succeeds)──> healthy
    half-open ──(probe fails)──> quarantined   (timer restarts)

While quarantined, the mover releases queued batches for the node
immediately as failures (``Orchestrator`` turns them into structured
``MoveFailure``s) instead of invoking the callback — so a dead node's
work drains fast and the failure-aware recovery replan
(``rebalance_async``) can re-place it on live nodes.  After
``probe_after_s`` the breaker admits exactly ONE probe batch at a time;
a success re-admits the node, a failure re-trips it.

Wall-clock enters only through the injectable ``clock`` callable
(default ``time.monotonic``), so tier-1 tests drive the breaker through
its whole state machine in virtual time, deterministically.

Every trip bumps the ``orchestrate.quarantine_trips`` counter on the
obs Recorder (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs import get_recorder

__all__ = ["HEALTHY", "QUARANTINED", "HALF_OPEN", "NodeHealth",
           "HealthTracker", "HEALTH_FORMAT_VERSION"]

# On-disk schema version for HealthTracker.to_dict/from_dict (bumped on
# any incompatible field change; from_dict refuses other versions).
HEALTH_FORMAT_VERSION = 1

HEALTHY = "healthy"
QUARANTINED = "quarantined"
HALF_OPEN = "half-open"


@dataclass
class NodeHealth:
    """Mutable breaker state for one node."""

    state: str = HEALTHY
    consecutive_failures: int = 0
    trips: int = 0  # lifetime quarantine entries
    tripped_at: float = 0.0  # clock() of the last trip
    probe_in_flight: bool = False
    # Cumulative seconds spent quarantined/half-open across CLOSED
    # quarantine intervals; the currently-open interval (tripped_at ->
    # now) is added at read time (HealthTracker.exposure_s) — the SLO
    # plane's per-node quarantine-exposure gauge.
    exposure_s: float = 0.0


@dataclass
class HealthTracker:
    """Circuit breaker over a set of nodes.

    threshold: consecutive failures (or timeouts) that trip quarantine.
    probe_after_s: quarantine dwell before the first half-open probe.
    clock: monotonic-seconds source; injectable for virtual-time tests.
    """

    threshold: int = 3
    probe_after_s: float = 1.0
    clock: Callable[[], float] = time.monotonic
    _nodes: dict[str, NodeHealth] = field(default_factory=dict)

    def _get(self, node: str) -> NodeHealth:
        h = self._nodes.get(node)
        if h is None:
            h = self._nodes[node] = NodeHealth()
        return h

    # -- outcome reporting ---------------------------------------------------

    def record_success(self, node: str) -> bool:
        """A callback attempt for ``node`` succeeded: half-open heals,
        failure streaks reset.  Returns True when THIS success healed a
        quarantined/half-open node (the breaker's heal transition)."""
        h = self._get(node)
        healed = h.state in (QUARANTINED, HALF_OPEN)
        if healed:
            # Close the open quarantine interval into the exposure total.
            h.exposure_s += max(self.clock() - h.tripped_at, 0.0)
        h.consecutive_failures = 0
        h.probe_in_flight = False
        h.state = HEALTHY
        return healed

    def record_failure(self, node: str) -> bool:
        """A callback attempt for ``node`` failed or timed out.  Returns
        True when THIS failure tripped the node into quarantine (a
        half-open probe failure re-trips and also returns True)."""
        h = self._get(node)
        h.consecutive_failures += 1
        was_open = h.state in (QUARANTINED, HALF_OPEN)
        if h.state == HALF_OPEN:
            h.probe_in_flight = False
            tripped = True
        else:
            tripped = h.state == HEALTHY and \
                h.consecutive_failures >= max(self.threshold, 1)
        if tripped:
            if was_open:
                # Half-open re-trip: the dwell so far closes into the
                # exposure total before the interval clock restarts.
                h.exposure_s += max(self.clock() - h.tripped_at, 0.0)
            h.state = QUARANTINED
            h.tripped_at = self.clock()
            h.trips += 1
            get_recorder().count("orchestrate.quarantine_trips")
        elif was_open:
            # Failure while quarantined without an admitted probe (e.g. a
            # retry already in flight when the trip happened): stay put,
            # keep the original dwell timer.
            h.state = QUARANTINED
        return tripped

    # -- admission -----------------------------------------------------------

    def admit(self, node: str) -> str:
        """Gate one batch for ``node``: "ok" (healthy), "probe" (half-open
        trial admission — exactly one at a time), or "reject" (quarantined:
        release the batch as a failure without calling the app)."""
        h = self._nodes.get(node)
        if h is None or h.state == HEALTHY:
            return "ok"
        if h.state == QUARANTINED and \
                self.clock() - h.tripped_at >= self.probe_after_s:
            h.state = HALF_OPEN
        if h.state == HALF_OPEN and not h.probe_in_flight:
            h.probe_in_flight = True
            return "probe"
        return "reject"

    def forget(self, node: str) -> None:
        """Drop ``node``'s breaker state entirely — a node REPLACED by
        the control plane (e.g. a preempted spot instance or a flapped
        zone coming back) starts with a clean slate instead of
        inheriting the dead incarnation's quarantine.  Its accumulated
        exposure is forgotten with it; read ``exposures()`` before
        forgetting if the SLO account needs the history."""
        self._nodes.pop(node, None)

    # -- introspection -------------------------------------------------------

    def state(self, node: str) -> str:
        h = self._nodes.get(node)
        return h.state if h is not None else HEALTHY

    def quarantined_nodes(self) -> list[str]:
        """Nodes currently tripped (quarantined or half-open), sorted —
        the set the recovery replan treats as ``nodes_to_remove``."""
        return sorted(n for n, h in self._nodes.items()
                      if h.state in (QUARANTINED, HALF_OPEN))

    def total_trips(self) -> int:
        return sum(h.trips for h in self._nodes.values())

    def exposure_s(self, node: str, now: Optional[float] = None) -> float:
        """Cumulative quarantined/half-open seconds for ``node``: every
        closed interval plus the currently-open one (if tripped)."""
        h = self._nodes.get(node)
        if h is None:
            return 0.0
        total = h.exposure_s
        if h.state in (QUARANTINED, HALF_OPEN):
            t = self.clock() if now is None else now
            total += max(t - h.tripped_at, 0.0)
        return total

    def exposures(self, now: Optional[float] = None) -> dict[str, float]:
        """node -> cumulative exposure seconds, for every node that has
        ever been quarantined (the SLO per-node exposure gauge)."""
        out: dict[str, float] = {}
        for node, h in self._nodes.items():
            if h.trips > 0:
                out[node] = self.exposure_s(node, now)
        return out

    # -- serialization (durability snapshots) --------------------------------

    def to_dict(self, now: Optional[float] = None) -> dict[str, object]:
        """Versioned JSON-safe snapshot of the whole breaker.

        The open quarantine interval of a tripped node is stored as an
        AGE (``now - tripped_at``), not an absolute instant: the clock
        that measured ``tripped_at`` dies with the process, and a new
        incarnation's monotonic clock has an unrelated epoch.  Ages are
        epoch-free, so ``from_dict`` can re-base them onto whatever
        clock the restored tracker runs on, and exposure accounting
        stays continuous across the crash.
        """
        t = self.clock() if now is None else now
        nodes: dict[str, dict[str, object]] = {}
        for node, h in sorted(self._nodes.items()):
            open_interval = h.state in (QUARANTINED, HALF_OPEN)
            nodes[node] = {
                "state": h.state,
                "consecutive_failures": h.consecutive_failures,
                "trips": h.trips,
                "exposure_s": h.exposure_s,
                "tripped_age_s": (
                    max(t - h.tripped_at, 0.0) if open_interval else None),
            }
        return {
            "version": HEALTH_FORMAT_VERSION,
            "threshold": self.threshold,
            "probe_after_s": self.probe_after_s,
            "nodes": nodes,
        }

    @classmethod
    def from_dict(cls, data: dict[str, object], *,
                  clock: Callable[[], float] = time.monotonic,
                  now: Optional[float] = None) -> "HealthTracker":
        """Rebuild a tracker on a NEW clock from :meth:`to_dict` output.

        Open quarantine intervals are re-based: ``tripped_at`` becomes
        ``now - tripped_age_s`` on the new clock, so dwell timers and
        the open-interval exposure resume exactly where the crash cut
        them.  ``probe_in_flight`` is deliberately NOT restored — an
        in-flight probe died with the old process, and carrying the
        flag would wedge admission (half-open rejects everything until
        a completion that can never arrive); the restored node simply
        re-admits a fresh probe when its dwell allows.
        """
        version = data.get("version")
        if version != HEALTH_FORMAT_VERSION:
            raise ValueError(
                f"health snapshot version {version!r} != "
                f"{HEALTH_FORMAT_VERSION} (incompatible snapshot)")

        def num(v: object) -> float:
            assert isinstance(v, (int, float)) and not isinstance(v, bool)
            return float(v)

        tracker = cls(
            threshold=int(num(data["threshold"])),
            probe_after_s=num(data["probe_after_s"]),
            clock=clock)
        t = clock() if now is None else now
        raw_nodes = data.get("nodes", {})
        assert isinstance(raw_nodes, dict)
        for node, entry in raw_nodes.items():
            assert isinstance(entry, dict)
            age = entry.get("tripped_age_s")
            tracker._nodes[str(node)] = NodeHealth(
                state=str(entry["state"]),
                consecutive_failures=int(num(entry["consecutive_failures"])),
                trips=int(num(entry["trips"])),
                tripped_at=(t - num(age)) if age is not None else 0.0,
                probe_in_flight=False,
                exposure_s=num(entry["exposure_s"]),
            )
        return tracker
