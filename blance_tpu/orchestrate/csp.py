"""Minimal CSP layer for the orchestrator: rendezvous channels + select.

The reference's control plane is built from goroutines and unbuffered
channels (reference: /root/reference/orchestrate.go:258-261,319-335); this
module provides the same primitives for asyncio so the orchestrator's round
structure (broadcast, first-feed interrupt, in-flight waits) can be expressed
directly:

- ``Chan``: unbuffered rendezvous channel.  ``close()`` broadcasts: pending
  and future ``get``s complete with ``(None, False)`` — the Go
  closed-channel convention — which doubles as the stop/pause/broadcast
  signal (Go's ``close(stopCh)`` idiom).
- ``select(...)``: waits on several get/put operations, commits exactly one.

Single-threaded asyncio makes the commit discipline simple: all bookkeeping
between awaits is atomic, and a shared ``_Token`` per select guarantees
exactly-once completion.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Optional

__all__ = ["Chan", "ChanClosed", "select", "GET", "PUT"]


class ChanClosed(Exception):
    """Raised when putting to a closed channel."""


class _Token:
    """Exactly-once commit token shared by all ops of one select."""

    __slots__ = ("claimed",)

    def __init__(self) -> None:
        self.claimed = False

    def claim(self) -> bool:
        if self.claimed:
            return False
        self.claimed = True
        return True


class _Waiter:
    """One registered get/put op: a future plus its select token."""

    __slots__ = ("future", "token", "index")

    def __init__(self, future: "asyncio.Future[object]", token: _Token,
                 index: int) -> None:
        self.future = future
        self.token = token
        self.index = index


class Chan:
    """Unbuffered (rendezvous) channel of Go semantics.

    get() -> (value, True) on receive, (None, False) once closed.
    put() blocks for a receiver; raises ChanClosed if/when closed.
    """

    def __init__(self) -> None:
        self._getters: deque[_Waiter] = deque()
        self._putters: deque[tuple[_Waiter, Any]] = deque()
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    # -- non-blocking attempts (used by select's first pass) ----------------

    def _try_get(self) -> Optional[tuple[Any, bool]]:
        while self._putters:
            waiter, item = self._putters.popleft()
            # A done future here means the waiter was abandoned (its
            # awaiting task cancelled, e.g. an aborted timed wait) or its
            # select already committed elsewhere: skip it — resolving it
            # would raise InvalidStateError, and treating a cancelled
            # putter's item as delivered would lose the rendezvous
            # guarantee.
            if waiter.future.done():
                continue
            if waiter.token.claim():
                waiter.future.set_result((waiter.index, None))
                return (item, True)
        if self._closed:
            return (None, False)
        return None

    def _try_put(self, item: Any) -> bool:
        if self._closed:
            raise ChanClosed()
        while self._getters:
            waiter = self._getters.popleft()
            if waiter.future.done():  # abandoned/committed — see _try_get
                continue
            if waiter.token.claim():
                waiter.future.set_result((waiter.index, (item, True)))
                return True
        return False

    # -- registration (select's second pass) --------------------------------

    def _add_getter(self, waiter: _Waiter) -> None:
        self._getters.append(waiter)

    def _add_putter(self, waiter: _Waiter, item: Any) -> None:
        self._putters.append((waiter, item))

    def _gc(self) -> None:
        """Drop claimed AND abandoned (cancelled-future) waiters so
        deques don't grow across selects or expired timed waits."""
        self._getters = deque(
            w for w in self._getters
            if not w.token.claimed and not w.future.done())
        self._putters = deque(
            (w, i) for (w, i) in self._putters
            if not w.token.claimed and not w.future.done()
        )

    # -- blocking ops --------------------------------------------------------

    async def get(self) -> tuple[Any, bool]:
        got = self._try_get()
        if got is not None:
            return got
        token = _Token()
        fut: "asyncio.Future[object]" = \
            asyncio.get_running_loop().create_future()
        self._add_getter(_Waiter(fut, token, 0))
        _, value = await fut
        return value

    async def put(self, item: Any) -> None:
        if self._try_put(item):
            return
        token = _Token()
        fut: "asyncio.Future[object]" = \
            asyncio.get_running_loop().create_future()
        self._add_putter(_Waiter(fut, token, 0), item)
        _, err = await fut
        if err is not None:
            raise err

    def close(self) -> None:
        """Idempotent close; wakes all pending getters/putters (skipping
        abandoned waiters whose futures were cancelled)."""
        if self._closed:
            return
        self._closed = True
        while self._getters:
            waiter = self._getters.popleft()
            if waiter.future.done():
                continue
            if waiter.token.claim():
                waiter.future.set_result((waiter.index, (None, False)))
        while self._putters:
            waiter, _ = self._putters.popleft()
            if waiter.future.done():
                continue
            if waiter.token.claim():
                waiter.future.set_result((waiter.index, ChanClosed()))

    def __aiter__(self) -> "Chan":
        return self

    async def __anext__(self) -> Any:
        value, ok = await self.get()
        if not ok:
            raise StopAsyncIteration
        return value


GET = "get"
PUT = "put"


async def select(*ops: tuple[Any, ...]) -> tuple[int, Any]:
    """Wait for the first ready op among (GET, chan) / (PUT, chan, item).

    Returns (index, value) where value is (item, ok) for a get and None for
    a put.  Exactly one op commits, like Go's select.
    """
    # First pass: anything immediately ready?
    for i, op in enumerate(ops):
        if op[0] == GET:
            got = op[1]._try_get()
            if got is not None:
                return (i, got)
        else:
            if op[1]._try_put(op[2]):
                return (i, None)

    # Second pass: register on all, await first commit.
    token = _Token()
    fut: "asyncio.Future[object]" = \
        asyncio.get_running_loop().create_future()
    chans = []
    for i, op in enumerate(ops):
        waiter = _Waiter(fut, token, i)
        if op[0] == GET:
            op[1]._add_getter(waiter)
        else:
            op[1]._add_putter(waiter, op[2])
        chans.append(op[1])
    try:
        index, value = await fut
    finally:
        for ch in chans:
            ch._gc()
    if isinstance(value, ChanClosed):
        raise value
    return (index, value)
