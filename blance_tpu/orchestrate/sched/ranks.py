"""Upward-rank (critical-path) priorities over the leveled move DAG.

The move DAG is a union of per-partition chains, so a move's upward
rank — its cost plus the longest path of predicted cost below it —
reduces to the SUFFIX SUM of its chain's remaining costs:

    rank[p][k] = cost[p][k] + rank[p][k + 1]

which is exactly a longest-path sweep over the DAG's levels, last level
first.  Two implementations share that recurrence:

- **host** (the default below ``device_threshold`` total moves): plain
  Python floats, zero dispatch overhead — the right tool for the
  simulator-scale move sets the control loop sees every cycle;
- **device** (``rank_levels``, a jitted ``lax.scan`` over the level
  axis of the ``[P, L]`` zero-padded cost matrix): one fused program
  for the 100k+-move sets a fleet-scale drain produces, attributed to
  the ``sched.ranks`` entry in the compile observatory and shape-
  audited by ``analysis/shape_audit.py``.

Both paths emit a counter (``sched.host_ranks`` / ``sched.device_ranks``)
so dashboards can see which engine a deployment actually runs.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

__all__ = ["DEVICE_THRESHOLD", "rank_levels", "upward_ranks"]

# Total remaining moves at which the rank sweep moves on-device.  Host
# suffix sums are O(M) python-loop work — fine to ~thousands of moves;
# past that the padded [P, L] scan amortizes its dispatch.
DEVICE_THRESHOLD = int(os.environ.get("BLANCE_SCHED_DEVICE_THRESHOLD",
                                      "4096"))

_rank_levels_jit: Optional[Any] = None


def rank_levels(costs: Any) -> Any:
    """Jitted leveled-DAG longest-path sweep: ``costs`` is the
    ``[P, L]`` float32 per-move cost matrix (rows = chains, column k =
    the chain's level-k move, zero-padded past each chain's end);
    returns the ``[P, L]`` upward ranks (suffix sums).  Zero padding is
    inert: a padded level contributes nothing to the ranks before it."""
    global _rank_levels_jit
    if _rank_levels_jit is None:
        import jax
        import jax.numpy as jnp

        def _impl(costs: Any) -> Any:
            def step(carry: Any, level_cost: Any) -> tuple[Any, Any]:
                rank = level_cost + carry
                return rank, rank

            # Scan the level axis back-to-front: carry = the successor
            # level's ranks, the longest-path recurrence per chain.
            init = jnp.zeros(costs.shape[0], costs.dtype)
            _, ranks_rev = jax.lax.scan(step, init, costs[:, ::-1].T)
            return ranks_rev.T[:, ::-1]

        _rank_levels_jit = jax.jit(_impl)
    return _rank_levels_jit(costs)


def _upward_ranks_host(
        chain_costs: Sequence[Sequence[float]]) -> list[list[float]]:
    out: list[list[float]] = []
    for costs in chain_costs:
        ranks = [0.0] * len(costs)
        acc = 0.0
        for k in range(len(costs) - 1, -1, -1):
            acc += costs[k]
            ranks[k] = acc
        out.append(ranks)
    return out


def _upward_ranks_device(
        chain_costs: Sequence[Sequence[float]]) -> list[list[float]]:
    import numpy as np

    from ...obs import device as obs_device

    lens = [len(c) for c in chain_costs]
    max_len = max(lens, default=0)
    if max_len == 0:
        return [[] for _ in chain_costs]
    padded = np.zeros((len(chain_costs), max_len), dtype=np.float32)
    for i, costs in enumerate(chain_costs):
        padded[i, :lens[i]] = costs
    with obs_device.entry("sched.ranks"):
        ranks = np.asarray(rank_levels(padded))
    return [ranks[i, :lens[i]].tolist() for i in range(len(chain_costs))]


def upward_ranks(
    chain_costs: Sequence[Sequence[float]],
    device_threshold: Optional[int] = None,
    recorder: Optional[Any] = None,
) -> list[list[float]]:
    """Per-chain upward ranks (suffix sums of predicted move costs).

    ``chain_costs[i][k]`` is the predicted cost of chain ``i``'s
    level-``k`` remaining move; the result is shape-congruent.  Move
    sets of ``device_threshold`` moves or more run the jitted device
    sweep (float32); smaller sets stay on host (python floats).  Pass
    ``device_threshold=0`` to force the device path, or a huge value to
    pin the host path."""
    threshold = DEVICE_THRESHOLD if device_threshold is None \
        else device_threshold
    total = sum(len(c) for c in chain_costs)
    if total >= threshold:
        if recorder is not None:
            recorder.count("sched.device_ranks")
        return _upward_ranks_device(chain_costs)
    if recorder is not None:
        recorder.count("sched.host_ranks")
    return _upward_ranks_host(chain_costs)
