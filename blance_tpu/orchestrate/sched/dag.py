"""Move-DAG builder: dependencies + machine capacities for scheduling.

The orchestrator's per-partition move lists are already DEPENDENCY
CHAINS: the cursor (``NextMoves.next``) releases move ``i+1`` only after
move ``i``'s batch succeeded, which is exactly what makes the plans safe
(the ``del`` off the old holder must not run before the ``add`` onto the
new one completed, a ``promote`` must not run before the replica it
promotes was built).  This module makes that structure explicit as a
DAG the scheduler can reason about:

- one :class:`DagMove` per REMAINING move (cursor position onward;
  abandoned partitions contribute nothing),
- edges = the within-partition chain order (level ``k`` of the DAG is
  every chain's ``k``-th remaining move — the leveled form the device
  rank kernel scans over),
- machines = one lane set per destination node with capacity
  ``max_concurrent_partition_moves_per_node`` (the orchestrator feeds a
  node at most that many moves per batch).

``build_move_dag`` also VALIDATES the state-transition order per
(partition, node) lifecycle and raises :class:`MoveDagError` on a chain
that would tear coverage if reordered by a buggy policy: an op on a
node after its ``del``, or a ``promote``/``demote``/``del`` of a node
before the ``add`` that creates it (when the chain contains that
``add``).  The reference move calculus never produces such chains; the
check guards hand-built cursors.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Any, Mapping, Sequence

__all__ = ["DagMove", "MoveDag", "MoveDagError", "build_move_dag"]


class MoveDagError(ValueError):
    """A partition's move chain violates the state-transition order."""


@dataclass(frozen=True)
class DagMove:
    """One remaining move: a node of the DAG.

    ``index`` is the ABSOLUTE index into the partition's full move list
    (the cursor's coordinate system), so a plan entry maps back onto
    the live ``NextMoves`` state without translation.  ``level`` is the
    position within the REMAINING chain (the DAG layer)."""

    partition: str
    index: int
    level: int
    node: str
    state: str
    op: str


@dataclass(frozen=True)
class MoveDag:
    """The leveled move DAG plus its machine model.

    ``chains`` maps partition -> its remaining moves in dependency
    order; ``machines`` maps each schedulable destination node to its
    lane count.  Moves whose destination has no machine (no mover, or
    quarantined) are still IN the chains — the list scheduler reports
    them (and their chain successors) as stalled instead of placing
    them on a lane."""

    chains: Mapping[str, tuple[DagMove, ...]]
    machines: Mapping[str, int]

    @cached_property
    def levels(self) -> tuple[tuple[DagMove, ...], ...]:
        """``levels[k]`` = every chain's ``k``-th remaining move — the
        leveled form the device rank sweep's ``[P, L]`` padding mirrors.
        Derived lazily: the scheduler itself ranks/places off ``chains``
        directly, so a bind or mid-schedule rebuild (one sync no-await
        window) never pays for materializing it."""
        max_len = max((len(c) for c in self.chains.values()), default=0)
        return tuple(
            tuple(chain[k] for chain in self.chains.values()
                  if len(chain) > k)
            for k in range(max_len))

    def moves(self) -> list[DagMove]:
        """Every remaining move, chain-grouped, chain order preserved."""
        out: list[DagMove] = []
        for chain in self.chains.values():
            out.extend(chain)
        return out

    def predecessor(self, mv: DagMove) -> DagMove | None:
        """The move that must complete before ``mv`` (chain edge)."""
        if mv.level == 0:
            return None
        return self.chains[mv.partition][mv.level - 1]


def _validate_chain(partition: str, moves: Sequence[Any]) -> None:
    """State-transition order per (partition, node) lifecycle: add ->
    promote/demote -> del, with nothing after the del and nothing
    before an add the chain itself contains."""
    adds_at: dict[str, int] = {}
    deleted_at: dict[str, int] = {}
    for i, mv in enumerate(moves):
        if mv.op == "add":
            adds_at.setdefault(mv.node, i)
    for i, mv in enumerate(moves):
        dead = deleted_at.get(mv.node)
        if dead is not None:
            raise MoveDagError(
                f"partition {partition!r}: move {i} ({mv.op} on "
                f"{mv.node!r}) follows that node's del at move {dead} — "
                f"nothing may touch a node after its removal")
        add_i = adds_at.get(mv.node)
        if add_i is not None and i < add_i and mv.op != "add":
            raise MoveDagError(
                f"partition {partition!r}: move {i} ({mv.op} on "
                f"{mv.node!r}) precedes the add that creates that node "
                f"at move {add_i} — run the add first (make before "
                f"break)")
        if mv.op == "del":
            deleted_at[mv.node] = i


def build_move_dag(
    cursors: Mapping[str, Any],
    nodes_all: Sequence[str] = (),
    max_concurrent: int = 1,
    validate: bool = True,
) -> MoveDag:
    """Build the leveled move DAG from live move cursors.

    ``cursors`` is the orchestrator's ``map_partition_to_next_moves``
    view (anything mapping partition -> an object with ``next``,
    ``moves`` and optional ``failed_at``); only moves from the cursor
    position onward enter the DAG, and an abandoned partition
    (``failed_at`` set) contributes nothing — its remaining moves must
    never be scheduled.  ``nodes_all`` + ``max_concurrent`` define the
    machine model (lanes per destination node)."""
    lanes = max_concurrent if max_concurrent > 0 else 1
    chains: dict[str, tuple[DagMove, ...]] = {}
    for name in sorted(cursors):
        nm = cursors[name]
        if validate:
            _validate_chain(name, nm.moves)
        if getattr(nm, "failed_at", None) is not None:
            continue
        start = nm.next
        if start >= len(nm.moves):
            continue
        chains[name] = tuple(
            DagMove(partition=name, index=start + k, level=k,
                    node=mv.node, state=mv.state, op=mv.op)
            for k, mv in enumerate(nm.moves[start:]))
    machines = {node: lanes for node in nodes_all}
    return MoveDag(chains=chains, machines=machines)
