"""Scheduler policies: the move-ordering interface the orchestrator binds.

The orchestrator's supplier asks, per destination node and per round,
"which available move next?"; a :class:`SchedulerPolicy` answers.  Two
implementations:

- :class:`LegacyWeightOrder` — the reference's app-weight order
  (``MOVE_OP_WEIGHT``: promote < demote < add < del, first-lowest wins
  ties), EXTRACTED verbatim from ``orchestrate/orchestrator.py`` behind
  this interface.  It is the pinned default: an orchestration with no
  ``OrchestratorOptions.scheduler`` set behaves byte-identically to the
  pre-extraction code (the untouched ``test_orchestrate*`` suites pin
  it).
- :class:`CriticalPathScheduler` — critical-path list scheduling
  (arxiv 1711.01912): upward-rank priorities from calibrated
  :meth:`~blance_tpu.obs.costmodel.CostModel.predict_move` costs over
  the move DAG (:mod:`.dag`), HEFT-style earliest-finish assignment
  onto per-node lanes (:func:`list_schedule`) for the makespan
  prediction, and the highest-rank-first selection rule at feed time.
  The final map and the move SET are bit-identical to the legacy order
  by construction — the policy only chooses ORDER, the cursors still
  release each partition's moves strictly in sequence — so only the
  clock changes.  When the health breaker quarantines a node the bound
  scheduler REBUILDS priorities from the remaining DAG and the live
  cursor state (``sched.reschedules``); a controller supersede rebuilds
  for free, because each new pass binds the policy against the fresh
  move plans computed from the achieved map.

Metrics (``sched.*`` in the registry; docs/OBSERVABILITY.md):
makespan prediction, critical-path length, lane utilization at every
(re)build; achieved makespan and predicted-vs-actual relative error at
finish; reschedule and rank-engine counters.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

from ...obs.costmodel import CostModel, default_op_priors
from ...obs.recorder import Recorder
from .dag import MoveDag, build_move_dag
from .ranks import upward_ranks

__all__ = [
    "MOVE_OP_WEIGHT",
    "BoundScheduler",
    "CriticalPathScheduler",
    "LegacyWeightOrder",
    "ScheduledMove",
    "SchedulePlan",
    "SchedulerPolicy",
    "list_schedule",
    "lowest_weight_partition_move_for_node",
]


MOVE_OP_WEIGHT = {"promote": 1, "demote": 2, "add": 3, "del": 4}


def lowest_weight_partition_move_for_node(
    node: str, moves: Sequence[Any]
) -> int:
    """Default FindMoveFunc: index of the lightest op (orchestrate.go:177-186).

    First-lowest wins ties, so single-node promotions/demotions go first and
    clients regain coverage quickly.
    """
    r = 0
    for i, move in enumerate(moves):
        if MOVE_OP_WEIGHT.get(moves[r].op, 0) > MOVE_OP_WEIGHT.get(move.op, 0):
            r = i
    return r


class BoundScheduler(abc.ABC):
    """One orchestration run's scheduler state (``Orchestrator.sched``).

    ``select`` is the feed-time hook (same contract as the app's
    ``find_move``, but over the live cursors so no move views need
    materializing); the rest are lifecycle notifications the
    orchestrator drives.  All methods are plain sync code — mutations
    are atomic on the event loop (race lint ``SHARED_STATE``)."""

    # True when the orchestrator should register this bound as a move
    # observer (``on_batch`` sees every batch outcome).  The legacy
    # bound opts out so the default path's observer loop stays empty.
    observes_batches: bool = False

    @abc.abstractmethod
    def select(self, node: str, candidates: Sequence[Any]) -> int:
        """Index of the move to feed next for ``node``; ``candidates``
        are live cursors (``NextMoves``-shaped) whose current move all
        target ``node``."""

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        """Move-observer hook (only called when ``observes_batches``)."""

    def on_quarantine(self, node: str) -> None:
        """The health breaker quarantined ``node`` — rebuild if the
        policy maintains an online schedule."""

    def on_heal(self, node: str) -> None:
        """A half-open probe healed ``node`` — its lanes rejoin the
        machine model; rebuild if the policy maintains one."""

    def finish(self, now: float) -> None:
        """The orchestration wound down (progress stream closing)."""


class SchedulerPolicy(abc.ABC):
    """A reusable move-ordering policy; ``bind`` yields per-run state.

    One policy object can serve many orchestrations (the controller's
    passes, recovery rounds): every run binds fresh, so priorities are
    always rebuilt from that run's move plans — a superseded pass never
    replays a stale order."""

    name: str = "scheduler"

    @abc.abstractmethod
    def bind(self, nodes: Sequence[str], cursors: Mapping[str, Any],
             max_concurrent: int, recorder: Recorder) -> BoundScheduler:
        """Bind to one orchestration: its mover nodes, its live move
        cursors (``map_partition_to_next_moves``), the per-node lane
        count, and the run's Recorder (time source + metric sink)."""


# -- the pinned default: the reference's app-weight order ---------------------


class _LegacyBound(BoundScheduler):
    """Stateless; selection is EXACTLY the pre-extraction fast path
    (hand the op-bearing cursor entries straight to the weight rule)."""

    def select(self, node: str, candidates: Sequence[Any]) -> int:
        return lowest_weight_partition_move_for_node(
            node, [nm.moves[nm.next] for nm in candidates])


_LEGACY_BOUND = _LegacyBound()


class LegacyWeightOrder(SchedulerPolicy):
    """The reference ordering (orchestrate.go:177-186) behind the
    scheduler interface — the default when ``OrchestratorOptions.
    scheduler`` is None, byte-identical to the pre-sched code."""

    name = "legacy-weight"

    def bind(self, nodes: Sequence[str], cursors: Mapping[str, Any],
             max_concurrent: int, recorder: Recorder) -> BoundScheduler:
        return _LEGACY_BOUND


# -- critical-path list scheduling -------------------------------------------


@dataclass(frozen=True)
class ScheduledMove:
    """One move placed on a node lane by the list scheduler."""

    partition: str
    index: int  # absolute index into the partition's move list
    node: str
    lane: int
    start_s: float
    finish_s: float


@dataclass(frozen=True)
class SchedulePlan:
    """A predicted execution plan: every remaining move exactly once —
    on a lane, or in ``stalled`` when its chain reaches a machine-less
    (moverless / quarantined) node.  ``critical_path_s`` is the longest
    remaining chain by predicted cost (a makespan lower bound);
    ``lane_utilization`` is predicted busy time over the active nodes'
    lane capacity across the makespan."""

    makespan_s: float
    critical_path_s: float
    lane_utilization: float
    moves: tuple[ScheduledMove, ...]
    stalled: tuple[tuple[str, int], ...]
    lanes_total: int

    def scheduled_keys(self) -> set[tuple[str, int]]:
        return {(m.partition, m.index) for m in self.moves}


def list_schedule(
    dag: MoveDag,
    costs: Mapping[tuple[str, int], float],
    ranks: Mapping[tuple[str, int], float],
) -> SchedulePlan:
    """HEFT-style earliest-finish list scheduling of the move DAG.

    Moves are taken in non-increasing upward-rank order (which respects
    the chain edges by construction: a predecessor's rank is its
    successor's plus its own positive cost) and placed on their
    destination node's earliest-free lane, starting no earlier than
    their predecessor's finish.  Deterministic: ties break on
    (partition, index), lanes on lowest index."""
    order = sorted(
        dag.moves(),
        key=lambda m: (-ranks.get((m.partition, m.index), 0.0),
                       m.partition, m.index))
    lane_free: dict[str, list[float]] = {
        node: [0.0] * lanes for node, lanes in dag.machines.items()}
    chain_ready: dict[str, float] = {}
    chain_cost: dict[str, float] = {}
    chain_stalled: dict[str, int] = {}
    scheduled: list[ScheduledMove] = []
    stalled: list[tuple[str, int]] = []
    busy = 0.0
    active_nodes: set[str] = set()
    for mv in order:
        stall_at = chain_stalled.get(mv.partition)
        if stall_at is not None and mv.level >= stall_at:
            stalled.append((mv.partition, mv.index))
            continue
        lanes = lane_free.get(mv.node)
        if lanes is None:
            # No machine (moverless or quarantined destination): this
            # move — and everything after it in the chain — stalls.
            chain_stalled[mv.partition] = mv.level
            stalled.append((mv.partition, mv.index))
            continue
        lane = min(range(len(lanes)), key=lambda i: lanes[i])
        cost = max(costs.get((mv.partition, mv.index), 0.0), 0.0)
        start = max(lanes[lane], chain_ready.get(mv.partition, 0.0))
        finish = start + cost
        lanes[lane] = finish
        chain_ready[mv.partition] = finish
        chain_cost[mv.partition] = chain_cost.get(mv.partition, 0.0) + cost
        busy += cost
        active_nodes.add(mv.node)
        scheduled.append(ScheduledMove(
            partition=mv.partition, index=mv.index, node=mv.node,
            lane=lane, start_s=start, finish_s=finish))
    makespan = max((m.finish_s for m in scheduled), default=0.0)
    # Longest SCHEDULED chain by predicted cost: for a fully scheduled
    # chain this is its head's upward rank; a chain stalled at level k
    # contributes only its scheduled prefix, so the gauge stays a true
    # lower bound on the predicted makespan (a stalled tail isn't in
    # the schedule and must not inflate the "bound" past it).
    critical = max(chain_cost.values(), default=0.0)
    active_lanes = sum(dag.machines.get(n, 0) for n in active_nodes)
    util = busy / (active_lanes * makespan) \
        if makespan > 0.0 and active_lanes > 0 else 0.0
    return SchedulePlan(
        makespan_s=makespan, critical_path_s=critical,
        lane_utilization=util, moves=tuple(scheduled),
        stalled=tuple(stalled),
        lanes_total=sum(dag.machines.values()))


class _CriticalPathBound(BoundScheduler):
    """Per-run critical-path scheduler state.

    Mutable shared state (``_rank``, ``plan``, ``last_remaining``,
    ``_quarantined``, ``_t_last_exec``, ``reschedules``) is declared in
    the race lint's ``SHARED_STATE`` table: every mutator is a plain
    sync method (one atomic window on the event loop) — ``select`` runs
    on the supplier task, ``on_batch``/``on_quarantine`` on mover
    tasks, never concurrently within a window."""

    observes_batches = True

    def __init__(self, cost_model: CostModel, nodes: Sequence[str],
                 cursors: Mapping[str, Any], max_concurrent: int,
                 recorder: Recorder,
                 device_threshold: Optional[int]) -> None:
        self._cost = cost_model
        self._nodes = tuple(nodes)
        self._cursors = cursors  # the orchestrator's LIVE cursor map
        self._lanes = max_concurrent if max_concurrent > 0 else 1
        self._rec = recorder
        self._device_threshold = device_threshold
        self._quarantined: set[str] = set()
        self._t0 = recorder.now()
        self._t_last_exec: Optional[float] = None
        self._first_predicted: Optional[float] = None
        self._finished = False
        self.reschedules = 0
        self._rank: dict[tuple[str, int], float] = {}
        self.plan: SchedulePlan = SchedulePlan(
            0.0, 0.0, 0.0, (), (), 0)
        # The (partition, absolute-index) set the current plan was
        # built from, captured in the SAME sync window as the plan —
        # the explorer's every-unfinished-move-exactly-once probe
        # compares plan vs this snapshot, race-free by construction.
        self.last_remaining: frozenset[tuple[str, int]] = frozenset()
        self._build(validate=True)

    # -- schedule construction ------------------------------------------------

    def _build(self, validate: bool = False) -> None:
        dag = build_move_dag(
            self._cursors,
            nodes_all=[n for n in self._nodes
                       if n not in self._quarantined],
            max_concurrent=self._lanes, validate=validate)
        chains = list(dag.chains.values())
        chain_costs = [
            [self._cost.predict_move(mv) for mv in chain]
            for chain in chains]
        chain_ranks = upward_ranks(
            chain_costs, device_threshold=self._device_threshold,
            recorder=self._rec)
        costs: dict[tuple[str, int], float] = {}
        rank: dict[tuple[str, int], float] = {}
        for chain, ccosts, cranks in zip(chains, chain_costs,
                                         chain_ranks):
            for mv, c, r in zip(chain, ccosts, cranks):
                costs[(mv.partition, mv.index)] = c
                rank[(mv.partition, mv.index)] = r
        self._rank = rank
        self.plan = list_schedule(dag, costs, rank)
        self.last_remaining = frozenset(rank)
        if self._first_predicted is None:
            self._first_predicted = self.plan.makespan_s
        self._rec.set_gauge("sched.makespan_predicted_s",
                            self.plan.makespan_s)
        self._rec.set_gauge("sched.critical_path_s",
                            self.plan.critical_path_s)
        self._rec.set_gauge("sched.lane_utilization",
                            self.plan.lane_utilization)

    # -- orchestrator hooks ---------------------------------------------------

    def select(self, node: str, candidates: Sequence[Any]) -> int:
        best = 0
        best_key: Optional[tuple[float, str]] = None
        for i, nm in enumerate(candidates):
            r = self._rank.get((nm.partition, nm.next), 0.0)
            key = (-r, nm.partition)
            if best_key is None or key < best_key:
                best_key = key
                best = i
        return best

    def on_batch(self, node: str, moves: Sequence[Any], ok: bool,
                 now: float) -> None:
        if ok:
            self._t_last_exec = now

    def on_quarantine(self, node: str) -> None:
        """Online reschedule: the breaker quarantined ``node``, so its
        lanes leave the machine model and every surviving move's
        priority is rebuilt from the live cursors (the orchestrator's
        achieved frontier) and the cost model's CURRENT estimates —
        never a replay of the stale order."""
        self._quarantined.add(node)
        self.reschedules += 1
        self._rec.count("sched.reschedules")
        self._build()

    def on_heal(self, node: str) -> None:
        """The half-open probe healed ``node``: its lanes rejoin the
        machine model and the schedule rebuilds, so the makespan/
        critical-path/utilization gauges (and the wind-down rel-err
        score) track the machines actually serving — a heal-blind plan
        would keep the node's chains 'stalled' forever."""
        if node not in self._quarantined:
            return
        self._quarantined.discard(node)
        self.reschedules += 1
        self._rec.count("sched.reschedules")
        self._build()

    def quarantined(self) -> frozenset[str]:
        return frozenset(self._quarantined)

    def finish(self, now: float) -> None:
        if self._finished:
            return
        self._finished = True
        # A cancelled/superseded orchestration winds down with live
        # moves still pending — that truncated clock is not an achieved
        # makespan, and scoring |predicted - actual| against it would
        # drown the rel-err histogram in supersede noise (abandoned
        # chains are DONE: their failure is the run's real outcome).
        if any(getattr(nm, "failed_at", None) is None
               and nm.next < len(nm.moves)
               for nm in self._cursors.values()):
            return
        t_end = self._t_last_exec if self._t_last_exec is not None \
            else now
        actual = max(t_end - self._t0, 0.0)
        self._rec.set_gauge("sched.makespan_actual_s", actual)
        predicted = self._first_predicted or 0.0
        if actual > 0.0 and predicted > 0.0:
            self._rec.observe("sched.makespan_rel_err",
                              abs(predicted - actual) / actual)


class CriticalPathScheduler(SchedulerPolicy):
    """Critical-path move scheduling on calibrated costs (module doc).

    ``cost_model``: the :class:`~blance_tpu.obs.costmodel.CostModel`
    whose ``predict_move`` prices every move — pass the one you attach
    to the live Recorder (``rec.add_sink(model)``) so estimates
    recalibrate online across passes; by default a fresh model seeded
    with the committed per-op bench priors
    (``obs/costmodel_priors.json``), so even a never-observed cluster
    schedules on non-uniform costs.  ``device_threshold`` overrides
    when the rank sweep moves on-device (:mod:`.ranks`)."""

    name = "critical-path"

    def __init__(self, cost_model: Optional[CostModel] = None,
                 device_threshold: Optional[int] = None,
                 use_priors: bool = True) -> None:
        if cost_model is None:
            cost_model = CostModel()
            if use_priors:
                cost_model.seed_priors(default_op_priors())
        self.cost_model = cost_model
        self.device_threshold = device_threshold

    def bind(self, nodes: Sequence[str], cursors: Mapping[str, Any],
             max_concurrent: int, recorder: Recorder) -> BoundScheduler:
        return _CriticalPathBound(
            self.cost_model, nodes, cursors, max_concurrent, recorder,
            self.device_threshold)
