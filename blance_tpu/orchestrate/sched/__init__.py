"""blance_tpu.orchestrate.sched — critical-path move scheduling.

The orchestrator executes a flat per-partition move list; this package
decides the ORDER, turning the list into a scheduled execution plan
that minimizes rebalance makespan instead of leaving it to fall out of
per-node concurrency by accident (docs/SCHEDULER.md; arxiv 1711.01912
"it's the critical path!").

- :mod:`.dag` — the move-DAG builder: per-partition state-transition
  chains (never run the ``del`` before its ``add`` completed,
  promote-after-replica-build) plus per-node concurrency lanes
  (``max_concurrent_partition_moves_per_node`` as machine capacity).
- :mod:`.ranks` — upward-rank (critical-path) priorities over the
  leveled DAG: a jitted on-device scan for large move sets, a host
  fallback below the size threshold.
- :mod:`.policy` — the scheduler interface the orchestrator binds:
  :class:`LegacyWeightOrder` (the reference's app-weight order,
  extracted verbatim — the pinned default) and
  :class:`CriticalPathScheduler` (HEFT-style earliest-finish list
  scheduling on calibrated ``CostModel.predict_move`` costs, with
  online rescheduling when the health breaker quarantines a node).
"""

from .dag import DagMove, MoveDag, MoveDagError, build_move_dag
from .policy import (
    MOVE_OP_WEIGHT,
    BoundScheduler,
    CriticalPathScheduler,
    LegacyWeightOrder,
    ScheduledMove,
    SchedulePlan,
    SchedulerPolicy,
    list_schedule,
    lowest_weight_partition_move_for_node,
)
from .ranks import upward_ranks

__all__ = [
    "DagMove",
    "MoveDag",
    "MoveDagError",
    "build_move_dag",
    "MOVE_OP_WEIGHT",
    "BoundScheduler",
    "CriticalPathScheduler",
    "LegacyWeightOrder",
    "ScheduledMove",
    "SchedulePlan",
    "SchedulerPolicy",
    "list_schedule",
    "lowest_weight_partition_move_for_node",
    "upward_ranks",
]
