"""Deterministic fault injection for orchestrator chaos testing.

A :class:`FaultPlan` wraps the app's ``assign_partitions`` callback and
scripts failures, hangs, and flakes per ``(node, partition, attempt)``.
Decisions come from a SHA-256 hash of ``(seed, node, partition, attempt)``
— not ``random`` state and not Python's randomized ``hash()`` — so a
given seed produces the exact same fault schedule on every run, every
platform, and regardless of asyncio interleaving: the same (node,
partition) pair fails on the same attempt numbers no matter when the
orchestrator gets around to trying it.  That is what makes chaos
scenarios (flaky node at 30%, dead node, hung node) reproducible in
tier-1 CPU tests with no real hardware.

Hangs are virtual-time: a "hang" decision parks the callback on an event
that never fires, and the orchestrator's ``move_timeout_s`` deadline
(OrchestratorOptions) cancels it — so a test models a wedged node with a
10 ms timeout instead of a wall-clock sleep.

Typical use::

    plan = FaultPlan(seed=7, nodes={
        "flaky": NodeFaults(fail_rate=0.3),
        "dead":  NodeFaults(dead=True),
        "hung":  NodeFaults(dead=True, hang=True),
    })
    o = orchestrate_moves(model, ft_options, nodes, beg, end,
                          plan.wrap(assign))

``plan.injected`` / ``plan.events`` record exactly what was injected,
for assertions.
"""

from __future__ import annotations

import asyncio
import hashlib
import inspect
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["FaultInjected", "NodeFaults", "FaultPlan"]


class FaultInjected(Exception):
    """The scripted failure a FaultPlan raises in place of the callback."""

    def __init__(self, node: str, partitions: tuple[str, ...],
                 attempt: int) -> None:
        super().__init__(
            f"injected fault: node={node} partitions={list(partitions)} "
            f"attempt={attempt}")
        self.node = node
        self.partitions = partitions
        self.attempt = attempt


@dataclass(frozen=True)
class NodeFaults:
    """Fault profile for one node.

    fail_rate: per-(partition, attempt) probability of a fast failure.
    hang_rate: per-(partition, attempt) probability of a hang (needs
        ``move_timeout_s`` set, or the mover stalls like the reference).
    dead: every attempt faults (with ``hang`` choosing the flavor).
    hang: with ``dead``, hang instead of failing fast.
    heal_after: node-level attempt count after which the node behaves
        perfectly — models a node that recovers, exercising the breaker's
        half-open probe re-admission.
    """

    fail_rate: float = 0.0
    hang_rate: float = 0.0
    dead: bool = False
    hang: bool = False
    heal_after: Optional[int] = None


def _unit_interval(seed: int, node: str, partition: str, attempt: int) -> float:
    """Deterministic u in [0, 1) from a stable cryptographic hash."""
    digest = hashlib.sha256(
        f"{seed}:{node}:{partition}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2 ** 64


@dataclass
class FaultPlan:
    """Seeded, scripted chaos for an assign_partitions callback."""

    seed: int = 0
    nodes: dict[str, NodeFaults] = field(default_factory=dict)
    # bookkeeping (all deterministic given the schedule):
    attempts: dict[tuple[str, str], int] = field(default_factory=dict)
    node_attempts: dict[str, int] = field(default_factory=dict)
    injected: dict[str, int] = field(default_factory=dict)
    events: list[tuple[str, tuple[str, ...], str]] = \
        field(default_factory=list)

    def decide(self, node: str, partition: str, attempt: int) -> str:
        """Scripted outcome for one (node, partition, attempt): "ok",
        "fail", or "hang".  Pure given the plan's seed and profiles —
        callable from tests to predict the schedule."""
        nf = self.nodes.get(node)
        if nf is None:
            return "ok"
        if nf.heal_after is not None and \
                self.node_attempts.get(node, 0) >= nf.heal_after:
            return "ok"
        if nf.dead:
            return "hang" if nf.hang else "fail"
        u = _unit_interval(self.seed, node, partition, attempt)
        if u < nf.hang_rate:
            return "hang"
        if u < nf.hang_rate + nf.fail_rate:
            return "fail"
        return "ok"

    def _bump(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def wrap(self, assign: Callable[..., object]) -> Callable[..., object]:
        """Wrap a sync-or-async assign_partitions callback.  The wrapper
        consults the schedule per batch (a batch faults when ANY of its
        partitions' next attempts is scripted to fault — hang beats fail
        when both appear) and otherwise forwards to the app."""

        async def chaotic(stop_ch, node, partitions, states, ops):
            decision = "ok"
            batch_attempt = self.node_attempts.get(node, 0)
            for p in partitions:
                att = self.attempts.get((node, p), 0)
                d = self.decide(node, p, att)
                if d == "hang" or (d == "fail" and decision == "ok"):
                    decision = d
            for p in partitions:
                self.attempts[(node, p)] = self.attempts.get((node, p), 0) + 1
            self.node_attempts[node] = batch_attempt + 1
            self.events.append((node, tuple(partitions), decision))
            if decision == "hang":
                self._bump("hang")
                # Virtual hang: parks forever; move_timeout_s cancels it.
                await asyncio.Event().wait()
            if decision == "fail":
                self._bump("fail")
                raise FaultInjected(node, tuple(partitions), batch_attempt)
            self._bump("ok")
            result = assign(stop_ch, node, partitions, states, ops)
            if inspect.isawaitable(result):
                result = await result
            return result

        return chaotic
